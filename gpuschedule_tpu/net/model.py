"""The shared-fabric contention model: dynamic multislice speed factors.

This is the piece that turns :class:`~gpuschedule_tpu.cluster.tpu.
TpuCluster`'s *static* per-allocation ``speed_factor`` into a **dynamic**
one.  The static model assumes every DCN-spanning gang owns the whole
fabric; here, whenever the engine's running set changes (start / done /
preempt / migrate / revoke) or a link degrades (``("link", pod)`` faults),
:meth:`NetModel.recompute` re-derives every multislice job's effective
bandwidth by max-min fair sharing over the fabric graph and re-prices its
``locality_factor`` with the same analytic allreduce term the static
model uses — just fed the job's *actual* share instead of the nominal
:data:`~gpuschedule_tpu.cluster.tpu.DCN_GBPS`.

Demands, from the existing :mod:`gpuschedule_tpu.profiler.ici` model:

- each running **multislice** job contributes one elastic flow over the
  uplinks of the pods it spans plus the aggregation core (weighted by its
  pod count — see :meth:`FabricTopology.path`).  Its offered demand is
  one full uplink (``hosts_per_pod x dcn_gbps``): with every host NIC
  saturated the per-host share is the nominal ``DCN_GBPS``, which is
  exactly what the static model assumed — so an uncontended job on a
  non-blocking core reproduces the static factor bit-for-bit;
- each running job (any size) contributes **inelastic ingest** of
  ``ingest_gbps_per_chip`` per occupied chip on its pod's uplink — the
  input-pipeline traffic that makes residual-bandwidth placement scoring
  meaningful.  Ingest is subtracted from link capacity before the elastic
  flows are filled (it does not slow the ingesting job; docs/network.md
  records that omission).

The resulting per-host bandwidth ``share / hosts_per_pod`` feeds
``cross_pod_allreduce_seconds(..., dcn_gbps=share_per_host)`` and the
familiar ``t / (t + t_dcn)`` factor.  A fully degraded uplink gives a
factor of 0.0: the job *stalls* (holding its chips) until the link is
repaired — slowed, never killed.

Deterministic, pure Python, jax-free (sim-core rule).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from gpuschedule_tpu.net.fabric import CORE, FabricTopology, uplink
from gpuschedule_tpu.net.maxmin import Flow, maxmin_allocate


@dataclass
class NetConfig:
    """Knobs of the shared-fabric model.

    ``oversubscription`` is the core:uplink capacity ratio (1.0 =
    non-blocking, no contention between disjoint-pod jobs; 4.0 = the
    textbook 4:1 datacenter fabric).  ``ingest_gbps_per_chip`` is the
    inelastic input-pipeline draw per occupied chip (0 disables the
    ingest term entirely)."""

    oversubscription: float = 4.0
    ingest_gbps_per_chip: float = 0.05


_SPEC_KEYS = {
    "os": "oversubscription",
    "oversubscription": "oversubscription",
    "ingest": "ingest_gbps_per_chip",
}


def parse_net_spec(spec: str) -> NetConfig:
    """Parse the CLI's ``--net k=v,...`` spec.  Keys: ``os`` /
    ``oversubscription`` (core oversubscription ratio), ``ingest``
    (Gbps per occupied chip)."""
    config = NetConfig()
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, raw = pair.partition("=")
        key = key.strip().replace("-", "_")
        if not sep or key not in _SPEC_KEYS:
            raise ValueError(
                f"bad --net entry {pair!r}; known keys: {sorted(set(_SPEC_KEYS))}"
            )
        setattr(config, _SPEC_KEYS[key], float(raw))
    # range-check here, not deep inside FabricTopology at Simulator
    # construction: a bad spec must be a clean CLI error, not a traceback
    if not config.oversubscription > 0:
        raise ValueError(
            f"--net oversubscription must be > 0, got {config.oversubscription}"
        )
    if config.ingest_gbps_per_chip < 0:
        raise ValueError(
            f"--net ingest must be >= 0, got {config.ingest_gbps_per_chip}"
        )
    return config


@dataclass(frozen=True)
class JobShare:
    """One multislice job's allocation in the latest recompute."""

    gbps: float           # per-uplink injection rate granted (max-min fair)
    demand_gbps: float    # offered demand (one full uplink)
    factor: float         # the dynamic locality factor at this share
    pods: Tuple[int, ...]


@dataclass(frozen=True)
class LinkSample:
    """One link's load in the latest recompute (capacity is post-degrade)."""

    used_gbps: float
    capacity_gbps: float

    @property
    def util(self) -> float:
        if self.capacity_gbps <= 0.0:
            return 1.0 if self.used_gbps > 0.0 else 0.0
        return self.used_gbps / self.capacity_gbps


@dataclass
class NetState:
    """What one :meth:`NetModel.recompute` derived."""

    shares: Dict[str, JobShare] = field(default_factory=dict)
    links: Dict[str, LinkSample] = field(default_factory=dict)


class NetModel:
    """Engine-facing contention model over one fleet's shared fabric.

    The engine calls :meth:`attach` once, :meth:`recompute` after every
    event batch that may have changed the running set, and
    :meth:`degrade_link` / :meth:`repair_link` from ``("link", pod)``
    fault records.  Placement (the ``contention`` scheme) reads
    :meth:`residual_gbps` between recomputes.
    """

    def __init__(self, config: Optional[NetConfig] = None):
        self.config = config or NetConfig()
        self.topology: Optional[FabricTopology] = None
        self._cluster = None
        # active uplink degradations: pod -> list of residual-capacity
        # fractions (stacked outages multiply; repair pops one instance)
        self._degraded: Dict[int, List[float]] = {}
        # last recompute's elastic usage per link (residual_gbps reads it)
        self._elastic_used: Dict[str, float] = {}
        self.recomputes = 0
        # time-weighted utilization integrals (tools/net_sweep.py and the
        # compare-topology contention column read the means)
        self._last_t: Optional[float] = None
        self._last_util: Dict[str, float] = {}
        self._util_area: Dict[str, float] = {}
        self._horizon = 0.0

    # ------------------------------------------------------------------ #

    def attach(self, cluster) -> None:
        """Bind to a (possibly placement-wrapped) TpuCluster; idempotent —
        the engine and the CLI may both attach the same cluster."""
        inner = getattr(cluster, "inner", cluster)
        if self._cluster is inner:
            return
        self.topology = FabricTopology.from_cluster(
            inner, oversubscription=self.config.oversubscription
        )
        self._cluster = inner
        self._elastic_used = {}
        self._degraded = {}

    def _require_attached(self) -> FabricTopology:
        if self.topology is None:
            raise RuntimeError("NetModel.attach(cluster) must run first")
        return self.topology

    # ------------------------------------------------------------------ #
    # link health (the ("link", pod) fault scope, faults/)

    def degrade_link(self, pod: int, residual_frac: float) -> None:
        """One DCN-uplink outage: pod ``pod``'s uplink drops to
        ``residual_frac`` of its current capacity (0.0 = hard outage).
        Outages stack multiplicatively until each is repaired."""
        topo = self._require_attached()
        if not 0 <= pod < topo.num_pods:
            raise ValueError(f"link fault pod {pod} out of range")
        self._degraded.setdefault(pod, []).append(
            min(1.0, max(0.0, float(residual_frac)))
        )

    def repair_link(self, pod: int, residual_frac: float) -> None:
        """Undo one :meth:`degrade_link` of the same severity."""
        stack = self._degraded.get(pod)
        frac = min(1.0, max(0.0, float(residual_frac)))
        if not stack or frac not in stack:
            raise ValueError(f"repair of healthy link pod{pod}")
        stack.remove(frac)
        if not stack:
            del self._degraded[pod]

    def _capacity(self, link: str) -> float:
        """Current (post-degrade) capacity of one link."""
        topo = self._require_attached()
        cap = topo.links[link].capacity_gbps
        if link != CORE:
            pod = int(link.rsplit("pod", 1)[1])
            for frac in self._degraded.get(pod, ()):
                cap *= frac
        return cap

    # ------------------------------------------------------------------ #
    # demands

    def _multislice_pods(self, job) -> Optional[Tuple[int, ...]]:
        """The pods a running job's allocation spans, or None when it is
        not a DCN-spanning gang (single-pod slices produce no elastic
        flow).  Overlay guests with their own multislice detail count —
        they share the base's uplinks and must share its bandwidth."""
        alloc = getattr(job, "allocation", None)
        detail = getattr(alloc, "detail", None)
        slices = getattr(detail, "slices", None)
        if not slices:
            return None
        return tuple(sorted({s.pod for s in slices}))

    def _demand_gbps(self) -> float:
        """Offered demand of one multislice flow: one full uplink, i.e.
        per-host nominal DCN_GBPS across all the pod's host NICs — the
        bandwidth the static model silently assumed."""
        topo = self._require_attached()
        return topo.uplink_gbps

    def _grad_bytes(self, job) -> float:
        from gpuschedule_tpu.models.config import resolve_model_config
        from gpuschedule_tpu.profiler.ici import dp_gradient_bytes

        cfg = resolve_model_config(getattr(job, "model_name", None))
        tp = max(1, int(getattr(job, "tp", 1) or 1))
        return dp_gradient_bytes(cfg.param_count // tp)

    def _factor(self, job, m: int, per_host_gbps: float) -> float:
        """The dynamic locality factor: the static formula with the job's
        actual per-host share in place of the nominal DCN_GBPS."""
        from gpuschedule_tpu.profiler.ici import cross_pod_allreduce_seconds

        t_step = float(getattr(self._cluster, "dcn_step_seconds", 1.0))
        t_dcn = cross_pod_allreduce_seconds(
            self._grad_bytes(job), m, dcn_gbps=per_host_gbps
        )
        if math.isinf(t_dcn):
            return 0.0
        return t_step / (t_step + t_dcn)

    def _ingest_gbps(self, pod: int) -> float:
        """Inelastic input-pipeline draw on one pod's uplink, clamped to
        the link's (post-degrade) capacity."""
        rate = self.config.ingest_gbps_per_chip
        if rate <= 0.0 or self._cluster is None:
            return 0.0
        used = self._cluster.pod_used_chips(pod)
        return min(used * rate, self._capacity(uplink(pod)))

    # ------------------------------------------------------------------ #

    def recompute(self, now: float, running_jobs: Iterable) -> NetState:
        """Progressive-filling pass over the active flow set: derive every
        running multislice job's max-min fair share, its dynamic locality
        factor, and each link's load.  Deterministic — same running set,
        occupancy, and link health give identical floats."""
        topo = self._require_attached()
        self._integrate(now)
        self.recomputes += 1

        demand = self._demand_gbps()
        flows: List[Flow] = []
        meta: Dict[str, Tuple[int, ...]] = {}
        job_by_id: Dict[str, object] = {}
        for job in running_jobs:
            pods = self._multislice_pods(job)
            if pods is None:
                continue
            flows.append(Flow(job.job_id, topo.path(pods), demand))
            meta[job.job_id] = pods
            job_by_id[job.job_id] = job

        ingest = {p: self._ingest_gbps(p) for p in range(topo.num_pods)}
        capacity: Dict[str, float] = {}
        for name in topo.links:
            cap = self._capacity(name)
            if name == CORE:
                capacity[name] = max(0.0, cap - sum(ingest.values()))
            else:
                pod = int(name.rsplit("pod", 1)[1])
                capacity[name] = max(0.0, cap - ingest[pod])
        rates = maxmin_allocate(flows, capacity)

        state = NetState()
        elastic: Dict[str, float] = {name: 0.0 for name in topo.links}
        for flow in flows:
            r = rates[flow.key]
            pods = meta[flow.key]
            for link, w in flow.links:
                elastic[link] += w * r
            per_host = r / topo.hosts_per_pod
            job = job_by_id[flow.key]
            state.shares[flow.key] = JobShare(
                gbps=r,
                demand_gbps=demand,
                factor=self._factor(job, len(pods), per_host),
                pods=pods,
            )
        for name in sorted(topo.links):
            cap = self._capacity(name)
            if name == CORE:
                used = sum(ingest.values()) + elastic[name]
            else:
                pod = int(name.rsplit("pod", 1)[1])
                used = ingest[pod] + elastic[name]
            state.links[name] = LinkSample(used_gbps=used, capacity_gbps=cap)
        self._elastic_used = elastic
        self._last_util = {n: s.util for n, s in state.links.items()}
        return state

    def residual_gbps(self, pod: int) -> float:
        """Unallocated uplink bandwidth on pod ``pod`` right now: the
        (post-degrade) capacity minus live ingest minus the elastic load
        the last recompute granted — the contention placement scheme's
        scoring signal."""
        cap = self._capacity(uplink(pod))
        used = self._ingest_gbps(pod) + self._elastic_used.get(uplink(pod), 0.0)
        return max(0.0, cap - used)

    # ------------------------------------------------------------------ #
    # time-weighted link utilization (sweep / compare-topology reporting)

    def _integrate(self, now: float) -> None:
        if self._last_t is not None and now > self._last_t:
            dt = now - self._last_t
            self._horizon += dt
            for name, util in self._last_util.items():
                self._util_area[name] = self._util_area.get(name, 0.0) + util * dt
        self._last_t = now

    def close(self, now: float) -> None:
        """Close the utilization integrals at the end of a run."""
        self._integrate(now)

    def mean_utilization(self) -> Dict[str, float]:
        """Time-weighted mean utilization per link over the replay."""
        if self._horizon <= 0.0:
            return {}
        return {
            name: area / self._horizon
            for name, area in sorted(self._util_area.items())
        }
