"""The shared-fabric contention model: dynamic multislice speed factors.

This is the piece that turns :class:`~gpuschedule_tpu.cluster.tpu.
TpuCluster`'s *static* per-allocation ``speed_factor`` into a **dynamic**
one.  The static model assumes every DCN-spanning gang owns the whole
fabric; here, whenever the engine's running set changes (start / done /
preempt / migrate / revoke) or a link degrades (``("link", pod)`` faults),
:meth:`NetModel.recompute` re-derives every multislice job's effective
bandwidth by max-min fair sharing over the fabric graph and re-prices its
``locality_factor`` with the same analytic allreduce term the static
model uses — just fed the job's *actual* share instead of the nominal
:data:`~gpuschedule_tpu.cluster.tpu.DCN_GBPS`.

Demands, from the existing :mod:`gpuschedule_tpu.profiler.ici` model:

- each running **multislice** job contributes one elastic flow over the
  uplinks of the pods it spans plus the aggregation core (weighted by its
  pod count — see :meth:`FabricTopology.path`).  Its offered demand is
  one full uplink (``hosts_per_pod x dcn_gbps``): with every host NIC
  saturated the per-host share is the nominal ``DCN_GBPS``, which is
  exactly what the static model assumed — so an uncontended job on a
  non-blocking core reproduces the static factor bit-for-bit;
- each running job (any size) contributes **inelastic ingest** of
  ``ingest_gbps_per_chip`` per occupied chip on its pod's uplink — the
  input-pipeline traffic that makes residual-bandwidth placement scoring
  meaningful.  Ingest is subtracted from link capacity before the elastic
  flows are filled (it does not slow the ingesting job; docs/network.md
  records that omission).

The resulting per-host bandwidth ``share / hosts_per_pod`` feeds
``cross_pod_allreduce_seconds(..., dcn_gbps=share_per_host)`` and the
familiar ``t / (t + t_dcn)`` factor.  A fully degraded uplink gives a
factor of 0.0: the job *stalls* (holding its chips) until the link is
repaired — slowed, never killed.

Deterministic, pure Python, jax-free (sim-core rule).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from gpuschedule_tpu.models.config import resolve_model_config
from gpuschedule_tpu.net.fabric import CORE, FabricTopology
from gpuschedule_tpu.net.maxmin import (
    Flow,
    GroupCache,
    maxmin_allocate,
    maxmin_allocate_grouped,
)
from gpuschedule_tpu.profiler.ici import (
    cross_pod_allreduce_seconds,
    dp_gradient_bytes,
)


@dataclass
class NetConfig:
    """Knobs of the shared-fabric model.

    ``oversubscription`` is the core:uplink capacity ratio (1.0 =
    non-blocking, no contention between disjoint-pod jobs; 4.0 = the
    textbook 4:1 datacenter fabric).  ``ingest_gbps_per_chip`` is the
    inelastic input-pipeline draw per occupied chip (0 disables the
    ingest term entirely).  ``uplinks_per_pod`` (ISSUE 8) splits each
    pod's injection budget across that many redundant sibling uplinks —
    independent failure domains the model routes flows around when one
    degrades; 1 (the default) is the historical single-uplink fabric,
    byte-identical.  ``partial`` (ISSUE 9) arms the bottleneck-group
    max-min solve: flows decompose into connected components over
    contended links, each group solves independently, and a dirty set
    touching only some groups re-solves only those against cached group
    solutions (``net/maxmin.py:maxmin_allocate_grouped``).  Off (the
    default) keeps the flat progressive-filling pass — the historical
    float chunking, byte-identical to PR 7; the grouped arithmetic can
    differ from it in the last ulp across multiple groups, which is why
    the knob rides the config hash like every other ``--net`` key."""

    oversubscription: float = 4.0
    ingest_gbps_per_chip: float = 0.05
    uplinks_per_pod: int = 1
    partial: bool = False


_SPEC_KEYS = {
    "os": "oversubscription",
    "oversubscription": "oversubscription",
    "ingest": "ingest_gbps_per_chip",
    "uplinks": "uplinks_per_pod",
    "partial": "partial",
}


def parse_net_spec(spec: str) -> NetConfig:
    """Parse the CLI's ``--net k=v,...`` spec.  Keys: ``os`` /
    ``oversubscription`` (core oversubscription ratio), ``ingest``
    (Gbps per occupied chip), ``uplinks`` (redundant sibling uplinks
    per pod, 1-8; >1 arms adaptive routing), ``partial`` (0/1: arm the
    bottleneck-group partial max-min re-solve)."""
    config = NetConfig()
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, raw = pair.partition("=")
        key = key.strip().replace("-", "_")
        if not sep or key not in _SPEC_KEYS:
            raise ValueError(
                f"bad --net entry {pair!r}; known keys: {sorted(set(_SPEC_KEYS))}"
            )
        if key == "uplinks":
            v = float(raw)
            if v != int(v):
                # every other malformed --net value errors loudly; a
                # fractional sibling count must not silently truncate
                raise ValueError(
                    f"--net uplinks must be a whole number of sibling "
                    f"uplinks, got {raw.strip()}"
                )
            config.uplinks_per_pod = int(v)
        elif key == "partial":
            if raw.strip() not in ("0", "1"):
                raise ValueError(
                    f"--net partial must be 0 or 1, got {raw.strip()}"
                )
            config.partial = raw.strip() == "1"
        else:
            setattr(config, _SPEC_KEYS[key], float(raw))
    # range-check here, not deep inside FabricTopology at Simulator
    # construction: a bad spec must be a clean CLI error, not a traceback
    if not config.oversubscription > 0:
        raise ValueError(
            f"--net oversubscription must be > 0, got {config.oversubscription}"
        )
    if config.ingest_gbps_per_chip < 0:
        raise ValueError(
            f"--net ingest must be >= 0, got {config.ingest_gbps_per_chip}"
        )
    if not 1 <= config.uplinks_per_pod <= 8:
        raise ValueError(
            f"--net uplinks must be in [1, 8], got {config.uplinks_per_pod}"
        )
    return config


@dataclass(frozen=True)
class JobShare:
    """One multislice job's allocation in the latest recompute.

    ``route`` (ISSUE 8 adaptive routing) is the flow's weighted uplink
    set on a redundant-sibling fabric — the engine emits a ``reroute``
    event when it changes.  Always the empty tuple on a single-uplink
    fabric."""

    gbps: float           # per-pod injection rate granted (max-min fair)
    demand_gbps: float    # offered demand (one full pod uplink budget)
    factor: float         # the dynamic locality factor at this share
    pods: Tuple[int, ...]
    route: Tuple[Tuple[str, float], ...] = ()


@dataclass(frozen=True)
class LinkSample:
    """One link's load in the latest recompute (capacity is post-degrade)."""

    used_gbps: float
    capacity_gbps: float

    @property
    def util(self) -> float:
        if self.capacity_gbps <= 0.0:
            return 1.0 if self.used_gbps > 0.0 else 0.0
        return self.used_gbps / self.capacity_gbps


@dataclass
class NetState:
    """What one :meth:`NetModel.recompute` derived."""

    shares: Dict[str, JobShare] = field(default_factory=dict)
    links: Dict[str, LinkSample] = field(default_factory=dict)


class NetModel:
    """Engine-facing contention model over one fleet's shared fabric.

    The engine calls :meth:`attach` once, :meth:`mark_dirty` on every
    allocation mutation, :meth:`poll` / :meth:`recompute` after every
    event batch that may have changed the running set (poll returns the
    cached state when the dirty set is empty — the ISSUE 7 incremental
    fast path), and :meth:`degrade_link` / :meth:`repair_link` from
    ``("link", pod)`` fault records.  Placement (the ``contention``
    scheme) reads :meth:`residual_gbps` between recomputes.
    :meth:`recompute` alone is always a correct full pass — direct
    callers need no marking discipline.
    """

    def __init__(self, config: Optional[NetConfig] = None):
        self.config = config or NetConfig()
        self.topology: Optional[FabricTopology] = None
        self._cluster = None
        # active uplink degradations: link NAME -> list of residual-
        # capacity fractions (stacked outages multiply; repair pops one
        # instance).  On a redundant-sibling fabric each new outage lands
        # on the least-degraded sibling, spreading damage deterministically.
        self._degraded: Dict[str, List[float]] = {}
        # outage identity -> the sibling it landed on, so repair heals
        # exactly the right sibling under overlapping equal-severity
        # outages (the engine keys by fault-record identity)
        self._degrade_sites: Dict[object, str] = {}
        # cached per-pod route weights (routing fabrics): a pure function
        # of link health, invalidated by degrade/repair alongside the
        # flow cache so healthy-fabric recomputes skip the rebuild
        self._pod_routes: Optional[List[Tuple[Tuple[str, float], ...]]] = None
        # last recompute's elastic usage per link (residual_gbps reads it)
        self._elastic_used: Dict[str, float] = {}
        self.recomputes = 0
        # Incremental re-pricing (ISSUE 7 tentpole): the progressive-
        # filling pass is a pure function of (flow set, pod occupancy,
        # link health), so the engine marks this model dirty on every
        # mutation of those inputs (mark_dirty / degrade_link /
        # repair_link) and skips the whole pass via poll() when nothing
        # changed since the cached state was derived.  recompute() itself
        # is always a full pass — direct callers (tests, tools) need no
        # marking discipline to stay correct.
        self._dirty = True
        self._state = NetState()
        self.cache_hits = 0
        # flow-set cache (second dirty tier): the flow list only changes
        # when a *multislice* allocation is bound or released, which is
        # rare next to single-pod churn — occupancy-only invalidations
        # (the ingest term) reuse the cached flows and skip the whole
        # running-set scan.  Only the engine's reuse_flows=True path
        # consults it; direct recompute() callers always rebuild.
        self._flows_dirty = True
        self._flows: List[Flow] = []
        self._flow_meta: Dict[str, Tuple[int, ...]] = {}
        self._flow_jobs: Dict[str, object] = {}
        # flow-cache telemetry (ISSUE 10): reuses vs running-set rebuilds
        self.flow_reuses = 0
        self.flow_rebuilds = 0
        # Bottleneck-group partial re-solve (ISSUE 9): when the config
        # arms it, recompute() solves per connected component over
        # contended links and reuses cached group solutions whose inputs
        # are bitwise unchanged.  ``partial_cache`` (test hook) disables
        # only the reuse — every group solves fresh with the identical
        # grouped arithmetic, the byte-equivalence comparator.
        self._group_cache = GroupCache() if self.config.partial else None
        self.partial_cache = True
        # per-(model, tp) gradient payload cache: the resolved config and
        # payload never change for a given job, so the per-flow model
        # lookup happens once per distinct model instead of per recompute
        self._grad_bytes: Dict[Tuple[Optional[str], int], float] = {}
        # per-pods-tuple weighted link path (topo.path validates and
        # rebuilds the tuple on every call; flows reuse a handful of
        # distinct pod sets for the whole replay)
        self._paths: Dict[Tuple[int, ...], Tuple] = {}
        # attach()-time link metadata: sorted names and the name -> pod
        # parse, so recompute stops re-sorting and re-splitting per pass
        self._sorted_links: Tuple[str, ...] = ()
        self._uplinks: Tuple[str, ...] = ()
        self._link_pod: Dict[str, Optional[int]] = {}
        self._base_caps: Dict[str, float] = {}
        self._t_step = 1.0
        # time-weighted utilization integrals (tools/net_sweep.py and the
        # compare-topology contention column read the means)
        self._last_t: Optional[float] = None
        self._last_util: Dict[str, float] = {}
        self._util_area: Dict[str, float] = {}
        self._horizon = 0.0

    # ------------------------------------------------------------------ #

    # the snapshot contract's audit surface (ISSUE 13): every derived
    # cache listed here must be rebuilt/invalidated in restored() —
    # cross-checked statically by the contract linter (GS502,
    # docs/static-analysis.md)
    _DERIVED_CACHES = (
        "_dirty",
        "_flows_dirty",
        "_state",
        "_pod_routes",
        "_group_cache",
    )

    def restored(self) -> None:
        """Post-restore cache invalidation (engine snapshots, ISSUE 11):
        a deserialized model keeps its authoritative state — link degrade
        stacks, the elastic/ingest bookkeeping, the utilization-integral
        accumulators (whose exact values make a v1 resume's ``netlink``
        means byte-identical) — but every derived cache is marked for
        rebuild, so the first post-restore ``poll``/``recompute`` prices
        from scratch instead of trusting pre-snapshot flow lists, group
        solves, or route weights."""
        self._dirty = True
        self._flows_dirty = True
        self._state = NetState()
        self._pod_routes = None
        if self._group_cache is not None:
            self._group_cache = GroupCache()

    def attach(self, cluster) -> None:
        """Bind to a (possibly placement-wrapped) TpuCluster; idempotent —
        the engine and the CLI may both attach the same cluster."""
        inner = getattr(cluster, "inner", cluster)
        if self._cluster is inner:
            # same fleet, but drop the pricing cache: a NetModel reused
            # for a second Simulator over the same cluster must start
            # from a full recompute (pre-incremental semantics), not
            # serve the previous run's final state from poll().  The
            # group cache drops with it — a fresh run must not reuse the
            # previous run's group solves (same rule, same reason).
            self._dirty = True
            self._flows_dirty = True
            self._state = NetState()
            if self._group_cache is not None:
                self._group_cache = GroupCache()
            return
        self.topology = FabricTopology.from_cluster(
            inner,
            oversubscription=self.config.oversubscription,
            uplinks_per_pod=self.config.uplinks_per_pod,
        )
        self._cluster = inner
        self._elastic_used = {}
        self._degraded = {}
        self._degrade_sites = {}
        self._pod_routes = None
        self._dirty = True
        self._flows_dirty = True
        self._state = NetState()
        self._paths = {}
        topo = self.topology
        self._base_caps = {
            name: link.capacity_gbps for name, link in topo.links.items()
        }
        self._sorted_links = tuple(sorted(topo.links))
        # per-pod sibling uplink names (one historical name each on a
        # non-redundant fabric); _uplinks keeps the primary sibling for
        # the single-uplink fast paths
        self._pod_links = tuple(
            topo.pod_uplinks(p) for p in range(topo.num_pods)
        )
        self._uplinks = tuple(names[0] for names in self._pod_links)
        self._link_pod = {
            name: (
                None if name == CORE
                else int(name.rsplit("pod", 1)[1].split(".", 1)[0])
            )
            for name in topo.links
        }
        self._t_step = float(getattr(inner, "dcn_step_seconds", 1.0))

    def _require_attached(self) -> FabricTopology:
        if self.topology is None:
            raise RuntimeError("NetModel.attach(cluster) must run first")
        return self.topology

    # ------------------------------------------------------------------ #
    # link health (the ("link", pod) fault scope, faults/)

    @property
    def routing_enabled(self) -> bool:
        """True when the fabric has redundant sibling uplinks to route
        around (ISSUE 8); single-uplink fabrics keep every historical
        code path."""
        return self.topology is not None and self.topology.uplinks_per_pod > 1

    def degrade_link(self, pod: int, residual_frac: float, *, key=None) -> None:
        """One DCN-uplink outage: a sibling of pod ``pod``'s uplink set
        drops to ``residual_frac`` of its current capacity (0.0 = hard
        outage).  On a redundant fabric the outage lands on the sibling
        with the fewest active degradations (lowest index breaks ties),
        spreading damage deterministically; outages stack
        multiplicatively until each is repaired.

        ``key`` (the engine passes the fault record's identity) pins the
        chosen sibling so the matching :meth:`repair_link` heals exactly
        the sibling THIS outage degraded — overlapping outages of equal
        severity on different siblings would otherwise be un-pairable
        from the fraction alone."""
        topo = self._require_attached()
        if not 0 <= pod < topo.num_pods:
            raise ValueError(f"link fault pod {pod} out of range")
        name = min(
            self._pod_links[pod],
            key=lambda n: (len(self._degraded.get(n, ())), n),
        )
        frac = min(1.0, max(0.0, float(residual_frac)))
        self._degraded.setdefault(name, []).append(frac)
        if key is not None:
            self._degrade_sites[key] = name
        self._dirty = True
        if topo.uplinks_per_pod > 1:
            # route weights are part of the cached flow links: a health
            # change re-routes, so the flow cache must rebuild
            self._flows_dirty = True
            self._pod_routes = None

    def repair_link(self, pod: int, residual_frac: float, *, key=None) -> None:
        """Undo one :meth:`degrade_link` of the same severity — on the
        sibling its ``key`` recorded, falling back (keyless callers) to
        the first sibling in index order holding a matching
        degradation."""
        topo = self._require_attached()
        frac = min(1.0, max(0.0, float(residual_frac)))
        site = self._degrade_sites.pop(key, None) if key is not None else None
        names = (site,) if site is not None else self._pod_links[pod]
        for name in names:
            stack = self._degraded.get(name)
            if stack and frac in stack:
                stack.remove(frac)
                if not stack:
                    del self._degraded[name]
                self._dirty = True
                if topo.uplinks_per_pod > 1:
                    self._flows_dirty = True
                    self._pod_routes = None
                return
        raise ValueError(f"repair of healthy link pod{pod}")

    def _capacity(self, link: str) -> float:
        """Current (post-degrade) capacity of one link."""
        topo = self._require_attached()
        cap = topo.links[link].capacity_gbps
        if link != CORE:
            for frac in self._degraded.get(link, ()):
                cap *= frac
        return cap

    # ------------------------------------------------------------------ #
    # demands

    def _multislice_pods(self, job) -> Optional[Tuple[int, ...]]:
        """The pods a running job's allocation spans, or None when it is
        not a DCN-spanning gang (single-pod slices produce no elastic
        flow).  Overlay guests with their own multislice detail count —
        they share the base's uplinks and must share its bandwidth."""
        alloc = getattr(job, "allocation", None)
        detail = getattr(alloc, "detail", None)
        slices = getattr(detail, "slices", None)
        if not slices:
            return None
        return tuple(sorted({s.pod for s in slices}))

    def _demand_gbps(self) -> float:
        """Offered demand of one multislice flow: one full uplink, i.e.
        per-host nominal DCN_GBPS across all the pod's host NICs — the
        bandwidth the static model silently assumed."""
        topo = self._require_attached()
        return topo.uplink_gbps

    def _job_grad_bytes(self, job) -> float:
        """Gradient payload for one job's allreduce flow, cached per
        (model, tp): the resolved config never changes for a job, so the
        zoo lookup runs once per distinct model instead of per recompute
        (ISSUE 7 hot-path satellite)."""
        model = getattr(job, "model_name", None)
        tp = max(1, int(getattr(job, "tp", 1) or 1))
        key = (model, tp)
        cached = self._grad_bytes.get(key)
        if cached is None:
            cfg = resolve_model_config(model)
            cached = dp_gradient_bytes(cfg.param_count // tp)
            self._grad_bytes[key] = cached
        return cached

    def _factor(self, job, m: int, per_host_gbps: float) -> float:
        """The dynamic locality factor: the static formula with the job's
        actual per-host share in place of the nominal DCN_GBPS."""
        t_dcn = cross_pod_allreduce_seconds(
            self._job_grad_bytes(job), m, dcn_gbps=per_host_gbps
        )
        if math.isinf(t_dcn):
            return 0.0
        return self._t_step / (self._t_step + t_dcn)

    def _path(self, pods: Tuple[int, ...]):
        """Weighted link set for one (already sorted, de-duplicated) pods
        tuple, cached — topo.path re-validates and rebuilds per call."""
        path = self._paths.get(pods)
        if path is None:
            path = self._paths[pods] = self.topology.path(pods)
        return path

    def _ingest_gbps(self, pod: int) -> float:
        """Inelastic input-pipeline draw on one pod's uplink set, clamped
        to its total (post-degrade) capacity."""
        rate = self.config.ingest_gbps_per_chip
        if rate <= 0.0 or self._cluster is None:
            return 0.0
        used = self._cluster.pod_used_chips(pod)
        names = self._pod_links[pod]
        if len(names) == 1:
            cap = self._capacity(names[0])
        else:
            cap = sum(self._capacity(n) for n in names)
        return min(used * rate, cap)

    # ------------------------------------------------------------------ #
    # the dirty set (ISSUE 7 tentpole): what invalidates the cached state

    def mark_dirty(self, job=None) -> None:
        """Engine-facing: a scheduler-visible mutation touched this job's
        allocation (bind or imminent free).  Two invalidation tiers:

        - a **multislice** bind/release (the job is in the current flow
          set, or its attached allocation spans pods) invalidates the
          flow cache too — the next recompute rebuilds flows from the
          running set;
        - any other allocation change matters only through the ingest
          term: with ingest armed it invalidates the cached *state*
          (capacities moved) but the flow set is reused; with ingest off
          it provably cannot perturb the fabric and the cache survives.

        Call with the allocation still attached; ``job=None`` marks
        everything unconditionally."""
        if self._dirty and self._flows_dirty:
            return
        if (
            job is not None
            and job.job_id not in self._state.shares
            and self._multislice_pods(job) is None
        ):
            if self.config.ingest_gbps_per_chip > 0.0:
                self._dirty = True
            return
        self._dirty = True
        self._flows_dirty = True

    def poll(self, now: float) -> Optional[NetState]:
        """Engine fast path: the cached state when nothing marked the
        model dirty since it was derived, else None (run
        :meth:`recompute`).  Integrates the utilization means either way,
        at the same instants a full pass would — the integral's float
        chunking is part of the byte-identity contract."""
        if self._dirty:
            return None
        self._integrate(now)
        self.cache_hits += 1
        return self._state

    def recompute(
        self, now: float, running_jobs: Iterable, *, reuse_flows: bool = False
    ) -> NetState:
        """Progressive-filling pass over the active flow set: derive every
        running multislice job's max-min fair share, its dynamic locality
        factor, and each link's load.  Deterministic — same running set,
        occupancy, and link health give identical floats.

        ``reuse_flows`` is the engine's second-tier fast path: when the
        flow cache is clean (no multislice bind/release since the last
        rebuild — see :meth:`mark_dirty`), the flow list a running-set
        scan would produce is the cached one verbatim, so the scan is
        skipped and only capacities/rates/factors re-derive.  Direct
        callers keep the default (False): a full rebuild every time, no
        marking discipline required."""
        topo = self._require_attached()
        self._integrate(now)
        self.recomputes += 1

        # effective (post-degrade) capacities, one map per pass: the
        # degradation stack is almost always empty, so start from the
        # attach-time base capacities and only touch degraded uplinks
        # (same multiplication order as _capacity — identical floats).
        # Built before the flow set because adaptive routing derives its
        # per-pod route weights from them.
        link_pod = self._link_pod
        caps = dict(self._base_caps)
        for name, stack in self._degraded.items():
            cap = caps[name]
            for frac in stack:
                cap *= frac
            caps[name] = cap

        routing = topo.uplinks_per_pod > 1
        pod_routes = self._pod_routes
        if routing and pod_routes is None:
            # Adaptive route choice (ISSUE 8): each pod's injection
            # spreads across its sibling uplinks IN PROPORTION TO their
            # surviving capacity, so every loaded sibling saturates at
            # the same flow rate and the pod's effective uplink budget is
            # exactly the sum of surviving sibling capacities — a
            # degraded sibling sheds load onto the healthy ones (jobs
            # slow by the lost fraction instead of stalling), a dead one
            # leaves the route entirely.  All siblings dead falls back to
            # an even spread over zero-capacity links: the flow stalls.
            # Routes are a pure function of link health: cached until the
            # next degrade/repair invalidates them.
            pod_routes = []
            for names in self._pod_links:
                total = 0.0
                caps_p = []
                for n in names:
                    c = caps[n]
                    caps_p.append((n, c))
                    total += c
                if total > 0.0:
                    pod_routes.append(tuple(
                        (n, c / total) for n, c in caps_p if c > 0.0
                    ))
                else:
                    w = 1.0 / len(names)
                    pod_routes.append(tuple((n, w) for n in names))
            self._pod_routes = pod_routes

        demand = self._demand_gbps()
        reused = reuse_flows and not self._flows_dirty
        if reused:
            self.flow_reuses += 1
            flows = self._flows
            meta = self._flow_meta
            job_by_id = self._flow_jobs
        else:
            self.flow_rebuilds += 1
            flows = []
            meta = {}
            job_by_id = {}
            for job in running_jobs:
                pods = self._multislice_pods(job)
                if pods is None:
                    continue
                if routing:
                    links = tuple(
                        item for p in pods for item in pod_routes[p]
                    ) + ((CORE, float(len(pods))),)
                else:
                    links = self._path(pods)
                flows.append(Flow(job.job_id, links, demand))
                meta[job.job_id] = pods
                job_by_id[job.job_id] = job
            if reuse_flows:
                # only the engine's marked path caches the rebuild — a
                # direct caller's ad-hoc running list must never leak
                # into a later engine reuse.  (Route weights are part of
                # the links, which is why degrade/repair invalidate the
                # flow cache on a redundant fabric.)
                self._flows, self._flow_meta, self._flow_jobs = (
                    flows, meta, job_by_id
                )
                self._flows_dirty = False

        rate = self.config.ingest_gbps_per_chip
        ingest_link: Dict[str, float] = {}
        if rate > 0.0:
            cluster = self._cluster
            if routing:
                # ingest follows the same proportional spread as the
                # elastic routes, clamped to the pod's surviving total
                ingest = {}
                for p, names in enumerate(self._pod_links):
                    pod_cap = sum(caps[n] for n in names)
                    amt = min(cluster.pod_used_chips(p) * rate, pod_cap)
                    ingest[p] = amt
                    for n, w in pod_routes[p]:
                        ingest_link[n] = amt * w
                ingest_total = sum(ingest.values())
                capacity: Dict[str, float] = {}
                for name in topo.links:
                    cap = caps[name]
                    if name == CORE:
                        capacity[name] = max(0.0, cap - ingest_total)
                    else:
                        capacity[name] = max(
                            0.0, cap - ingest_link.get(name, 0.0)
                        )
            else:
                ingest = {
                    p: min(cluster.pod_used_chips(p) * rate, caps[up])
                    for p, up in enumerate(self._uplinks)
                }
                ingest_total = sum(ingest.values())
                capacity = {}
                for name in topo.links:
                    cap = caps[name]
                    if name == CORE:
                        capacity[name] = max(0.0, cap - ingest_total)
                    else:
                        capacity[name] = max(0.0, cap - ingest[link_pod[name]])
        else:
            ingest = dict.fromkeys(range(topo.num_pods), 0.0)
            ingest_total = 0.0
            capacity = {name: max(0.0, cap) for name, cap in caps.items()}
        # a reused flow list was validated when it was built; skip the
        # well-formedness sweep (keys/links/weights), not any arithmetic
        if self._group_cache is not None:
            # bottleneck-group solve (ISSUE 9): group reuse only through
            # the cache; partial_cache=False solves every group fresh
            # with identical arithmetic (the equivalence comparator).
            # top=CORE (ISSUE 12) arms the hierarchical tier: a contended
            # oversubscribed core no longer couples every flow into one
            # monolithic group — per-pod groups solve (and cache) beneath
            # it, with the core applied as an exact water-level clamp.
            rates = maxmin_allocate_grouped(
                flows, capacity,
                cache=self._group_cache if self.partial_cache else None,
                validate=not reused,
                top=CORE,
            )
        else:
            rates = maxmin_allocate(flows, capacity, validate=not reused)

        prev = self._state
        state = NetState()
        elastic: Dict[str, float] = dict.fromkeys(topo.links, 0.0)
        hosts_per_pod = topo.hosts_per_pod
        prev_shares = prev.shares
        for flow in flows:
            key = flow.key
            r = rates[key]
            for link, w in flow.links:
                elastic[link] += w * r
            share = prev_shares.get(key)
            route = flow.links[:-1] if routing else ()
            if share is None or share.gbps != r or share.pods != meta[key] or (
                routing and share.route != route
            ):
                # the factor is a pure function of (job model/tp, pod
                # set, share): an unchanged (rate, pods) pair reuses the
                # previous JobShare outright and skips the allreduce-term
                # call — same key with different pods (a rebind between
                # passes) re-derives.  A route change alone rebuilds too
                # (same factor, but the engine must see the new route to
                # emit its reroute event).
                pods = meta[key]
                share = JobShare(
                    gbps=r,
                    demand_gbps=demand,
                    factor=self._factor(
                        job_by_id[key], len(pods), r / hosts_per_pod
                    ),
                    pods=pods,
                    route=route,
                )
            state.shares[key] = share
        prev_links = prev.links
        for name in self._sorted_links:
            cap = caps[name]
            if name == CORE:
                used = ingest_total + elastic[name]
            elif routing:
                used = ingest_link.get(name, 0.0) + elastic[name]
            else:
                used = ingest[link_pod[name]] + elastic[name]
            sample = prev_links.get(name)
            if sample is None or (
                sample.used_gbps != used or sample.capacity_gbps != cap
            ):
                sample = LinkSample(used_gbps=used, capacity_gbps=cap)
            state.links[name] = sample
        self._elastic_used = elastic
        self._last_util = {n: s.util for n, s in state.links.items()}
        self._state = state
        self._dirty = False
        return state

    def cache_stats(self) -> Dict[str, Dict[str, int]]:
        """Unified cache telemetry (ISSUE 10): the incremental-pricing
        cache (poll hits vs full recomputes), the flow-list cache
        (reuses vs running-set rebuilds), and — when ``partial`` armed
        the bottleneck-group solver — group-solution reuses vs fresh
        group solves."""
        out = {
            "net_price": {"hit": self.cache_hits, "miss": self.recomputes},
            "net_flows": {
                "hit": self.flow_reuses, "miss": self.flow_rebuilds,
            },
        }
        if self._group_cache is not None:
            out["net_partial"] = {
                "hit": self._group_cache.reused,
                "miss": self._group_cache.solved,
            }
        return out

    @property
    def partial_solves(self) -> int:
        """Group re-solves avoided by the bottleneck-group cache (ISSUE 9
        non-vacuity signal): 0 whenever ``partial`` is off or nothing was
        ever reusable."""
        return self._group_cache.reused if self._group_cache is not None else 0

    def residual_gbps(self, pod: int) -> float:
        """Unallocated uplink bandwidth on pod ``pod`` right now: the
        (post-degrade) capacity minus live ingest minus the elastic load
        the last recompute granted — the contention placement scheme's
        scoring signal.  Summed across siblings on a redundant fabric."""
        names = self._pod_links[pod]
        if len(names) == 1:
            name = names[0]
            cap = self._capacity(name)
            used = self._ingest_gbps(pod) + self._elastic_used.get(name, 0.0)
            return max(0.0, cap - used)
        cap = sum(self._capacity(n) for n in names)
        used = self._ingest_gbps(pod) + sum(
            self._elastic_used.get(n, 0.0) for n in names
        )
        return max(0.0, cap - used)

    # ------------------------------------------------------------------ #
    # time-weighted link utilization (sweep / compare-topology reporting)

    def _integrate(self, now: float) -> None:
        if self._last_t is not None and now > self._last_t:
            dt = now - self._last_t
            self._horizon += dt
            for name, util in self._last_util.items():
                self._util_area[name] = self._util_area.get(name, 0.0) + util * dt
        self._last_t = now

    def close(self, now: float) -> None:
        """Close the utilization integrals at the end of a run."""
        self._integrate(now)

    def mean_utilization(self) -> Dict[str, float]:
        """Time-weighted mean utilization per link over the replay."""
        if self._horizon <= 0.0:
            return {}
        return {
            name: area / self._horizon
            for name, area in sorted(self._util_area.items())
        }
