"""Contention-vs-offered-load sweep: what does multislice share cost?

The question the shared-fabric model exists to answer at the grid level:
as the *multislice share* of the workload rises (the fraction of jobs
that span pods and therefore compete for the aggregation core), how fast
do aggregate goodput and the slowdown tail degrade, and which policies
degrade most gracefully?  Mirrors :mod:`gpuschedule_tpu.faults.sweep`
(the MTBF grid): one seeded Philly-like trace per cell, a deterministic
subset of jobs promoted to 2-pod multislice gangs, the same eight-policy
suite, one JSON-ready artifact.  ``tools/net_sweep.py`` is the CLI
wrapper; the functions are importable so the pytest smoke can run one
tiny cell end-to-end.
"""

from __future__ import annotations

import math
import random
from typing import Iterable, Optional, Sequence

from gpuschedule_tpu.cluster.tpu import TpuCluster
from gpuschedule_tpu.faults.sweep import POLICY_CONFIGS, jsonable  # noqa: F401
from gpuschedule_tpu.net.model import NetConfig, NetModel
from gpuschedule_tpu.obs.fleet import (
    task_profiler as _task_profiler,
    task_span as _task_span,
)
from gpuschedule_tpu.policies import make_policy
from gpuschedule_tpu.sim import Simulator
from gpuschedule_tpu.sim.metrics import MetricsLog
from gpuschedule_tpu.sim.philly import generate_philly_like_trace

# Default offered-load grid: the multislice share of the job mix.
DEFAULT_SHARES = (0.0, 0.05, 0.1, 0.2)


def promote_to_multislice(jobs, share: float, pod_chips: int, *, seed: int = 0):
    """Deterministically promote ``share`` of ``jobs`` to 2-pod multislice
    gangs (``2 * pod_chips`` chips, a gradient-heavy model so the DCN toll
    is visible).  Seeded independently of the trace stream (the same
    seed-split rule faults/ uses): the un-promoted jobs are byte-identical
    across shares, so cells differ only by the promotion itself."""
    k = round(share * len(jobs))
    if k <= 0:
        return jobs
    rng = random.Random(f"{seed}:net:share")
    for i in sorted(rng.sample(range(len(jobs)), k)):
        jobs[i].num_chips = 2 * pod_chips
        jobs[i].model_name = "transformer-base"
    return jobs


def run_cell(
    policy_key: str,
    *,
    multislice_share: float,
    num_jobs: int = 200,
    seed: int = 0,
    dims: Sequence[int] = (4, 4),
    num_pods: int = 4,
    oversubscription: float = 4.0,
    ingest: float = 0.05,
    max_time: Optional[float] = None,
    attribution: bool = False,
) -> dict:
    """One (policy, multislice-share) cell on a fresh cluster + trace +
    net model.  Deterministic per argument tuple.  ``attribution`` arms
    the causal layer (ISSUE 5): the cell then reports ``delay_by_cause``
    — in particular the ``net-degraded`` leg, the seconds the share's
    jobs lost to fabric contention rather than queueing."""
    if num_pods < 2:
        raise ValueError("the contention sweep needs num_pods >= 2")
    name, kwargs = POLICY_CONFIGS[policy_key]
    # ISSUE 16: same worker-side build/replay spans + per-cell engine
    # profiler as the MTBF grid; no-ops when no fleet harness is armed
    with _task_span("build", cat="sweep", policy=policy_key):
        cluster = TpuCluster("v5e", dims=tuple(dims), num_pods=num_pods)
        jobs = promote_to_multislice(
            generate_philly_like_trace(num_jobs, seed=seed),
            multislice_share, cluster.pod_chips, seed=seed,
        )
        net = NetModel(NetConfig(
            oversubscription=oversubscription, ingest_gbps_per_chip=ingest,
        ))
    metrics = MetricsLog(attribution=attribution) if attribution else None
    with _task_span("replay", cat="sweep", policy=policy_key,
                    share=multislice_share, seed=seed):
        res = Simulator(
            cluster, make_policy(name, **kwargs), jobs,
            metrics=metrics,
            net=net,
            max_time=max_time if max_time is not None else math.inf,
            profiler=_task_profiler(),
        ).run()
    cell_extra = (
        {"delay_by_cause": dict(res.delay_by_cause)}
        if res.delay_by_cause else {}
    )
    return {
        **cell_extra,
        "policy": policy_key,
        "multislice_share": multislice_share,
        "avg_jct": res.avg_jct,
        "p95_slowdown": res.p95_slowdown,
        "makespan": res.makespan,
        "num_finished": res.num_finished,
        "num_unfinished": res.num_unfinished,
        "net_reprices": int(res.counters.get("net_reprices", 0)),
        "goodput": dict(res.goodput),
        "mean_link_utilization": net.mean_utilization(),
    }


def _share_cell(key: str, share: float, cell_kwargs: dict) -> dict:
    """Module-level cell thunk (picklable for the process pool)."""
    return run_cell(key, multislice_share=share, **cell_kwargs)


def sweep(
    shares: Iterable[float] = DEFAULT_SHARES,
    policies: Optional[Iterable[str]] = None,
    *,
    workers: int = 1,
    fleet=None,
    **cell_kwargs,
) -> dict:
    """The full grid: ``{"multislice_share": [...], "policies": {name:
    [cell, ...]}}`` with each policy's cells ordered like the shares.

    ``workers`` > 1 fans the cells across a process pool (each cell is an
    isolated seeded replay — the faults/sweep.py grid_cells machinery);
    the reassembled artifact is byte-identical to the serial one.
    ``fleet`` arms ISSUE 16 cross-process tracing (see
    :func:`gpuschedule_tpu.faults.sweep.grid_cells`)."""
    shares = list(shares)
    keys = list(policies) if policies is not None else list(POLICY_CONFIGS)
    unknown = [k for k in keys if k not in POLICY_CONFIGS]
    if unknown:
        raise ValueError(
            f"unknown policy configs {unknown}; known: {sorted(POLICY_CONFIGS)}"
        )
    from functools import partial

    from gpuschedule_tpu.faults.sweep import grid_cells

    out = grid_cells(
        keys, shares, partial(_share_cell, cell_kwargs=cell_kwargs),
        workers=workers, fleet=fleet,
    )
    return {"multislice_share": shares, "policies": out}
