"""Shared-fabric network contention model (ISSUE 4 tentpole).

The simulator's multislice speed model priced every DCN-spanning gang in
isolation; this package models the *shared* fabric so contention becomes
a scheduling signal (the axis TopoOpt and Blink show changes placement
decisions at production scale):

- :mod:`gpuschedule_tpu.net.fabric` — the capacitated topology graph:
  per-pod DCN uplinks (``hosts x DCN_GBPS``) feeding one aggregation
  core (``sum(uplinks) / oversubscription``);
- :mod:`gpuschedule_tpu.net.maxmin` — the deterministic max-min fair
  allocator (progressive filling over the active flow set);
- :mod:`gpuschedule_tpu.net.model` — ``NetModel``: per-job demands from
  the :mod:`~gpuschedule_tpu.profiler.ici` analytic allreduce model,
  dynamic ``locality_factor`` re-pricing on every running-set change,
  ``("link", pod)`` fault degradation, residual-bandwidth scoring
  for the ``contention`` placement scheme, and — with redundant sibling
  uplinks (``uplinks_per_pod > 1``, ISSUE 8) — proportional-multipath
  adaptive routing around degraded links (``reroute`` events);
- :mod:`gpuschedule_tpu.net.sweep` — the contention-vs-offered-load grid
  behind ``tools/net_sweep.py``.

Engine integration lives in :mod:`gpuschedule_tpu.sim.engine`
(``Simulator(net=...)``, the ``net`` / ``netlink`` event kinds); the
observability side is in :mod:`gpuschedule_tpu.obs` (link-utilization
gauges, per-link Perfetto tracks, the analyzer's network panel).  Like
the sim core, this package is deliberately jax-free.
"""

from gpuschedule_tpu.net.fabric import (
    CORE,
    FabricTopology,
    Link,
    sibling_uplink,
    uplink,
)
from gpuschedule_tpu.net.maxmin import Flow, maxmin_allocate
from gpuschedule_tpu.net.model import (
    JobShare,
    LinkSample,
    NetConfig,
    NetModel,
    NetState,
    parse_net_spec,
)

__all__ = [
    "CORE",
    "FabricTopology",
    "Link",
    "sibling_uplink",
    "uplink",
    "Flow",
    "maxmin_allocate",
    "JobShare",
    "LinkSample",
    "NetConfig",
    "NetModel",
    "NetState",
    "parse_net_spec",
]
