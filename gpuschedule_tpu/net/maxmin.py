"""Max-min fair bandwidth allocation by progressive filling.

The contention solver at the heart of :mod:`gpuschedule_tpu.net`: given a
set of flows (each loading a weighted set of links, each with a finite
offered demand) and per-link capacities, find the max-min fair rate
vector — the classic water-filling construction (Bertsekas & Gallager):
every unfrozen flow's rate rises at the same pace; a flow freezes when it
reaches its demand or when any link it loads saturates.  The result is
the unique allocation in which no flow's rate can be raised without
lowering that of another flow with an equal-or-smaller rate.

Weighted link loading: a flow ``f`` at rate ``r`` consumes
``w * r`` of each link it crosses with weight ``w`` (the fabric uses this
for the aggregation core, which carries every pod's injection of the same
allreduce).

Deterministic and pure Python (sim-core rule): flows are processed in
sorted-key order, arithmetic is plain floats, and two calls with the same
inputs return identical rates regardless of input ordering.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Tuple

# Relative freeze tolerance: a link whose remaining capacity is below
# _EPS x its original capacity is saturated; a flow within _EPS x demand
# of its demand is satisfied.  Floats only ever accumulate a handful of
# operations here, so 1e-9 is comfortably past any rounding residue.
_EPS = 1e-9


@dataclass(frozen=True)
class Flow:
    """One elastic demand: ``links`` are ``(name, weight)`` pairs."""

    key: str
    links: Tuple[Tuple[str, float], ...]
    demand: float


def maxmin_allocate(
    flows: Iterable[Flow],
    capacity_gbps: Dict[str, float],
    *,
    validate: bool = True,
) -> Dict[str, float]:
    """Max-min fair rates for ``flows`` under ``capacity_gbps``.

    Every flow's links must exist in ``capacity_gbps``; capacities may be
    zero (flows crossing a dead link get rate 0).  Returns ``{flow.key:
    rate}`` for every input flow.

    ``validate=False`` skips the well-formedness sweep (duplicate keys,
    unknown links, non-positive weights) for callers that construct the
    flow set themselves and re-solve it repeatedly (the contention
    model's hot path, ISSUE 7); the arithmetic is identical either way.
    """
    flows = sorted(flows, key=lambda f: f.key)
    if validate:
        if len({f.key for f in flows}) != len(flows):
            raise ValueError("duplicate flow keys")
        for f in flows:
            for link, w in f.links:
                if link not in capacity_gbps:
                    raise ValueError(
                        f"flow {f.key!r} crosses unknown link {link!r}")
                if w <= 0:
                    raise ValueError(
                        f"flow {f.key!r} has non-positive weight on {link!r}")
    rate: Dict[str, float] = {f.key: 0.0 for f in flows}
    headroom = {k: max(0.0, float(v)) for k, v in capacity_gbps.items()}
    sat_floor = {k: _EPS * (1.0 + headroom[k]) for k in headroom}
    active: Dict[str, Flow] = {
        f.key: f for f in flows if f.demand > 0.0 and f.links
    }

    while active:
        # weight of the active flow set on each loaded link
        wsum: Dict[str, float] = {}
        for f in active.values():
            for link, w in f.links:
                wsum[link] = wsum.get(link, 0.0) + w
        # the common rate increment: the first link to saturate or the
        # first demand to be met, whichever is nearer
        inc = min(headroom[link] / ws for link, ws in wsum.items())
        inc = min(inc, min(f.demand - rate[f.key] for f in active.values()))
        if inc > 0.0:
            for f in active.values():
                rate[f.key] += inc
                for link, w in f.links:
                    headroom[link] = max(0.0, headroom[link] - w * inc)
        saturated = {link for link in wsum if headroom[link] <= sat_floor[link]}
        frozen = [
            k for k, f in active.items()
            if rate[k] >= f.demand * (1.0 - _EPS)
            or any(link in saturated for link, _ in f.links)
        ]
        if not frozen:
            # unreachable for well-formed inputs (inc > 0 always saturates
            # a link or meets a demand); belt-and-braces against float
            # pathology so the solver can never spin
            break
        for k in frozen:
            del active[k]
    return rate
