"""Max-min fair bandwidth allocation by progressive filling.

The contention solver at the heart of :mod:`gpuschedule_tpu.net`: given a
set of flows (each loading a weighted set of links, each with a finite
offered demand) and per-link capacities, find the max-min fair rate
vector — the classic water-filling construction (Bertsekas & Gallager):
every unfrozen flow's rate rises at the same pace; a flow freezes when it
reaches its demand or when any link it loads saturates.  The result is
the unique allocation in which no flow's rate can be raised without
lowering that of another flow with an equal-or-smaller rate.

Weighted link loading: a flow ``f`` at rate ``r`` consumes
``w * r`` of each link it crosses with weight ``w`` (the fabric uses this
for the aggregation core, which carries every pod's injection of the same
allreduce).

Deterministic and pure Python (sim-core rule): flows are processed in
sorted-key order, arithmetic is plain floats, and two calls with the same
inputs return identical rates regardless of input ordering.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

# Relative freeze tolerance: a link whose remaining capacity is below
# _EPS x its original capacity is saturated; a flow within _EPS x demand
# of its demand is satisfied.  Floats only ever accumulate a handful of
# operations here, so 1e-9 is comfortably past any rounding residue.
_EPS = 1e-9


@dataclass(frozen=True)
class Flow:
    """One elastic demand: ``links`` are ``(name, weight)`` pairs."""

    key: str
    links: Tuple[Tuple[str, float], ...]
    demand: float


def _validate_flows(flows, capacity_gbps) -> None:
    if len({f.key for f in flows}) != len(flows):
        raise ValueError("duplicate flow keys")
    for f in flows:
        for link, w in f.links:
            if link not in capacity_gbps:
                raise ValueError(
                    f"flow {f.key!r} crosses unknown link {link!r}")
            if w <= 0:
                raise ValueError(
                    f"flow {f.key!r} has non-positive weight on {link!r}")


def _progressive_fill(
    active: Dict[str, Flow],
    rate: Dict[str, float],
    headroom: Dict[str, float],
    sat_floor: Dict[str, float],
) -> None:
    """The water-filling loop itself, mutating ``rate``/``headroom`` for
    ``active`` — shared VERBATIM by the flat solver and each bottleneck
    group's solve (:func:`maxmin_allocate_grouped`), so a one-group
    decomposition reproduces the flat arithmetic bit for bit."""
    while active:
        # weight of the active flow set on each loaded link
        wsum: Dict[str, float] = {}
        for f in active.values():
            for link, w in f.links:
                wsum[link] = wsum.get(link, 0.0) + w
        # the common rate increment: the first link to saturate or the
        # first demand to be met, whichever is nearer
        inc = min(headroom[link] / ws for link, ws in wsum.items())
        inc = min(inc, min(f.demand - rate[f.key] for f in active.values()))
        if inc > 0.0:
            for f in active.values():
                rate[f.key] += inc
                for link, w in f.links:
                    headroom[link] = max(0.0, headroom[link] - w * inc)
        saturated = {link for link in wsum if headroom[link] <= sat_floor[link]}
        frozen = [
            k for k, f in active.items()
            if rate[k] >= f.demand * (1.0 - _EPS)
            or any(link in saturated for link, _ in f.links)
        ]
        if not frozen:
            # unreachable for well-formed inputs (inc > 0 always saturates
            # a link or meets a demand); belt-and-braces against float
            # pathology so the solver can never spin
            break
        for k in frozen:
            del active[k]


def maxmin_allocate(
    flows: Iterable[Flow],
    capacity_gbps: Dict[str, float],
    *,
    validate: bool = True,
) -> Dict[str, float]:
    """Max-min fair rates for ``flows`` under ``capacity_gbps``.

    Every flow's links must exist in ``capacity_gbps``; capacities may be
    zero (flows crossing a dead link get rate 0).  Returns ``{flow.key:
    rate}`` for every input flow.

    ``validate=False`` skips the well-formedness sweep (duplicate keys,
    unknown links, non-positive weights) for callers that construct the
    flow set themselves and re-solve it repeatedly (the contention
    model's hot path, ISSUE 7); the arithmetic is identical either way.
    """
    flows = sorted(flows, key=lambda f: f.key)
    if validate:
        _validate_flows(flows, capacity_gbps)
    rate: Dict[str, float] = {f.key: 0.0 for f in flows}
    headroom = {k: max(0.0, float(v)) for k, v in capacity_gbps.items()}
    sat_floor = {k: _EPS * (1.0 + headroom[k]) for k in headroom}
    active: Dict[str, Flow] = {
        f.key: f for f in flows if f.demand > 0.0 and f.links
    }
    _progressive_fill(active, rate, headroom, sat_floor)
    return rate


# --------------------------------------------------------------------- #
# Bottleneck-group decomposition (ISSUE 9 partial re-solve)


@dataclass(frozen=True)
class GroupSolve:
    """One bottleneck group's cached solution: the exact inputs (member
    flows in key order, every loaded link's capacity) and the rates the
    fill derived from them.  Rates may be reused only when BOTH input
    tuples compare equal — bitwise-identical inputs into a deterministic
    pure solve give bitwise-identical outputs, which is the whole
    byte-identity argument."""

    flows: Tuple[Flow, ...]
    caps: Tuple[Tuple[str, float], ...]
    rates: Dict[str, float]


class GroupCache:
    """Across-recompute store of per-group solutions plus the reuse
    counters (``reused`` is the contention model's ``partial_solves``
    non-vacuity signal)."""

    def __init__(self) -> None:
        self.groups: Dict[Tuple[str, ...], GroupSolve] = {}
        self.reused = 0
        self.solved = 0


def _components(
    active: List[Flow], contended: set
) -> Tuple[Dict[int, List[Flow]], List[Flow]]:
    """Connected components of ``active`` over shared ``contended`` links
    (union-find), plus the *free* flows — those loading no contended link
    at all, which the fill would raise straight to their demand.  Both
    outputs are in deterministic key order (``active`` is pre-sorted)."""
    parent = list(range(len(active)))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    anchor: Dict[str, int] = {}
    for i, f in enumerate(active):
        for link, w in f.links:
            if link in contended:
                j = anchor.setdefault(link, i)
                if j != i:
                    ri, rj = find(i), find(j)
                    if ri != rj:
                        parent[ri] = rj

    comps: Dict[int, List[Flow]] = {}
    free: List[Flow] = []
    for i, f in enumerate(active):
        if not any(link in contended for link, _ in f.links):
            free.append(f)
            continue
        comps.setdefault(find(i), []).append(f)
    return comps, free


def _solve_groups(
    comps: Dict[int, List[Flow]],
    capacity_gbps: Dict[str, float],
    rate: Dict[str, float],
    cache: Optional[GroupCache],
    *,
    exclude: Optional[str] = None,
) -> None:
    """Solve each component with the verbatim fill over its member flows
    and every link they load (slack ones included, at full capacity —
    they never bind, but keeping them preserves the flat loop's shape),
    reusing any cached :class:`GroupSolve` whose inputs are bitwise
    unchanged.  ``exclude`` drops one link from every solve — the
    hierarchical path's top tier, which is applied afterwards as a
    water-level clamp instead of riding each group's fill."""
    new_groups: Dict[Tuple[str, ...], GroupSolve] = {}
    for members in comps.values():
        key = tuple(f.key for f in members)   # members are in key order
        links = sorted({
            link for f in members for link, _ in f.links if link != exclude
        })
        caps = tuple((link, float(capacity_gbps[link])) for link in links)
        flows_t = tuple(members)
        hit = cache.groups.get(key) if cache is not None else None
        if hit is not None and hit.flows == flows_t and hit.caps == caps:
            rate.update(hit.rates)
            solve = hit
            cache.reused += 1
        else:
            grate = {f.key: 0.0 for f in members}
            headroom = {link: max(0.0, c) for link, c in caps}
            sat_floor = {
                link: _EPS * (1.0 + headroom[link]) for link in headroom
            }
            if exclude is None:
                fill_members = {f.key: f for f in members}
            else:
                fill_members = {
                    f.key: Flow(
                        f.key,
                        tuple(
                            (link, w) for link, w in f.links
                            if link != exclude
                        ),
                        f.demand,
                    )
                    for f in members
                }
            _progressive_fill(fill_members, grate, headroom, sat_floor)
            rate.update(grate)
            solve = GroupSolve(flows_t, caps, grate)
            if cache is not None:
                cache.solved += 1
        if cache is not None:
            new_groups[key] = solve
    if cache is not None:
        # only current components stay cached: a group that dissolved
        # (membership changed) can never be reused under the bitwise
        # signature anyway
        cache.groups = new_groups


def maxmin_allocate_grouped(
    flows: Iterable[Flow],
    capacity_gbps: Dict[str, float],
    *,
    cache: Optional[GroupCache] = None,
    validate: bool = True,
    top: Optional[str] = None,
) -> Dict[str, float]:
    """Max-min fair rates by **bottleneck-group decomposition** — the
    ISSUE 9 partial re-solve, extended with the ISSUE 12 **hierarchical
    top tier**.

    Links that cannot bind — offered load comfortably below capacity, so
    progressive filling could never saturate them — are *slack*; flows
    couple only through the **contended** links (load within the
    saturation tolerance of capacity).  Connected components over shared
    contended links solve independently: each group runs the verbatim
    :func:`_progressive_fill` loop over its member flows and every link
    they load (slack ones included, at full capacity — they never bind,
    but keeping them preserves the flat loop's shape), and a flow none of
    whose links are contended takes its full demand outright.

    ``top`` names the fabric's single globally-shared link (the
    oversubscribed aggregation core).  Without it, a contended core
    couples every flow into one monolithic component and the
    decomposition gets nothing — the carried PR-9 omission.  With it,
    when the top link is contended the solve goes **hierarchical**:

    1. components form over the contended links *beneath* the top tier
       (per-pod uplink groups), each solved locally with the top link
       removed — progressive filling's dynamics cannot feel a constraint
       until it saturates, so below the core's waterline the local
       trajectories ARE the global ones;
    2. each local solve's final rates are the flows' *freeze levels*
       ``mu``; the core then binds every flow still active at its
       waterline ``lam`` — the unique level where
       ``sum(w_top * min(mu, lam)) == top capacity`` — and the global
       max-min rates are exactly ``min(mu, lam)`` in real arithmetic;
    3. the per-group local solves cache and reuse like any other group
       (a single-pod dirty set re-solves only that pod's group; the core
       clamp itself is a cheap exact re-derivation every pass).

    When the top tier never binds (slack by the 2x offered-load margin),
    when some active flow does not cross the top link (the clamp is only
    exact under the fabric invariant that ALL traffic transits the
    core), or when one local component spans every active flow anyway
    (nothing to decompose), the solve falls back to the non-hierarchical
    path —
    so slack-core fabrics and single-pod worlds keep their historical
    grouped arithmetic bit for bit, including the "one group spanning
    every flow reproduces the flat loop exactly" property.

    With a :class:`GroupCache`, a group whose inputs (member flows and
    all loaded-link capacities; the top link's capacity excluded for
    hierarchical groups — ingest churn moves it every pass) are bitwise
    unchanged since its last solve reuses the cached rates — the
    deterministic pure fill would redo identical arithmetic — so a dirty
    set touching one group re-solves only that group.  ``cache=None``
    solves every group fresh: the equivalence comparator, byte-identical
    by construction.

    The decomposition equals the flat solver exactly in real arithmetic
    (the hierarchical clamp to saturation-tolerance level, since the
    flat loop freezes the core within ``_EPS`` of capacity while the
    waterline is exact); across multiple groups the flat solver's global
    increment chunking re-associates float sums, so rates may differ in
    the last ulp — which is why the grouped arithmetic is an opt-in
    (``NetConfig.partial``) and the flat pass remains the no-flag
    fallback and oracle."""
    flows = sorted(flows, key=lambda f: f.key)
    if validate:
        _validate_flows(flows, capacity_gbps)
    rate: Dict[str, float] = {f.key: 0.0 for f in flows}
    active = [f for f in flows if f.demand > 0.0 and f.links]

    # per-link weighted offered load; a link is contended unless granting
    # every crossing flow its full demand leaves headroom comfortably
    # above the saturation floor (2x margin keeps borderline links in the
    # coupled set, so tolerance-level saturation can never differ between
    # a group solve and the flat loop)
    load: Dict[str, float] = {}
    for f in active:
        for link, w in f.links:
            load[link] = load.get(link, 0.0) + w * f.demand
    contended = set()
    for link, ld in load.items():
        cap = max(0.0, float(capacity_gbps[link]))
        if cap - ld < 2.0 * _EPS * (1.0 + cap):
            contended.add(link)

    # the hierarchical tier is exact only when EVERY active flow crosses
    # the top link (the fabric model's invariant: all traffic transits
    # the core).  A flow bypassing a contended top while sharing a
    # contended local link with a core-clamped flow could, in the flat
    # loop, keep filling the capacity the clamp freed — the water-level
    # clamp can only lower rates, never redistribute — so such instances
    # take the non-hierarchical path, which has no exactness caveat.
    if (
        top is not None
        and top in contended
        and all(any(link == top for link, _ in f.links) for f in active)
    ):
        local = contended - {top}
        comps, free = _components(active, local)
        if free or len(comps) > 1:
            # hierarchical: local solves beneath the top tier, then the
            # top tier's exact water-level clamp
            mu: Dict[str, float] = {}
            for f in free:
                mu[f.key] = f.demand
            _solve_groups(comps, capacity_gbps, mu, cache, exclude=top)
            return _clamp_to_top(active, mu, capacity_gbps, top, rate)
        # one component spans every active flow: nothing decomposes —
        # fall through to the non-hierarchical path, whose single group
        # (coupled via the contended top) IS the flat loop bit for bit

    comps, free = _components(active, contended)
    for f in free:
        # every link this flow loads can carry the whole offered load:
        # the fill would raise it straight to its demand
        rate[f.key] = f.demand
    _solve_groups(comps, capacity_gbps, rate, cache)
    return rate


def _clamp_to_top(
    active: List[Flow],
    mu: Dict[str, float],
    capacity_gbps: Dict[str, float],
    top: str,
    rate: Dict[str, float],
) -> Dict[str, float]:
    """Apply the top tier as a water-level clamp over the local freeze
    levels ``mu``: find the unique ``lam`` where the top link's consumed
    capacity ``sum(w * min(mu_f, lam))`` meets its capacity, and clamp
    every top-crossing flow there.  Flows not crossing the top link (and
    every flow, when the offered ``mu`` load fits outright) keep their
    local levels.  Deterministic: flows walk in ascending
    ``(mu, key)`` order, so every float sum has one canonical chunking —
    what makes cache-on and cache-off solves byte-identical."""
    weight: Dict[str, float] = {}
    for f in active:
        w = 0.0
        for link, lw in f.links:
            if link == top:
                w += lw
        weight[f.key] = w
    order = sorted((mu[f.key], f.key) for f in active if weight[f.key] > 0.0)
    top_cap = max(0.0, float(capacity_gbps[top]))
    total = 0.0
    wsum = 0.0
    for m, k in order:
        total += weight[k] * m
        wsum += weight[k]
    if total <= top_cap:
        # the top tier never binds at these freeze levels
        for f in active:
            rate[f.key] = mu[f.key]
        return rate
    below = 0.0
    wrem = wsum
    lam = order[-1][0] if order else 0.0
    for m, k in order:
        if below + m * wrem >= top_cap:
            lam = (top_cap - below) / wrem
            break
        below += weight[k] * m
        wrem -= weight[k]
    lam = max(0.0, lam)
    for f in active:
        k = f.key
        m = mu[k]
        rate[k] = min(m, lam) if weight[k] > 0.0 else m
    return rate
