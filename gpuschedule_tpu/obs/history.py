"""Cross-run history store: an append-only sqlite ledger of run results
(ISSUE 10 tentpole, retiring the PR-3 "no trend-over-history view across
more than one compare invocation" omission).

Every analytics surface so far is *within-invocation*: ``run`` prints one
summary, ``compare`` diffs the streams it was handed, ``engine_bench``
prints one ladder — and the next invocation starts blind.  This store
gives results a memory: ``run --history PATH``, ``compare --history
PATH`` and ``tools/engine_bench.py --history PATH`` append each
invocation's summary (keyed by ``run_id`` / ``config_hash`` / a bench
``label``), and the ``history`` CLI subcommand renders per-metric
trajectories across invocations — the substrate the ROADMAP's TopoOpt
compare-matrix search loop needs (accumulate topology x policy cells
across sessions, then ask "what fabric should we buy?").

Properties:

- **append-only**: rows are never updated or deleted; ``seq`` (the sqlite
  rowid) is the invocation order;
- **deterministic reads**: ``trend``/``rows`` are pure functions of the
  store's contents — two CLI invocations over the same file render the
  same table (the insertion timestamp is stored but never breaks a tie;
  ``seq`` already totally orders rows);
- **schema-stable JSON payload**: arbitrary summary dicts ride a single
  ``metrics`` JSON column, so new summary keys never need a migration;
- pure stdlib, no sim imports (the obs-layer rule).
"""

from __future__ import annotations

import json
import math
import sqlite3
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence


@dataclass
class HistoryRow:
    """One appended invocation result."""

    seq: int
    ts: float
    kind: str            # "run" | "compare" | "bench" | caller-defined
    run_id: str
    config_hash: str
    policy: str
    seed: Optional[int]
    label: str           # free-form sub-key (bench: "plain/1000")
    metrics: Dict[str, object] = field(default_factory=dict)

    def metric(self, name: str) -> Optional[float]:
        v = self.metrics.get(name)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)


class HistoryStore:
    """The sqlite-backed ledger.  Safe to open concurrently for appends
    (sqlite serializes writers); a missing file is created with the
    schema on first open."""

    def __init__(self, path):
        self.path = Path(path)
        if self.path.parent and not self.path.parent.exists():
            self.path.parent.mkdir(parents=True, exist_ok=True)
        self._db = sqlite3.connect(str(self.path))
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS runs ("
            "seq INTEGER PRIMARY KEY AUTOINCREMENT,"
            "ts REAL NOT NULL,"
            "kind TEXT NOT NULL,"
            "run_id TEXT NOT NULL DEFAULT '',"
            "config_hash TEXT NOT NULL DEFAULT '',"
            "policy TEXT NOT NULL DEFAULT '',"
            "seed INTEGER,"
            "label TEXT NOT NULL DEFAULT '',"
            "metrics TEXT NOT NULL)"
        )
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS runs_key "
            "ON runs (kind, config_hash, label)"
        )
        self._db.commit()

    # ------------------------------------------------------------------ #

    def append(
        self,
        kind: str,
        *,
        metrics: Dict[str, object],
        run_id: str = "",
        config_hash: str = "",
        policy: str = "",
        seed: Optional[int] = None,
        label: str = "",
        ts: Optional[float] = None,
    ) -> int:
        """Append one invocation result; returns its ``seq``.  Non-finite
        floats are stored as strings ("inf"/"nan") so the payload stays
        strict JSON — the same rule the sweep artifacts follow."""
        cur = self._db.execute(
            "INSERT INTO runs (ts, kind, run_id, config_hash, policy, seed,"
            " label, metrics) VALUES (?,?,?,?,?,?,?,?)",
            (
                float(ts if ts is not None else time.time()),
                str(kind), str(run_id), str(config_hash), str(policy),
                None if seed is None else int(seed), str(label),
                json.dumps(_jsonable(metrics), sort_keys=True),
            ),
        )
        self._db.commit()
        return int(cur.lastrowid)

    def rows(
        self,
        *,
        kind: Optional[str] = None,
        config_hash: Optional[str] = None,
        run_id: Optional[str] = None,
        label: Optional[str] = None,
        last: Optional[int] = None,
    ) -> List[HistoryRow]:
        """Matching rows in ``seq`` (invocation) order; ``last`` keeps
        only the newest N."""
        clauses, params = [], []
        for col, val in (
            ("kind", kind), ("config_hash", config_hash),
            ("run_id", run_id), ("label", label),
        ):
            if val is not None:
                clauses.append(f"{col} = ?")
                params.append(val)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        sql = (
            "SELECT seq, ts, kind, run_id, config_hash, policy, seed, "
            f"label, metrics FROM runs{where} ORDER BY seq"
        )
        out = [
            HistoryRow(
                seq=int(r[0]), ts=float(r[1]), kind=r[2], run_id=r[3],
                config_hash=r[4], policy=r[5],
                seed=None if r[6] is None else int(r[6]),
                label=r[7], metrics=json.loads(r[8]),
            )
            for r in self._db.execute(sql, params)
        ]
        if last is not None and last >= 0:
            out = out[-last:] if last > 0 else []
        return out

    def count(
        self,
        *,
        kind: Optional[str] = None,
        config_hash: Optional[str] = None,
        run_id: Optional[str] = None,
        label: Optional[str] = None,
    ) -> int:
        """Matching-row count without materializing the rows — how the
        watchtower's incident drill proves its alert counter and this
        ledger agree (ISSUE 15)."""
        clauses, params = [], []
        for col, val in (
            ("kind", kind), ("config_hash", config_hash),
            ("run_id", run_id), ("label", label),
        ):
            if val is not None:
                clauses.append(f"{col} = ?")
                params.append(val)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        (n,) = self._db.execute(
            f"SELECT COUNT(*) FROM runs{where}", params
        ).fetchone()
        return int(n)

    def close(self) -> None:
        self._db.close()

    def __enter__(self) -> "HistoryStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _jsonable(obj):
    """Strict-JSON coercion (inf/nan -> strings), recursively."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)  # "inf" / "-inf" / "nan"
    return obj


# --------------------------------------------------------------------- #
# trend rendering


def trend_points(
    rows: Sequence[HistoryRow], metric: str
) -> List[HistoryRow]:
    """The rows that actually carry ``metric`` as a number, in order."""
    return [r for r in rows if r.metric(metric) is not None]


def trend_delta(
    rows: Sequence[HistoryRow], metric: str, *, last: int = 5
) -> Optional[dict]:
    """The newest row's value against the median of up to ``last`` prior
    rows — how engine_bench turns one suspect number on a 2x-noise box
    into a position within a distribution.  None when there is no prior
    history (first invocation) or no carrying row at all."""
    pts = trend_points(rows, metric)
    if not pts or last <= 0:
        return None
    cur = pts[-1]
    prior = [r.metric(metric) for r in pts[:-1]][-last:]
    if not prior:
        return None
    s = sorted(prior)
    n = len(s)
    med = s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0
    value = cur.metric(metric)
    return {
        "metric": metric,
        "value": value,
        "median": med,
        "n_prior": len(prior),
        "delta": (value - med),
        "delta_frac": ((value - med) / med) if med else None,
    }


def render_trend(
    rows: Sequence[HistoryRow], metrics: Sequence[str]
) -> str:
    """Fixed-width per-metric trajectory table over ``rows`` (invocation
    order).  Deterministic: a pure function of the rows — two separate
    CLI invocations over the same store print identical bytes."""
    if not rows:
        return "(empty history)"
    headers = ["seq", "kind", "policy", "label", "run"] + [
        f"{m}" for m in metrics
    ] + [f"d%({m})" for m in metrics]
    table: List[List[str]] = [headers]
    prev: Dict[str, Optional[float]] = {m: None for m in metrics}
    for r in rows:
        cells = [
            str(r.seq), r.kind, r.policy or "-", r.label or "-",
            (r.run_id[:24] or "-"),
        ]
        deltas = []
        for m in metrics:
            v = r.metric(m)
            cells.append(_fmt(v))
            p = prev[m]
            if v is None or p is None or p == 0:
                deltas.append("-")
            else:
                deltas.append(f"{100.0 * (v - p) / abs(p):+.1f}")
            if v is not None:
                prev[m] = v
        table.append(cells + deltas)
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"
