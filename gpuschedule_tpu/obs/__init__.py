"""Observability layer: span tracer, metrics registry, timeline export.

A zero-dependency, near-zero-cost-when-disabled telemetry substrate shared
by every layer (ISSUE 1 tentpole; the measurement prerequisite for the
ROADMAP's production-scale north star):

- :mod:`gpuschedule_tpu.obs.tracer` — nested wall/sim-time spans behind a
  process-wide singleton; disabled by default (``GSTPU_TRACE=1`` or
  ``run --spans`` turns it on);
- :mod:`gpuschedule_tpu.obs.metrics` — labeled counters/gauges/histograms
  with Prometheus text + JSON exposition, absorbed by ``MetricsLog``;
- :mod:`gpuschedule_tpu.obs.perfetto` — Chrome trace-event export of a
  replay's event stream (one track per pod/slice, one slice per occupancy
  interval), loadable in ui.perfetto.dev.

Like the sim core, this package must stay jax-free: replay observability
cannot pull an accelerator stack into the loop (tests/test_overhead.py
pins the import boundary).
"""

from gpuschedule_tpu.obs.tracer import NULL_SPAN, Span, Tracer, get_tracer
from gpuschedule_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from gpuschedule_tpu.obs.perfetto import (
    export_chrome_trace,
    load_events_jsonl,
    trace_events,
    track_label,
    validate_chrome_trace,
)

__all__ = [
    "NULL_SPAN",
    "Span",
    "Tracer",
    "get_tracer",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "export_chrome_trace",
    "load_events_jsonl",
    "trace_events",
    "track_label",
    "validate_chrome_trace",
]
