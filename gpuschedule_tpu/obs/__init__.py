"""Observability layer: span tracer, metrics registry, timeline export.

A zero-dependency, near-zero-cost-when-disabled telemetry substrate shared
by every layer (ISSUE 1 tentpole; the measurement prerequisite for the
ROADMAP's production-scale north star):

- :mod:`gpuschedule_tpu.obs.tracer` — nested wall/sim-time spans behind a
  process-wide singleton; disabled by default (``GSTPU_TRACE=1`` or
  ``run --spans`` turns it on);
- :mod:`gpuschedule_tpu.obs.metrics` — labeled counters/gauges/histograms
  with Prometheus text + JSON exposition, absorbed by ``MetricsLog``;
- :mod:`gpuschedule_tpu.obs.perfetto` — Chrome trace-event export of a
  replay's event stream (one track per pod/slice, one slice per occupancy
  interval), loadable in ui.perfetto.dev;
- :mod:`gpuschedule_tpu.obs.analyze` — streaming per-job lifecycle
  reconstruction from the JSONL event log: distributions with exact
  quantiles, utilization/fragmentation series, a fault-attribution
  table that closes bit-exactly against ``SimResult.goodput`` (ISSUE 3
  tentpole), and the causal wait/slowdown decomposition + physical
  occupancy series that answer "why was this job slow?" (ISSUE 5
  tentpole, closing against ``SimResult.delay_by_cause``);
- :mod:`gpuschedule_tpu.obs.compare` — cross-run regression diff with
  polarity-aware thresholds and CI exit codes, plus the n-way
  policy x metric matrix (``compare_matrix``);
- :mod:`gpuschedule_tpu.obs.report` — one self-contained HTML report
  (inline CSS/SVG, zero network fetches);
- :mod:`gpuschedule_tpu.obs.selfprof` — wall-clock phase profiler for the
  replay loop itself (ISSUE 10): ``run --self-profile`` buckets each
  batch's wall time into event-apply / policy / net-resolve / fault /
  metrics / analytics phases, with a Perfetto wall-time track;
- :mod:`gpuschedule_tpu.obs.history` — append-only sqlite store of run /
  compare / bench summaries keyed by run_id/config_hash, with the
  ``history trend`` CLI rendering per-metric trajectories across
  invocations (ISSUE 10);
- :mod:`gpuschedule_tpu.obs.fleet` — cross-process observability
  (ISSUE 16): trace-context envelopes propagated through the worker
  pool, per-task child tracer/registry/profiler harnesses, deterministic
  registry/selfprof federation, and one merged Perfetto document with a
  named track per worker.

Like the sim core, this package must stay jax-free: replay observability
cannot pull an accelerator stack into the loop (tests/test_overhead.py
pins the import boundary).
"""

from gpuschedule_tpu.obs.tracer import NULL_SPAN, Span, Tracer, get_tracer
from gpuschedule_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    exact_quantile,
    get_registry,
    quantile_sorted,
)
from gpuschedule_tpu.obs.analyze import (
    RunAnalysis,
    RunHeader,
    SchemaError,
    StreamCursor,
    StreamError,
    analyze_events,
    analyze_file,
    config_hash,
    iter_jsonl_items,
    iter_jsonl_records,
)
from gpuschedule_tpu.obs.watch import (
    DEFAULT_RULES,
    AlertStream,
    Watcher,
    follow_stream,
    iter_stream,
    load_rules,
    replay_stream,
    run_watch,
)
from gpuschedule_tpu.obs.compare import (
    CompareResult,
    MatrixResult,
    compare_matrix,
    compare_runs,
    parse_thresholds,
    write_compare_json,
    write_matrix_json,
)
from gpuschedule_tpu.obs.report import render_report, write_report
from gpuschedule_tpu.obs.selfprof import (
    PHASES,
    PhaseProfiler,
    load_profile,
    merge_profiles,
)
from gpuschedule_tpu.obs.fleet import (
    FleetCollector,
    TaskContext,
    WorkerTelemetry,
    task_profiler,
    task_span,
)
from gpuschedule_tpu.obs.history import (
    HistoryRow,
    HistoryStore,
    render_trend,
    trend_delta,
)
from gpuschedule_tpu.obs.perfetto import (
    export_chrome_trace,
    fleet_trace_events,
    load_events_jsonl,
    trace_events,
    track_label,
    validate_chrome_trace,
)

__all__ = [
    "NULL_SPAN",
    "Span",
    "Tracer",
    "get_tracer",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "exact_quantile",
    "get_registry",
    "quantile_sorted",
    "RunAnalysis",
    "RunHeader",
    "SchemaError",
    "StreamCursor",
    "StreamError",
    "analyze_events",
    "analyze_file",
    "config_hash",
    "iter_jsonl_items",
    "iter_jsonl_records",
    "DEFAULT_RULES",
    "AlertStream",
    "Watcher",
    "follow_stream",
    "iter_stream",
    "load_rules",
    "replay_stream",
    "run_watch",
    "CompareResult",
    "MatrixResult",
    "compare_matrix",
    "compare_runs",
    "parse_thresholds",
    "write_compare_json",
    "write_matrix_json",
    "render_report",
    "write_report",
    "PHASES",
    "PhaseProfiler",
    "load_profile",
    "merge_profiles",
    "FleetCollector",
    "TaskContext",
    "WorkerTelemetry",
    "task_profiler",
    "task_span",
    "HistoryRow",
    "HistoryStore",
    "render_trend",
    "trend_delta",
    "export_chrome_trace",
    "fleet_trace_events",
    "load_events_jsonl",
    "trace_events",
    "track_label",
    "validate_chrome_trace",
]
