"""Serve the twin (ISSUE 18 tentpole): a live observability control
plane over the paused engine — ``/metrics`` scrape, SSE alert feed,
HTTP what-if API, and a self-SLO watchdog.

Every earlier surface is a one-shot CLI invocation; this module is the
long-lived daemon production observability expects: it tails a live (or
replayed-as-live) event stream through the PR-15 :class:`Watcher`,
fronts a warm :class:`WhatIfService` pool, and exposes

- ``GET /metrics`` — Prometheus text exposition of the live registry
  (query-latency histogram, rejection counter, federated pool
  lifecycle counters, process self-gauges);
- ``GET /alerts`` — an SSE feed of latched watchtower alerts the
  instant they fire (backlog replay on connect, keepalive comments);
- ``POST /whatif`` — JSON queries against the warm mirror, admission-
  controlled: a bounded in-flight queue keyed to pool depth answers
  saturation with HTTP 429 + ``whatif_rejected_total``;
- ``GET /status`` / ``/healthz`` / ``/readyz`` — pool depth, respawn /
  retry counters, watcher window position, query-latency summary;
- ``GET /`` — a self-contained live dashboard reusing the report
  palette.

Observability all the way down: a :class:`~.watch.SelfSLO` watchdog —
the PR-15 multi-window burn-rate machinery pointed at the daemon's own
latency / rejection / error series — raises alerts about *itself* into
the same alert stream, history rows, and ``watch_alerts_total`` family
as cluster incidents.

**Determinism boundary** (lint: this file sits in
``LintConfig.determinism_files``): the HTTP layer is strictly a veneer
over the deterministic cores.  The served what-if document is byte-
identical to the offline ``whatif`` CLI on the same mirror (modulo the
wall-clock latency readings — :func:`~.whatif.canonical_document`), the
SSE alert sequence is identical to batch ``watch`` on the same stream,
and wall clock lives only at the edge (uptime, drain deadlines), each
read behind a reasoned pragma.  Pinned by tests/test_serve.py.

Graceful shutdown (SIGTERM/SIGINT via
:func:`install_signal_handlers`): stop admitting, drain in-flight
queries up to ``drain_s``, stop the HTTP server, finish the watcher
(header + summary + alert-file flush), close the pool, and append one
``kind="serve"`` history row so service health trends across sessions.
"""

from __future__ import annotations

import json
import queue
import signal
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from gpuschedule_tpu.obs.metrics import (
    MetricsRegistry,
    exact_quantile,
    exposition,
    process_gauges,
)
from gpuschedule_tpu.obs.watch import (
    AlertStream,
    SelfSLO,
    Watcher,
    follow_stream,
    iter_stream,
    replay_stream,
)
from gpuschedule_tpu.sim.whatif import (
    AdmissionError,
    WhatIfService,
    normalize_query,
    result_document,
    validate_query,
)

SERVER_NAME = "gpuschedule-twin"


# --------------------------------------------------------------------- #
# alert fan-out


class AlertHub:
    """Fan one alert-record sequence out to any number of SSE clients:
    each client gets its own bounded queue; late joiners replay the
    retained backlog first, so the SSE sequence every client sees is a
    prefix-complete copy of the write order (the batch ``watch``
    identity contract).  A slow client's full queue drops for THAT
    client only (counted) — delivery never blocks the detector path."""

    def __init__(self, max_backlog: int = 256, max_queue: int = 1024):
        self._lock = threading.Lock()
        self._clients: List[queue.Queue] = []
        self._backlog: deque = deque(maxlen=max_backlog)
        self._max_queue = max_queue
        self.published = 0
        self.dropped = 0

    def publish(self, rec: dict) -> None:
        with self._lock:
            self.published += 1
            self._backlog.append(rec)
            for q in self._clients:
                try:
                    q.put_nowait(rec)
                except queue.Full:
                    self.dropped += 1

    def attach(self) -> Tuple[List[dict], queue.Queue]:
        """Join: returns (backlog so far, this client's live queue)."""
        q: queue.Queue = queue.Queue(maxsize=self._max_queue)
        with self._lock:
            backlog = list(self._backlog)
            self._clients.append(q)
        return backlog, q

    def detach(self, q: queue.Queue) -> None:
        with self._lock:
            if q in self._clients:
                self._clients.remove(q)

    @property
    def clients(self) -> int:
        with self._lock:
            return len(self._clients)


class _HistoryTee:
    """History writes from daemon threads: sqlite connections are
    thread-affine, so each append opens (and closes) its own
    :class:`HistoryStore` — alerts are rare, a per-row open is noise."""

    def __init__(self, path):
        self.path = path

    def append(self, kind: str, **kw) -> None:
        from gpuschedule_tpu.obs.history import HistoryStore

        with HistoryStore(self.path) as store:
            store.append(kind, **kw)


def _normalize_queries(payload) -> List[dict]:
    """The POST /whatif body grammar: ``{"queries": [...]}``, one bare
    query object, or a bare list.  Raises ValueError on anything else —
    the edge turns that into HTTP 400."""
    if isinstance(payload, dict):
        if "queries" in payload:
            payload = payload["queries"]
        elif "kind" in payload:
            payload = [payload]
        else:
            raise ValueError(
                'POST /whatif wants {"queries": [...]}, one query '
                "object, or a list of query objects"
            )
    if not isinstance(payload, list) or not payload:
        raise ValueError("POST /whatif needs at least one query")
    for q in payload:
        if not isinstance(q, dict):
            raise ValueError(f"query must be an object, got {type(q).__name__}")
    # wire-format numeric coercion: the echoed query is part of the
    # served document's byte-identity surface
    return [normalize_query(q) for q in payload]


# --------------------------------------------------------------------- #
# the daemon


class TwinServer:
    """The serving daemon: one warm :class:`WhatIfService`, one
    :class:`Watcher` over an event stream (optional), one
    :class:`SelfSLO` watchdog over its own serving series, one HTTP
    front end.  Construct, :meth:`start`, wait, :meth:`shutdown`."""

    def __init__(
        self,
        service: WhatIfService,
        *,
        registry: MetricsRegistry,
        requested_at: float,
        run_meta: dict,
        events=None,
        mode: str = "batch",
        rules: Optional[dict] = None,
        self_slo: Optional[dict] = None,
        alerts_path=None,
        history=None,
        host: str = "127.0.0.1",
        port: int = 0,
        speed: float = 0.0,
        poll_s: float = 0.5,
        idle_timeout_s: Optional[float] = None,
        max_wall_s: Optional[float] = None,
        sse_keepalive_s: float = 15.0,
        drain_s: float = 10.0,
    ):
        if mode not in ("batch", "replay", "follow"):
            raise ValueError(
                f"serve mode must be batch|replay|follow, got {mode!r}"
            )
        self.service = service
        self.registry = registry
        self.requested_at = float(requested_at)
        self.run_meta = dict(run_meta)
        self.host = host
        self.port = int(port)
        self.mode = mode
        self.sse_keepalive_s = float(sse_keepalive_s)
        self.drain_s = float(drain_s)
        self._events = events
        self._speed = float(speed)
        self._poll_s = float(poll_s)
        self._idle_timeout_s = idle_timeout_s
        self._max_wall_s = max_wall_s

        self.hub = AlertHub()
        tee = _HistoryTee(history) if history is not None else None
        self._history = tee
        # ONE alert side stream for cluster and self alerts alike — the
        # hub subscribes as a pluggable sink, so SSE clients see exactly
        # the sequence the file tee records
        self.sink = AlertStream(alerts_path)
        self.sink.subscribe(self._on_alert_rec)
        self.watcher: Optional[Watcher] = None
        if events is not None:
            self.watcher = Watcher(
                rules, alerts=self.sink, registry=registry,
                history=tee, source=str(events),
            )
        self.self_slo = SelfSLO(
            self_slo, sink=self.sink, registry=registry,
            history=tee, run_meta=self.run_meta,
        )

        # the serving registry's families exist from the first scrape,
        # not the first incident: pre-register the rejection counter and
        # the pool lifecycle counters (idempotent with the pool's own
        # registration), and arm the process self-gauges
        registry.counter(
            "whatif_rejected_total",
            "what-if queries refused by admission control "
            "(in-flight queue full)",
        )
        registry.counter(
            "pool_worker_respawns_total",
            "dead pool workers respawned (and re-warmed)",
        )
        registry.counter(
            "pool_task_retries_total",
            "pool task attempts retried after a crash or exception",
        )
        self._inflight_gauge = registry.gauge(
            "pool_inflight", "admitted what-if queries in flight right now"
        )
        self._update_process_gauges = process_gauges(registry)

        self.errors = 0
        self._latencies: List[float] = []
        self._lat_lock = threading.Lock()
        self._slo_lock = threading.Lock()
        self._watch_lock = threading.Lock()
        self._stopping = threading.Event()
        self._ready = threading.Event()
        self._stream_done = threading.Event()
        self.stream_error: Optional[str] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        self._finished = False
        self._summary: Optional[dict] = None
        # uptime anchor for /status and the serve history row
        self._t0 = time.monotonic()  # lint: allow[GS101] daemon uptime is wall-clock by design; nothing served derives from it

    # ------------------------------------------------------------------ #
    # lifecycle

    def start(self) -> None:
        """Bind, start the HTTP and watch threads, mark ready."""
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="twin-http", daemon=True,
        )
        self._http_thread.start()
        if self.watcher is not None:
            self._watch_thread = threading.Thread(
                target=self._run_watch, name="twin-watch", daemon=True,
            )
            self._watch_thread.start()
        else:
            # no stream watcher to emit the side stream's versioned
            # header lazily — write it now, before any self-SLO alert,
            # so the alert file keeps the PR-15 audit-trail shape
            self.sink.write_header({
                "run_id": self.run_meta.get("run_id", ""),
                "policy": self.run_meta.get("policy", ""),
                "seed": self.run_meta.get("seed"),
                "config_hash": self.run_meta.get("config_hash", ""),
                "source": "serve",
            })
            self._stream_done.set()
        self._ready.set()

    def _stream(self):
        if self.mode == "follow":
            return follow_stream(
                self._events, poll_s=self._poll_s,
                idle_timeout_s=self._idle_timeout_s,
                max_wall_s=self._max_wall_s,
            )
        if self.mode == "replay":
            return replay_stream(self._events, speed=self._speed)
        return iter_stream(self._events)

    def _run_watch(self) -> None:
        """The watch thread: drive the watcher over the stream.  The
        watcher is deliberately NOT finished here — finish() closes the
        alert file, and the self-SLO watchdog keeps writing into it for
        as long as the daemon serves; shutdown finishes it."""
        from gpuschedule_tpu.obs import StreamError

        try:
            for _, raw, rec in self._stream():
                if self._stopping.is_set():
                    break
                with self._watch_lock:
                    self.watcher.feed(rec, raw)
        except StreamError as e:
            self.stream_error = str(e)
        finally:
            self._stream_done.set()

    def _on_alert_rec(self, rec: dict) -> None:
        # the side stream also carries its header record at finish();
        # SSE clients (and the batch-identity contract) see alerts only
        if rec.get("event") == "alert":
            self.hub.publish(rec)

    # ------------------------------------------------------------------ #
    # the query path

    @property
    def ready(self) -> bool:
        return self._ready.is_set() and not self._stopping.is_set()

    def serve_whatif(self, payload) -> Tuple[int, dict]:
        """One POST /whatif: normalize, pre-validate (deterministic user
        errors must 400 BEFORE evaluation — a pooled worker would retry
        them with backoff), admit, evaluate, self-observe.  Returns
        (HTTP status, response document)."""
        if not self.ready:
            return 503, {"error": "service is warming up or draining"}
        sim = self.service.sim
        try:
            queries = _normalize_queries(payload)
            for q in queries:
                validate_query(dict(q))
                at = q.get("at")
                if at is None:
                    continue
                if at < sim.now:
                    raise ValueError(
                        f"query at={at} is before the mirror instant "
                        f"(t={sim.now})"
                    )
                if at > min(sim.now + self.service.horizon, sim.max_time):
                    raise ValueError(
                        f"query at={at} is beyond the bounded replay "
                        f"window (mirror t={sim.now} + horizon "
                        f"{self.service.horizon})"
                    )
        except ValueError as e:
            self.errors += 1
            with self._slo_lock:
                self.self_slo.observe(error=True)
            return 400, {"error": str(e)}
        try:
            with self.service.admitted():
                results = self.service.evaluate_admitted(queries)
        except AdmissionError as e:
            with self._slo_lock:
                self.self_slo.observe(rejected=True)
            return 429, {"error": str(e)}
        except ValueError as e:
            self.errors += 1
            with self._slo_lock:
                self.self_slo.observe(error=True)
            return 400, {"error": str(e)}
        doc = result_document(
            sim, results,
            requested_at=self.requested_at,
            horizon=self.service.horizon,
            pool=self.service.workers,
            run_meta=self.run_meta,
        )
        lats = [1000.0 * r["latency_s"] for r in results]
        with self._lat_lock:
            self._latencies.extend(lats)
        with self._slo_lock:
            for ms in lats:
                self.self_slo.observe(ms)
        return 200, doc

    # ------------------------------------------------------------------ #
    # status / metrics

    def refresh_gauges(self) -> None:
        self._inflight_gauge.set(float(self.service.inflight))
        self._update_process_gauges()

    def _latency_block(self) -> dict:
        with self._lat_lock:
            lats = sorted(self._latencies)
        if not lats:
            return {"count": 0}
        return {
            "count": len(lats),
            "p50_ms": exact_quantile(lats, 0.50),
            "p99_ms": exact_quantile(lats, 0.99),
            "max_ms": lats[-1],
        }

    def status(self) -> dict:
        svc = self.service
        pool = dict(svc.pool_stats())
        pool["max_inflight"] = svc.max_inflight
        pool["inflight"] = svc.inflight
        watch = None
        if self.watcher is not None:
            with self._watch_lock:
                w = self.watcher
                watch = {
                    "source": w.source,
                    "events": w.n_events,
                    "end_t": w.end_t,
                    "windows": w.windows,
                    "alerts": len(w.alerts),
                    "active": sorted(w._active_alerts),
                    "stream_done": self._stream_done.is_set(),
                }
                if self.stream_error:
                    watch["stream_error"] = self.stream_error
        with self._slo_lock:
            self_slo = {
                "observations": self.self_slo.observations,
                "windows": self.self_slo.windows,
                "alerts": len(self.self_slo.alerts),
                "active": self.self_slo.active,
            }
        return {
            "server": SERVER_NAME,
            "ready": self.ready,
            "stopping": self._stopping.is_set(),
            "mode": self.mode,
            "uptime_s": time.monotonic() - self._t0,  # lint: allow[GS101] same daemon-uptime surface as the anchor above
            "run": {
                "run_id": self.run_meta.get("run_id", ""),
                "policy": self.run_meta.get("policy", ""),
                "config_hash": self.run_meta.get("config_hash", ""),
                "seed": self.run_meta.get("seed"),
            },
            "mirror": {
                "at_s": svc.sim.now,
                "requested_at_s": self.requested_at,
                "horizon_s": svc.horizon,
                "running": len(svc.sim.running),
                "pending": len(svc.sim.pending),
                "finished": len(svc.sim.finished),
            },
            "pool": pool,
            "queries": {
                "served": svc.queries_served,
                "rejections": svc.rejections,
                "errors": self.errors,
                "latency_ms": self._latency_block(),
            },
            "watch": watch,
            "self_slo": self_slo,
            "alerts": {
                "total": self.hub.published,
                "dropped": self.hub.dropped,
                "sse_clients": self.hub.clients,
            },
        }

    # ------------------------------------------------------------------ #
    # shutdown

    def shutdown(self) -> dict:
        """Graceful stop: refuse new work, drain in-flight queries up to
        ``drain_s``, stop HTTP, finish the watcher (header + alert-file
        flush), close the pool, append the ``serve`` history row.
        Idempotent; returns the session summary."""
        if self._summary is not None:
            return self._summary
        self._stopping.set()
        deadline = time.monotonic() + self.drain_s  # lint: allow[GS101] drain deadline is a wall-clock budget at the edge; served bytes never depend on it
        while self.service.inflight > 0 and \
                time.monotonic() < deadline:  # lint: allow[GS101] same drain-deadline surface as above
            time.sleep(0.02)
        drained = self.service.inflight == 0
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=max(1.0, 2 * self._poll_s))
        pool_stats = self.service.pool_stats()
        with self._watch_lock:
            watch_summary = None
            if self.watcher is not None and not self._finished:
                watch_summary = self.watcher.finish()
            elif self.watcher is None and not self._finished:
                self.sink.close()
            self._finished = True
        self.service.close()
        lat = self._latency_block()
        uptime = time.monotonic() - self._t0  # lint: allow[GS101] same daemon-uptime surface as the anchor above
        metrics = {
            "queries": self.service.queries_served,
            "rejections": self.service.rejections,
            "errors": self.errors,
            "alerts": self.hub.published,
            "self_slo_alerts": len(self.self_slo.alerts),
            "p50_ms": lat.get("p50_ms", 0.0),
            "p99_ms": lat.get("p99_ms", 0.0),
            "uptime_s": uptime,
            "drained": int(drained),
        }
        if self._history is not None:
            self._history.append(
                "serve",
                run_id=self.run_meta.get("run_id", ""),
                config_hash=self.run_meta.get("config_hash", ""),
                policy=self.run_meta.get("policy", ""),
                seed=self.run_meta.get("seed"),
                label="session",
                metrics=metrics,
            )
        self._summary = {
            "host": self.host, "port": self.port, "mode": self.mode,
            **metrics,
        }
        if watch_summary is not None:
            self._summary["watch"] = watch_summary
        return self._summary


def install_signal_handlers(server: TwinServer) -> threading.Event:
    """SIGTERM/SIGINT → one stop event (main thread waits on it, then
    runs :meth:`TwinServer.shutdown`).  A second signal during the drain
    still only sets the event — shutdown itself is idempotent."""
    stop = threading.Event()

    def _handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    return stop


# --------------------------------------------------------------------- #
# the HTTP edge


def _make_handler(server: TwinServer):
    """One handler class bound to one :class:`TwinServer` (closure, not
    globals — tests run several daemons in one process)."""

    class Handler(BaseHTTPRequestHandler):
        server_version = SERVER_NAME
        protocol_version = "HTTP/1.1"

        # ------------------------------------------------------------- #

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, doc: dict) -> None:
            body = (json.dumps(doc, sort_keys=True) + "\n").encode("utf-8")
            self._send(code, body, "application/json; charset=utf-8")

        def log_message(self, fmt, *args):  # quiet: the daemon's own
            pass                            # telemetry is the log

        # ------------------------------------------------------------- #

        def do_GET(self) -> None:
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                server.refresh_gauges()
                body, ctype = exposition(server.registry)
                self._send(200, body, ctype)
            elif path == "/status":
                self._send_json(200, server.status())
            elif path == "/healthz":
                self._send(200, b"ok\n", "text/plain; charset=utf-8")
            elif path == "/readyz":
                if server.ready:
                    self._send(200, b"ready\n", "text/plain; charset=utf-8")
                else:
                    self._send_json(503, {"error": "not ready"})
            elif path == "/alerts":
                self._serve_sse()
            elif path == "/":
                self._send(
                    200, dashboard_html().encode("utf-8"),
                    "text/html; charset=utf-8",
                )
            else:
                self._send_json(404, {"error": f"no route {path}"})

        def do_POST(self) -> None:
            path = self.path.split("?", 1)[0]
            if path != "/whatif":
                self._send_json(404, {"error": f"no route {path}"})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(n) or b"null")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400, {"error": f"bad JSON body: {e}"})
                return
            code, doc = server.serve_whatif(payload)
            self._send_json(code, doc)

        # ------------------------------------------------------------- #

        def _serve_sse(self) -> None:
            """The alert feed: backlog replay, then live records as the
            hub delivers them, keepalive comments in the gaps.  Frame
            payloads are ``json.dumps(rec, sort_keys=True)`` — the exact
            bytes batch ``watch`` prints per alert (the identity
            contract, tests/test_serve.py)."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            backlog, q = server.hub.attach()
            try:
                for rec in backlog:
                    self._sse_frame(rec)
                while not server._stopping.is_set():
                    try:
                        rec = q.get(timeout=server.sse_keepalive_s)
                    except queue.Empty:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    self._sse_frame(rec)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                server.hub.detach(q)

        def _sse_frame(self, rec: dict) -> None:
            data = json.dumps(rec, sort_keys=True)
            self.wfile.write(
                f"event: alert\ndata: {data}\n\n".encode("utf-8")
            )
            self.wfile.flush()

    return Handler


# --------------------------------------------------------------------- #
# the dashboard


def dashboard_html() -> str:
    """GET /: a self-contained live page — status tiles polled from
    ``/status``, the alert feed via ``EventSource('/alerts')`` — in the
    report surface's palette (obs/report.py), light and dark."""
    return _DASHBOARD


_DASHBOARD = """<!doctype html>
<html><head><meta charset="utf-8">
<title>gpuschedule twin</title>
<style>
:root { color-scheme: light dark; }
body {
  margin: 0; padding: 24px;
  font-family: system-ui, -apple-system, "Segoe UI", sans-serif;
  background: var(--page); color: var(--text-primary);
}
.viz-root {
  --surface-1: #fcfcfb; --page: #f9f9f7;
  --text-primary: #0b0b0b; --text-secondary: #52514e; --muted: #898781;
  --grid: #e1e0d9; --baseline: #c3c2b7;
  --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
  --series-4: #9556c7; --series-5: #c23f87;
  --border: rgba(11,11,11,0.10);
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root {
    --surface-1: #1a1a19; --page: #0d0d0d;
    --text-primary: #ffffff; --text-secondary: #c3c2b7; --muted: #898781;
    --grid: #2c2c2a; --baseline: #383835;
    --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
    --series-4: #a365d6; --series-5: #d052a0;
    --border: rgba(255,255,255,0.10);
  }
}
body { background: var(--page); }
h1 { font-size: 20px; margin: 0 0 4px; }
h2 { font-size: 15px; margin: 24px 0 8px; }
.meta { color: var(--text-secondary); font-size: 13px; margin-bottom: 16px; }
.kpis { display: flex; flex-wrap: wrap; gap: 12px; }
.tile {
  background: var(--surface-1); border: 1px solid var(--border);
  border-radius: 8px; padding: 12px 16px; min-width: 130px; flex: 1;
}
.tile .label { font-size: 12px; color: var(--text-secondary); }
.tile .value { font-size: 26px; font-weight: 600; margin-top: 2px;
  font-variant-numeric: tabular-nums; }
.panel {
  background: var(--surface-1); border: 1px solid var(--border);
  border-radius: 8px; padding: 16px; margin-bottom: 16px;
}
#alerts { font-size: 13px; font-family: ui-monospace, monospace;
  white-space: pre-wrap; max-height: 320px; overflow-y: auto; }
#alerts .page { color: var(--series-2); }
.empty { color: var(--muted); font-size: 13px; }
</style></head>
<body><div class="viz-root">
<h1>gpuschedule twin</h1>
<div class="meta" id="meta">connecting&hellip;</div>
<div class="kpis">
  <div class="tile"><div class="label">queries served</div>
    <div class="value" id="k-served">&ndash;</div></div>
  <div class="tile"><div class="label">rejections (429)</div>
    <div class="value" id="k-rej">&ndash;</div></div>
  <div class="tile"><div class="label">p50 / p99 latency (ms)</div>
    <div class="value" id="k-lat">&ndash;</div></div>
  <div class="tile"><div class="label">pool (workers / in flight)</div>
    <div class="value" id="k-pool">&ndash;</div></div>
  <div class="tile"><div class="label">alerts</div>
    <div class="value" id="k-alerts">&ndash;</div></div>
</div>
<h2>alert feed</h2>
<div class="panel"><div id="alerts" class="empty">no alerts yet</div></div>
<script>
function fmt(v, d) { return v == null ? "\\u2013" : Number(v).toFixed(d); }
async function poll() {
  try {
    const s = await (await fetch("/status")).json();
    document.getElementById("meta").textContent =
      s.run.run_id + " \\u00b7 " + s.mode + " \\u00b7 mirror t=" +
      fmt(s.mirror.at_s, 0) + "s \\u00b7 up " + fmt(s.uptime_s, 0) + "s" +
      (s.ready ? "" : " \\u00b7 NOT READY");
    document.getElementById("k-served").textContent = s.queries.served;
    document.getElementById("k-rej").textContent = s.queries.rejections;
    const l = s.queries.latency_ms;
    document.getElementById("k-lat").textContent =
      l.count ? fmt(l.p50_ms, 1) + " / " + fmt(l.p99_ms, 1) : "\\u2013";
    document.getElementById("k-pool").textContent =
      s.pool.workers + " / " + s.pool.inflight;
    document.getElementById("k-alerts").textContent = s.alerts.total;
  } catch (e) { /* daemon draining */ }
}
poll(); setInterval(poll, 2000);
const box = document.getElementById("alerts");
new EventSource("/alerts").addEventListener("alert", (ev) => {
  const a = JSON.parse(ev.data);
  if (box.classList.contains("empty")) {
    box.textContent = ""; box.classList.remove("empty");
  }
  const line = document.createElement("div");
  line.className = a.severity || "";
  line.textContent =
    "t=" + a.t + " " + a.detector + " [" + a.severity + "] value=" +
    fmt(a.value, 3) + " threshold=" + fmt(a.threshold, 3);
  box.prepend(line);
});
</script>
</div></body></html>
"""
