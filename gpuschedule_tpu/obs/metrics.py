"""Metrics registry: labeled counters/gauges/histograms, Prometheus + JSON.

The aggregation half of the observability layer (ISSUE 1 tentpole): where
:class:`~gpuschedule_tpu.sim.metrics.MetricsLog` is a per-run recorder (CSV
rows, event stream), this registry is a process-level surface in the
Prometheus data model — monotone counters, point-in-time gauges, and
bucketed histograms, each optionally labeled — with two exports:

- :meth:`MetricsRegistry.prometheus_text`: the text exposition format, the
  thing a scrape endpoint would serve (``# HELP`` / ``# TYPE`` / samples);
- :meth:`MetricsRegistry.to_json`: the same state as one JSON document for
  artifact files next to the run's CSVs.

``MetricsLog`` absorbs this registry when constructed with one: its
``counters`` keep working exactly as before (the BASELINE summary contract),
and every ``count()``/``sample()`` additionally feeds the registry, which is
how a replay's counters reach the Prometheus surface without a second
bookkeeping path.

Zero dependencies, thread-safe (one lock per metric family), and dormant
unless something asks for a registry — nothing global is updated during an
un-instrumented run.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# The Prometheus text exposition content type (format version 0.0.4) —
# what a conforming /metrics endpoint declares.
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Prometheus' default histogram buckets, trimmed to the second-to-minutes
# range scheduling telemetry actually spans.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 600.0, math.inf
)

# Millisecond-scale buckets for interactive-query latency (the what-if
# service's per-query histogram, ISSUE 12): sub-ms through tens of
# seconds, dense around the 100-500 ms budget the digital twin serves in.
LATENCY_BUCKETS_MS = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 350.0, 500.0, 750.0,
    1000.0, 2000.0, 5000.0, 10_000.0, 30_000.0, math.inf
)

_VALID_FIRST = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_:")
_VALID_REST = _VALID_FIRST | set("0123456789")


def quantile_sorted(s: Sequence[float], q: float) -> float:
    """:func:`exact_quantile` on an ALREADY-SORTED sequence — the one-sort-
    many-quantiles path (the analyzer pulls p50/p95/p99 from each metric's
    single sorted copy instead of re-sorting per quantile)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile wants q in [0, 1], got {q}")
    if not s:
        raise ValueError("quantile of empty data")
    h = (len(s) - 1) * q
    i = int(math.floor(h))
    g = h - i
    if g == 0.0 or i + 1 >= len(s):
        return float(s[i])
    a, b = float(s[i]), float(s[i + 1])
    # numpy _lerp: anchor at b for g >= 0.5 (same rounding, hence bit-equal)
    if g >= 0.5:
        return b - (b - a) * (1.0 - g)
    return a + (b - a) * g


def exact_quantile(values: Sequence[float], q: float) -> float:
    """Exact quantile of raw observations, matching ``numpy.quantile``'s
    default "linear" method bit-for-bit: with ``n`` sorted values the target
    rank is ``h = (n-1)q``; the result interpolates between the two
    straddling order statistics using numpy's own lerp formulation (which
    switches anchor at ``g >= 0.5`` to keep the interpolation monotone), so
    the analyzer's p50/p95/p99 agree with a pandas/numpy cross-check to the
    last float (ISSUE 3 satellite)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile wants q in [0, 1], got {q}")
    return quantile_sorted(sorted(float(v) for v in values), q)


def sanitize_name(name: str) -> str:
    """Coerce an arbitrary key into a legal Prometheus metric name."""
    out = "".join(c if c in _VALID_REST else "_" for c in name)
    if not out or out[0] not in _VALID_FIRST:
        out = "_" + out
    return out


def _fmt_labels(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Metric:
    """One metric family: a name, help text, label schema, and its children
    (one child per distinct label-value tuple; the unlabeled family is its
    own single child keyed by ``()``)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = sanitize_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}
        self._lock = threading.Lock()
        self._labelvalues: Tuple[str, ...] = ()

    def labels(self, *values, **kv) -> "_Metric":
        """The child for one label-value combination (created on first use)."""
        if kv:
            if values:
                raise ValueError("pass label values positionally or by name, not both")
            try:
                values = tuple(str(kv[k]) for k in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}") from None
            if set(kv) - set(self.labelnames):
                raise ValueError(
                    f"unknown labels {sorted(set(kv) - set(self.labelnames))} "
                    f"for {self.name}"
                )
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                child._labelvalues = values
                self._children[values] = child
            return child

    def _make_child(self) -> "_Metric":
        return type(self)(self.name, self.help, ())

    def _self_or_children(self) -> Iterable["_Metric"]:
        if self.labelnames:
            with self._lock:
                return list(self._children.values())
        return [self]

    def _check_unlabeled(self) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call .labels(...) first"
            )

    # exposition hooks ---------------------------------------------------
    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, ...], ...], float]]:
        raise NotImplementedError

    def to_json(self):
        raise NotImplementedError

    # federation hooks (ISSUE 16) ----------------------------------------
    def _payload(self):
        """This child's state as a picklable value (see
        :meth:`MetricsRegistry.snapshot`)."""
        raise NotImplementedError

    def _merge_payload(self, payload) -> None:
        """Fold one snapshot payload into this child."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self._check_unlabeled()
        if n < 0:
            raise ValueError(f"counters only go up; inc({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def labeled_values(self) -> Dict[Tuple[str, ...], float]:
        """Per-child values keyed by label-value tuple (the unlabeled
        family reads as ``{(): value}``) — the read-back surface the
        watchtower uses to prove its ``watch_alerts_total{detector}``
        family and the history ledger agree alert for alert (ISSUE 15)."""
        if not self.labelnames:
            return {(): self._value}
        with self._lock:
            return {lv: c._value for lv, c in self._children.items()}

    def samples(self):
        return [
            (self.name, c._labelvalues, c._value) for c in self._self_or_children()
        ]

    def to_json(self):
        if not self.labelnames:
            return self._value
        return {
            _fmt_labels(self.labelnames, lv) or "": c._value
            for lv, c in self._children.items()
        }

    def _payload(self):
        return self._value

    def _merge_payload(self, payload) -> None:
        self.inc(float(payload))


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def set(self, v: float) -> None:
        self._check_unlabeled()
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._check_unlabeled()
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        return [
            (self.name, c._labelvalues, c._value) for c in self._self_or_children()
        ]

    def to_json(self):
        if not self.labelnames:
            return self._value
        return {
            _fmt_labels(self.labelnames, lv) or "": c._value
            for lv, c in self._children.items()
        }

    def _payload(self):
        return self._value

    def _merge_payload(self, payload) -> None:
        # gauges are point-in-time readings: a merge keeps the incoming
        # value (last writer wins, in the caller's deterministic order)
        self.set(float(payload))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs or bs[-1] != math.inf:
            bs.append(math.inf)
        self.buckets: Tuple[float, ...] = tuple(bs)
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0

    def _make_child(self):
        return Histogram(self.name, self.help, (), self.buckets)

    def observe(self, v: float) -> None:
        self._check_unlabeled()
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (Prometheus ``histogram_quantile``
        semantics): find the bucket holding rank ``q * count`` and assume
        observations are uniform within it.  The first bucket's lower edge
        is 0 (non-negative observations assumed — durations and delays,
        which is what these histograms hold); ranks landing in the +Inf
        bucket return the last finite edge, the same saturation Prometheus
        applies.  NaN on an empty histogram."""
        self._check_unlabeled()
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile wants q in [0, 1], got {q}")
        with self._lock:
            n = self._count
            counts = list(self._counts)
        if n == 0:
            return math.nan
        rank = q * n
        cum = 0
        lo = 0.0
        for b, c in zip(self.buckets, counts):
            if c > 0 and cum + c >= rank:
                if math.isinf(b):
                    return lo
                return lo + (b - lo) * ((rank - cum) / c)
            cum += c
            if not math.isinf(b):
                lo = b
        return lo

    def samples(self):
        out = []
        for c in self._self_or_children():
            cum = 0
            for b, n in zip(c.buckets, c._counts):
                cum += n
                le = ("+Inf" if b == math.inf else _fmt_value(b),)
                out.append((self.name + "_bucket", c._labelvalues + ("__le__",) + le, cum))
            out.append((self.name + "_sum", c._labelvalues, c._sum))
            out.append((self.name + "_count", c._labelvalues, c._count))
        return out

    def to_json(self):
        def one(c):
            return {
                "count": c._count,
                "sum": c._sum,
                "buckets": {
                    ("+Inf" if b == math.inf else _fmt_value(b)): n
                    for b, n in zip(c.buckets, c._counts)
                },
            }

        if not self.labelnames:
            return one(self)
        return {
            _fmt_labels(self.labelnames, lv) or "": one(c)
            for lv, c in self._children.items()
        }

    def _payload(self):
        with self._lock:
            return {
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }

    def _merge_payload(self, payload) -> None:
        counts = payload["counts"]
        if len(counts) != len(self._counts):
            raise ValueError(
                f"{self.name}: cannot merge histogram with "
                f"{len(counts)} buckets into {len(self._counts)}"
            )
        with self._lock:
            for i, n in enumerate(counts):
                self._counts[i] += n
            self._sum += payload["sum"]
            self._count += payload["count"]


class MetricsRegistry:
    """A named collection of metric families with idempotent constructors:
    ``counter("x")`` returns the same family on every call, and re-declaring
    a name as a different kind or label schema is an error (the same contract
    prometheus_client enforces)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name: str, help: str, labelnames, **kw) -> _Metric:
        name = sanitize_name(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"{name} already registered as {m.kind}{m.labelnames}; "
                f"requested {cls.kind}{tuple(labelnames)}"
            )
        want_buckets = kw.get("buckets")
        if want_buckets is not None:
            bs = sorted(float(b) for b in want_buckets)
            if not bs or bs[-1] != math.inf:
                bs.append(math.inf)
            if tuple(bs) != m.buckets:
                raise ValueError(
                    f"{name} already registered with buckets {m.buckets}; "
                    f"requested {tuple(bs)}"
                )
        return m

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames, buckets=buckets)

    # ------------------------------------------------------------------ #
    # federation (ISSUE 16): registries cross process boundaries as plain
    # picklable snapshots; merge() folds a snapshot (or another registry)
    # into this one with counter sums, bucket-wise histogram addition, and
    # label-family union — the deterministic half of cross-process
    # telemetry (the caller supplies a deterministic merge order).

    def snapshot(self) -> Dict[str, dict]:
        """This registry's full state as a plain picklable dict:
        ``{name: {kind, help, labelnames, [buckets,] children}}`` where
        ``children`` is a sorted list of ``[labelvalues, payload]`` pairs
        (the unlabeled family is one child keyed by ``()``)."""
        with self._lock:
            families = sorted(self._metrics.values(), key=lambda m: m.name)
        out: Dict[str, dict] = {}
        for m in families:
            if m.labelnames:
                with m._lock:
                    pairs = sorted(m._children.items())
                children = [(lv, c._payload()) for lv, c in pairs]
            else:
                children = [((), m._payload())]
            entry: dict = {
                "kind": m.kind,
                "help": m.help,
                "labelnames": list(m.labelnames),
                "children": children,
            }
            if isinstance(m, Histogram):
                entry["buckets"] = list(m.buckets)
            out[m.name] = entry
        return out

    def merge(self, other) -> "MetricsRegistry":
        """Fold ``other`` — a :class:`MetricsRegistry` or a
        :meth:`snapshot` dict — into this registry: counters add,
        histograms add bucket-wise (bucket schemas must match), gauges
        take the incoming value, and labeled families union their
        children.  Re-declaring a name as a different kind or label
        schema raises, exactly like the constructors.  Returns ``self``
        so merges chain."""
        snap = other.snapshot() if isinstance(other, MetricsRegistry) else other
        kinds = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
        for name in sorted(snap):
            entry = snap[name]
            cls = kinds[entry["kind"]]
            kw = {}
            if entry["kind"] == "histogram":
                kw["buckets"] = entry["buckets"]
            fam = self._get_or_make(
                cls, name, entry["help"], tuple(entry["labelnames"]), **kw
            )
            for labelvalues, payload in entry["children"]:
                child = (
                    fam.labels(*labelvalues) if fam.labelnames else fam
                )
                child._merge_payload(payload)
        return self

    # ------------------------------------------------------------------ #
    # exposition

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format — what a ``/metrics``
        scrape endpoint would serve for this registry."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in families:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for sample_name, labelvalues, value in m.samples():
                # histogram buckets smuggle the 'le' label via the
                # ("__le__", v) convention in Histogram.samples
                if "__le__" in labelvalues:
                    i = labelvalues.index("__le__")
                    names = m.labelnames + ("le",)
                    values = labelvalues[:i] + (labelvalues[i + 1],)
                else:
                    names, values = m.labelnames, labelvalues
                lines.append(
                    f"{sample_name}{_fmt_labels(names, values)} {_fmt_value(value)}"
                )
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> Dict[str, dict]:
        """One JSON document: {name: {kind, help, value|children}}."""
        with self._lock:
            families = sorted(self._metrics.values(), key=lambda m: m.name)
        return {
            m.name: {"kind": m.kind, "help": m.help, "value": m.to_json()}
            for m in families
        }

    def write(self, prom_path=None, json_path=None) -> None:
        if prom_path is not None:
            with open(prom_path, "w") as f:
                f.write(self.prometheus_text())
        if json_path is not None:
            with open(json_path, "w") as f:
                json.dump(self.to_json(), f, indent=2, sort_keys=True)


def exposition(registry: MetricsRegistry) -> Tuple[bytes, str]:
    """The registry rendered for a scrape endpoint (ISSUE 18): the text
    exposition encoded to bytes plus the content type a conforming
    ``GET /metrics`` response declares."""
    return registry.prometheus_text().encode("utf-8"), PROM_CONTENT_TYPE


def process_rss_bytes() -> float:
    """This process's resident set size, in bytes — /proc when the
    platform has one, ``ru_maxrss`` (a high-water mark, the closest
    portable stand-in) otherwise, 0.0 when neither is readable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
    except Exception:
        return 0.0


def process_gauges(registry: MetricsRegistry, *, clock=time.monotonic):
    """Arm the serving daemon's process self-gauges (ISSUE 18 satellite):
    ``process_uptime_seconds`` (seconds since this call, on ``clock``)
    and ``process_rss_bytes``.  Returns an ``update()`` closure that
    refreshes both (called once here, then by the daemon before every
    scrape).  Nothing registers until this is called — a registry that
    never serves stays byte-identical to before this function existed
    (pinned by tests/test_serve.py)."""
    uptime = registry.gauge(
        "process_uptime_seconds",
        "seconds this process has been serving",
    )
    rss = registry.gauge(
        "process_rss_bytes",
        "resident set size of this process (bytes)",
    )
    t0 = clock()

    def update() -> None:
        uptime.set(clock() - t0)
        rss.set(process_rss_bytes())

    update()
    return update


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """Process-wide default registry (tests construct their own)."""
    return _REGISTRY
