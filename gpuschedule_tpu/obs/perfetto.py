"""Perfetto/Chrome trace-event export of a replay's event stream.

Converts the engine's structured events (``MetricsLog.events`` /
``events.jsonl``) into the Chrome trace-event JSON format, loadable in
ui.perfetto.dev or chrome://tracing — the "watch a trace replay as a
timeline" half of the observability layer (ISSUE 1 tentpole):

- **one track per pod/slice**: events carry a ``track`` label derived from
  the granted allocation's geometry (``pod0/4x4@0,0`` for a TPU slice,
  ``gpu/s0n1`` for a GPU node set, ``pool`` for the flat cluster); each
  distinct label becomes a thread track, grouped into processes by its
  ``pod.../gpu/pool`` prefix;
- **one complete event ("ph":"X") per job occupancy interval**: a job
  occupies its track from ``start`` until the next ``preempt`` / ``migrate``
  / ``resize`` / ``finish`` boundary (migrate and resize close one interval
  and open the next, since the slice — or its size — changed);
- **instant events ("ph":"i")** for preempt / migrate / reject / revoke,
  pinned to the track the job occupied (rejects land on a dedicated
  admission track);
- **health tracks** (faults/): each fault scope gets a thread under the
  "health" process with a fault/repair instant pair and an "unhealthy"
  interval spanning the outage (overlapping outages on one scope nest
  FIFO; unrepaired ones extend to the horizon);
- **net tracks** (net/): each fabric link gets a thread under the "net"
  process with one utilization slice per constant-load interval (named
  by percentage); contention re-prices land as "net" instants on the
  affected job's occupancy track;
- scheduling-rationale payloads (the policies' ``why`` records) ride along
  in each slice's ``args``, so clicking an interval answers *which rule put
  this job here*.

Timestamps are simulated seconds scaled to microseconds — the exported
timeline is the *replay* clock.  Wall-clock span timelines (the tracer's)
are exported separately by ``Tracer.write_chrome``; the two clocks do not
pretend to share an axis.

Pure stdlib; streams from an events iterable, so a JSONL file at Philly
scale never needs to be held in memory alongside the output.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

_ADMISSION_TRACK = "admission"
_US = 1e6  # sim seconds -> trace microseconds

# Occupancy intervals close on preempt/finish/migrate/resize/rebind/revoke
# (migrate/resize/rebind also open the next one, carrying the post-move
# track/size); preempt/migrate/reject/revoke/fault/repair additionally emit
# instants.  Header records (no "event" key) and unknown kinds fall through
# harmlessly.  The dispatch lives in the trace_events elif chain below.


def track_label(detail: Any) -> str:
    """Human track name for an allocation's flavor-specific detail.

    Duck-typed on the detail dataclasses (SliceGeometry / MultiSliceGeometry
    / GpuPlacement / None) so the sim layer stays import-light."""
    if detail is None:
        return "pool"
    slices = getattr(detail, "slices", None)
    if slices is not None:  # multislice gang: one track spanning its pods
        return "dcn/" + "+".join(track_label(s) for s in slices)
    pod = getattr(detail, "pod", None)
    if pod is not None:
        shape = "x".join(str(s) for s in getattr(detail, "shape", ()))
        origin = ",".join(str(o) for o in getattr(detail, "origin", ()))
        return f"pod{pod}/{shape}@{origin}"
    nodes = getattr(detail, "nodes", None)
    if nodes is not None:  # GpuPlacement: (switch, node) ids
        return "gpu/" + "+".join(f"s{s}n{n}" for (s, n), _ in nodes)
    return str(detail)


def load_events_jsonl(path) -> Iterator[dict]:
    """Stream events back out of a ``MetricsLog`` JSONL file."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


class _TrackIds:
    """Stable (pid, tid) assignment: one process per track-name prefix
    (pod0, gpu, pool, dcn, admission), one thread per full track name."""

    def __init__(self):
        self._pids: Dict[str, int] = {}
        self._tids: Dict[str, Tuple[int, int]] = {}
        self.meta: List[dict] = []

    def ids(self, track: str) -> Tuple[int, int]:
        got = self._tids.get(track)
        if got is not None:
            return got
        group = track.split("/", 1)[0]
        pid = self._pids.get(group)
        if pid is None:
            pid = len(self._pids) + 1
            self._pids[group] = pid
            self.meta.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": group},
            })
        tid = sum(1 for t in self._tids if t.split("/", 1)[0] == group) + 1
        self._tids[track] = (pid, tid)
        self.meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": track},
        })
        return pid, tid


def trace_events(events: Iterable[dict]) -> List[dict]:
    """Convert an ordered event stream into Chrome trace events (without the
    enclosing document).  Metadata records lead, then timed records sorted by
    ``ts`` (the input stream is time-ordered by construction; a defensive
    sort keeps the output valid even for hand-edited streams)."""
    ids = _TrackIds()
    timed: List[dict] = []
    # job -> (track, start_ts_us, args) for the open occupancy interval
    open_iv: Dict[str, Tuple[str, float, dict]] = {}
    # net/ link -> (start_ts_us, args) for the open utilization slice
    open_net: Dict[str, Tuple[float, dict]] = {}
    # fault track (health/<scope> or domain/<scope>) -> open outages as
    # (start_ts_us, args) entries.
    # Engine-emitted events carry a per-record "fid" so a repair closes ITS
    # outage even when outages of different durations overlap on one scope;
    # fid-less streams (hand-edited) fall back to oldest-first pairing.
    open_health: Dict[str, List[Tuple[float, dict]]] = {}
    t_last = 0.0

    def close(job: str, t_us: float, note: Optional[str] = None) -> None:
        iv = open_iv.pop(job, None)
        if iv is None:
            return
        track, t0, args = iv
        if note is not None:
            args = {**args, "ended_by": note}
        pid, tid = ids.ids(track)
        timed.append({
            "name": job, "cat": "occupancy", "ph": "X",
            "ts": t0, "dur": max(0.0, t_us - t0),
            "pid": pid, "tid": tid, "args": args,
        })

    def instant(name: str, track: str, t_us: float, args: dict) -> None:
        pid, tid = ids.ids(track)
        timed.append({
            "name": name, "cat": "transition", "ph": "i", "s": "t",
            "ts": t_us, "pid": pid, "tid": tid, "args": args,
        })

    def close_net(track: str, t_us: float) -> None:
        """Close one link's open utilization slice (net/ tracks: one
        slice per constant-utilization interval, named by percentage)."""
        iv = open_net.pop(track, None)
        if iv is None:
            return
        t0, args = iv
        pid, tid = ids.ids(track)
        timed.append({
            "name": f"{100.0 * float(args.get('util', 0.0)):.0f}%",
            "cat": "net", "ph": "X",
            "ts": t0, "dur": max(0.0, t_us - t0),
            "pid": pid, "tid": tid, "args": args,
        })

    for ev in events:
        kind = ev.get("event")
        t_us = float(ev.get("t", 0.0)) * _US
        t_last = max(t_last, t_us)
        job = ev.get("job", "?")
        extra = {
            k: v for k, v in ev.items() if k not in ("event", "t", "job", "track")
        }
        if kind == "start":
            close(job, t_us, "restart")  # defensive: stream said start twice
            track = ev.get("track") or f"job/{job}"
            open_iv[job] = (track, t_us, extra)
        elif kind in ("migrate", "resize", "rebind"):
            iv = open_iv.get(job)
            old_track = iv[0] if iv else ev.get("track") or f"job/{job}"
            close(job, t_us, kind)
            if kind == "migrate":
                instant("migrate", old_track, t_us, extra)
            new_track = ev.get("track") or old_track
            args = dict(iv[2]) if iv else {}
            args.update(extra)
            open_iv[job] = (new_track, t_us, args)
        elif kind in ("preempt", "revoke"):
            iv = open_iv.get(job)
            track = iv[0] if iv else f"job/{job}"
            close(job, t_us, kind)
            instant(kind, track, t_us, extra)
        elif kind == "finish":
            close(job, t_us, ev.get("end_state", "finish"))
        elif kind == "reject":
            instant("reject", _ADMISSION_TRACK, t_us, extra)
        elif kind in ("fault", "repair"):
            # unhealthy-interval tracks: one thread per fault scope under
            # the "health" process, an X slice per outage.  Correlated
            # domain outages (ISSUE 6) get their own "domain" process so
            # the blast-radius hierarchy reads as one track group.
            label = str(ev.get("scope", "?"))
            group = "domain" if ev.get("fault") == "domain" else "health"
            track = f"{group}/{label}"
            instant(kind, track, t_us, extra)
            if kind == "fault":
                open_health.setdefault(track, []).append((t_us, extra))
            else:
                stack = open_health.get(track)
                if stack:
                    fid = extra.get("fid")
                    at = next(
                        (i for i, (_, a) in enumerate(stack)
                         if fid is not None and a.get("fid") == fid),
                        0,
                    )
                    h0, args = stack.pop(at)
                    pid, tid = ids.ids(track)
                    timed.append({
                        "name": "unhealthy", "cat": "health", "ph": "X",
                        "ts": h0, "dur": max(0.0, t_us - h0),
                        "pid": pid, "tid": tid, "args": args,
                    })
        elif kind in ("net", "slow", "warn", "reroute"):
            # contention re-price / straggler re-price / spot pre-revoke
            # notice / adaptive-routing route change: instants on the
            # job's occupancy track
            iv = open_iv.get(job)
            instant(kind, iv[0] if iv else f"job/{job}", t_us, extra)
        elif kind == "netlink":
            # per-link utilization slices: one thread per fabric link
            # under the "net" process, a slice per constant-load interval
            track = f"net/{ev.get('link', '?')}"
            close_net(track, t_us)
            open_net[track] = (t_us, extra)
        elif kind == "sample":
            # periodic cluster samples (ISSUE 5) become counter tracks
            # ("ph":"C") under a "cluster" process: physical occupancy
            # (used + health-masked chips stack) and queue depth — the
            # two signals ui.perfetto.dev graphs as area charts above
            # the per-pod occupancy timelines
            pid, tid = ids.ids("cluster/occupancy")
            timed.append({
                "name": "physical chips", "cat": "sample", "ph": "C",
                "ts": t_us, "pid": pid, "tid": tid,
                "args": {
                    "used": ev.get("used", 0),
                    "unhealthy": ev.get("unhealthy", 0),
                },
            })
            pid, tid = ids.ids("cluster/queue")
            timed.append({
                "name": "pending jobs", "cat": "sample", "ph": "C",
                "ts": t_us, "pid": pid, "tid": tid,
                "args": {"pending": ev.get("pending", 0)},
            })
            pods = ev.get("pods")
            if pods and any("hazard" in p for p in pods):
                # per-pod hazard health track (ISSUE 15): present only on
                # hazard-armed captures, so historical traces are
                # byte-identical
                pid, tid = ids.ids("cluster/hazard")
                timed.append({
                    "name": "pod hazard", "cat": "sample", "ph": "C",
                    "ts": t_us, "pid": pid, "tid": tid,
                    "args": {
                        f"pod{i}": float(p.get("hazard", 0.0))
                        for i, p in enumerate(pods)
                    },
                })
        # arrival / speed / rationale-only events carry no timeline geometry

    # horizon cutoff: unfinished occupancies and unrepaired outages extend
    # to the last seen time
    for job in list(open_iv):
        close(job, t_last, "horizon")
    for track in list(open_net):
        close_net(track, t_last)
    for track, stack in open_health.items():
        pid, tid = ids.ids(track)
        for h0, args in stack:
            timed.append({
                "name": "unhealthy", "cat": "health", "ph": "X",
                "ts": h0, "dur": max(0.0, t_last - h0),
                "pid": pid, "tid": tid,
                "args": {**args, "ended_by": "horizon"},
            })

    timed.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "X" else 1))
    return ids.meta + timed


def fleet_trace_events(
    parent_events: List[dict],
    workers: Dict[str, List[dict]],
    *,
    parent_name: str = "parent",
) -> List[dict]:
    """Merge parent-side and per-worker span events into one Chrome trace
    event list with process/thread metadata records (ISSUE 16): the parent
    is pid 1 (named ``parent_name``), each worker key gets its own named
    pid in sorted-key order, and every timed event is stamped with its
    process ids and globally re-sorted by ts — the shape ui.perfetto.dev
    renders as one fleet timeline with a named track per worker.

    Each process's ``ts`` values are on its own wall anchor (the standard
    multi-process Chrome-trace situation); within a process the layout is
    real.  The output is a pure function of the inputs: worker keys sort,
    ties break on (ts, pid, tid, name), so adversarial completion order
    upstream cannot change a byte here.
    """
    meta: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": parent_name}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "spans"}},
    ]
    timed: List[dict] = []
    for e in parent_events:
        timed.append({**e, "pid": 1, "tid": 1})
    for i, key in enumerate(sorted(workers)):
        pid = i + 2
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": key}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 1, "args": {"name": "spans"}})
        for e in workers[key]:
            timed.append({**e, "pid": pid, "tid": 1})
    timed.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"]))
    return meta + timed


def export_chrome_trace(events: Iterable[dict], out_path) -> dict:
    """Write ``events`` as a Chrome trace-event JSON document; returns the
    document (handy for tests)."""
    doc = {
        "traceEvents": trace_events(events),
        "displayTimeUnit": "ms",
        "otherData": {"clock": "simulated", "exporter": "gpuschedule_tpu.obs"},
    }
    out = Path(out_path)
    if out.parent and not out.parent.exists():
        out.parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(doc, f)
    return doc


def validate_chrome_trace(doc: dict) -> List[str]:
    """Schema sanity: returns a list of violations (empty = valid).  The
    checks mirror what ui.perfetto.dev's importer requires: the traceEvents
    array, per-event phase/ts/pid/tid fields, non-negative durations, and
    time-ordered timed events."""
    problems: List[str] = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    last_ts = None
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"[{i}] not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "M", "C", "b", "e"):
            problems.append(f"[{i}] unknown phase {ph!r}")
            continue
        if not isinstance(e.get("name"), str):
            problems.append(f"[{i}] name missing")
        if ph == "M":
            continue
        for k in ("ts", "pid", "tid"):
            if not isinstance(e.get(k), (int, float)):
                problems.append(f"[{i}] {k} missing/non-numeric")
        if ph == "X" and (not isinstance(e.get("dur"), (int, float)) or e["dur"] < 0):
            problems.append(f"[{i}] complete event needs dur >= 0")
        if ph == "i" and e.get("s") not in (None, "t", "p", "g"):
            problems.append(f"[{i}] bad instant scope {e.get('s')!r}")
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            if ts < 0:
                problems.append(f"[{i}] negative ts")
            if last_ts is not None and ts < last_ts:
                problems.append(f"[{i}] ts decreases ({ts} < {last_ts})")
            last_ts = ts
    return problems
