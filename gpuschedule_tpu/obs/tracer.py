"""Span tracer: nested wall/sim-time spans with a zero-cost disabled path.

The observability layer's timing primitive (ISSUE 1 tentpole): a span is a
context-managed interval with a name, a category, arbitrary attributes, and
*two* clocks — wall time (``time.perf_counter``) always, and simulated time
when the caller supplies it (the engine passes ``sim.now`` so a span over a
policy invocation can be placed on the replay timeline as well as the wall
one).  Spans nest: each thread keeps its own depth stack, so concurrent
harness runs and the single-threaded sim engine share one tracer safely.

Cost model (the ``tools/check_overhead.py`` contract):

- **disabled** (the default): every instrumented call site either checks
  ``tracer.enabled`` (one attribute load) or receives the shared
  :data:`NULL_SPAN`, whose ``__enter__``/``__exit__``/``set`` are empty
  methods on a singleton — no allocation, no locking, no clock read;
- **enabled**: one ``perf_counter`` pair, one small ``Span`` object, and one
  lock-guarded list append per span.

The tracer is honest about what it cannot see: it times *host-side* code.
Device-side step timing still goes through the profiler harness's readback
fences (profiler/harness.py module docstring); the train-loop spans record
the fenced wall time the harness recipe produces.

Enable programmatically (``get_tracer().enable()``), via the CLI ``run
--spans`` flag, or with ``GSTPU_TRACE=1`` in the environment (picked up at
import, so library entry points inherit it without plumbing).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Span:
    """One finished (or in-flight) traced interval."""

    name: str
    cat: str = ""
    wall_start: float = 0.0          # perf_counter seconds, tracer-origin relative
    wall_dur: float = 0.0
    sim_start: Optional[float] = None   # simulated seconds, when the caller has a sim clock
    sim_end: Optional[float] = None
    depth: int = 0                   # nesting level within the opening thread
    thread: int = 0                  # opening thread ident
    attrs: Dict[str, Any] = field(default_factory=dict)

    def set(self, **attrs) -> "Span":
        """Attach attributes mid-span (e.g. a result computed inside it)."""
        self.attrs.update(attrs)
        return self

    def end_sim(self, sim_now: float) -> "Span":
        """Stamp the simulated-clock end (wall end is stamped by ``__exit__``)."""
        self.sim_end = sim_now
        return self


class _NullSpan:
    """Shared no-op span handed out while tracing is disabled.

    Supports the full :class:`Span` surface so instrumented code never
    branches on enablement beyond the initial ``tracer.span(...)`` call.
    """

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self

    def end_sim(self, sim_now: float) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Context manager binding one live Span to the tracer's thread stack."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        tl = self._tracer._tl
        depth = getattr(tl, "depth", 0)
        self.span.depth = depth
        tl.depth = depth + 1
        self.span.wall_start = time.perf_counter() - self._tracer._origin
        return self.span

    def __exit__(self, *exc) -> bool:
        sp = self.span
        sp.wall_dur = (time.perf_counter() - self._tracer._origin) - sp.wall_start
        tl = self._tracer._tl
        tl.depth = max(0, getattr(tl, "depth", 1) - 1)
        self._tracer._append(sp)
        return False


class Tracer:
    """Collects spans; a process-wide singleton lives behind :func:`get_tracer`.

    ``enabled`` is the single switch every instrumented call site keys on.
    """

    def __init__(self, *, enabled: bool = False,
                 origin: Optional[float] = None):
        self.enabled = bool(enabled)
        # ``origin`` lets several tracers in one process share a wall
        # anchor (the fleet layer gives every per-task child tracer the
        # worker process's first-task origin, so a worker's tasks lay
        # out sequentially on its Perfetto track instead of stacking at
        # ts=0); default: wall_start=0 is tracer creation.
        self._origin = time.perf_counter() if origin is None else origin
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._tl = threading.local()

    # ------------------------------------------------------------------ #
    # control

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def reset(self) -> "Tracer":
        """Drop collected spans and re-anchor the wall origin."""
        with self._lock:
            self._spans = []
        self._origin = time.perf_counter()
        return self

    # ------------------------------------------------------------------ #
    # recording

    def span(self, name: str, *, cat: str = "", sim_now: Optional[float] = None, **attrs):
        """Open a span as a context manager; returns :data:`NULL_SPAN` when
        disabled so the call site stays branch-free."""
        if not self.enabled:
            return NULL_SPAN
        return _SpanCtx(
            self,
            Span(
                name=name,
                cat=cat,
                sim_start=sim_now,
                thread=threading.get_ident(),
                attrs=dict(attrs) if attrs else {},
            ),
        )

    def record(
        self,
        name: str,
        *,
        wall_start: float,
        wall_dur: float,
        cat: str = "",
        sim_now: Optional[float] = None,
        **attrs,
    ) -> Optional[Span]:
        """Record a span measured externally (post-hoc), e.g. a fenced train
        step whose wall interval the caller timed itself.  ``wall_start`` is
        an absolute ``perf_counter`` reading; it is re-based to the tracer
        origin.  No-op (returns None) when disabled."""
        if not self.enabled:
            return None
        sp = Span(
            name=name,
            cat=cat,
            wall_start=wall_start - self._origin,
            wall_dur=wall_dur,
            sim_start=sim_now,
            depth=getattr(self._tl, "depth", 0),
            thread=threading.get_ident(),
            attrs=dict(attrs) if attrs else {},
        )
        self._append(sp)
        return sp

    def _append(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    # ------------------------------------------------------------------ #
    # readout

    @property
    def spans(self) -> List[Span]:
        """Snapshot of finished spans (copy: safe to iterate while tracing)."""
        with self._lock:
            return list(self._spans)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregate: count, total/mean/max wall seconds."""
        agg: Dict[str, Dict[str, float]] = {}
        for sp in self.spans:
            a = agg.setdefault(
                sp.name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            a["count"] += 1
            a["total_s"] += sp.wall_dur
            a["max_s"] = max(a["max_s"], sp.wall_dur)
        for a in agg.values():
            a["mean_s"] = a["total_s"] / a["count"] if a["count"] else 0.0
        return agg

    def chrome_events(self) -> List[dict]:
        """Spans as Chrome trace-event dicts on the wall-clock timeline
        (``ts`` in microseconds since the tracer origin), one ``tid`` per
        opening thread.  Complements the sim-timeline export in
        obs/perfetto.py — the two clocks stay on separate timelines rather
        than pretending to share one."""
        tids: Dict[int, int] = {}
        out: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "tracer (wall clock)"}},
        ]
        # spans are collected in close order (inner before outer); the trace
        # format wants begin order, and validate_chrome_trace checks ts is
        # non-decreasing
        for sp in sorted(self.spans, key=lambda s: s.wall_start):
            tid = tids.setdefault(sp.thread, len(tids) + 1)
            args = dict(sp.attrs)
            if sp.sim_start is not None:
                args["sim_start_s"] = sp.sim_start
            if sp.sim_end is not None:
                args["sim_end_s"] = sp.sim_end
            out.append({
                "name": sp.name,
                "cat": sp.cat or "span",
                "ph": "X",
                "ts": round(sp.wall_start * 1e6, 3),
                "dur": round(sp.wall_dur * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": args,
            })
        for thread, tid in tids.items():
            out.insert(1, {"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": f"thread-{thread}"}})
        return out

    def write_chrome(self, path) -> str:
        """Write the wall-clock span timeline as a ui.perfetto.dev-loadable
        JSON file; returns the path."""
        doc = {"traceEvents": self.chrome_events(), "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(doc, f)
        return str(path)


_TRACER = Tracer(
    enabled=os.environ.get("GSTPU_TRACE", "").strip().lower()
    not in ("", "0", "false", "no", "off")
)


def get_tracer() -> Tracer:
    """The process-wide tracer singleton every subsystem instruments against."""
    return _TRACER
