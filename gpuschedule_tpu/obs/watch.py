"""Live-tail watchtower (ISSUE 15 tentpole): streaming detectors, SLO
burn-rate alerts, and a flight recorder over an events.jsonl stream.

Every earlier analytics surface is post-hoc — ``analyze``/``report``/
``compare`` read a *finished* stream.  The paper's operational setting
(Philly-style fleet operation) is continuous monitoring of a live
cluster; this module is that loop: an **incremental** analyzer that
tails a (possibly still growing) stream, maintains O(active-jobs)
rolling-window state, and evaluates a declarative detector set at every
sim-time window boundary:

- ``queue-depth-surge`` — pending depth both deep and sharply up within
  one window;
- ``goodput-collapse`` — the cluster's work velocity (sum of running
  effective rates, piecewise-exact) falls below a fraction of its own
  trailing baseline while demand remains;
- ``frag-creep`` — fragmentation (from ``sample`` records) above a
  threshold for N consecutive windows;
- ``hazard-spike`` — any pod's hazard score (hazard-armed ``sample``
  records, ISSUE 15 satellite) past a threshold;
- ``slo-burn`` — multi-window SLO burn-rate alerting à la SRE: the
  queueing-delay SLO's error budget burning faster than ``fast_burn``
  over the last window AND faster than ``slow_burn`` over the trailing
  slow window, so a blip neither pages nor hides a slow leak.

Detections are **latched** (rising-edge): a detector fires once when its
condition becomes true and re-arms only after a window where it is
false, so a persistent outage is one alert, not one per window.

Every alert lands in four places: the **side stream** (schema-additive
``alert`` records behind their own versioned header — docs/events.md),
one PR-10 **history row** (kind ``watch``, label = detector), the
labeled ``watch_alerts_total{detector}`` **registry family**, and — when
a flight recorder is armed — a **ring-buffer dump** of the last N raw
events plus a pin of the watched run's nearest periodic engine snapshot
(``run --snapshot``; the ``<snapshot>.meta.json`` sidecar names its sim
instant), so ``whatif`` can immediately restore and replay the minutes
before the incident.

Determinism contract (pinned by tests/test_watch.py): the alert sequence
is a pure function of (record sequence, rules) — byte-identical across
one-shot batch, ``--replay`` (paced as-if-live by sim time), and
``--follow`` (polling a growing file in arbitrary chunks, including
mid-record truncated tails, which the shared
:class:`~gpuschedule_tpu.obs.analyze.StreamCursor` retains and re-reads
whole).  Wall clocks pace delivery only; alert content derives from sim
time alone.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import shutil
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from gpuschedule_tpu.obs.analyze import (
    RunHeader,
    StreamCursor,
    StreamError,
    iter_jsonl_items,
)
from gpuschedule_tpu.obs.metrics import exact_quantile

# Version of the alert side-stream schema (independent of the main event
# stream's EVENT_SCHEMA: the two streams version separately — an alert
# payload change must not force re-capturing replay streams, and vice
# versa; docs/events.md records the policy).
ALERTS_SCHEMA = 1

DETECTORS = (
    "queue-depth-surge",
    "goodput-collapse",
    "frag-creep",
    "hazard-spike",
    "slo-burn",
)

# Alert severities: "page" for the two failure modes that demand a human
# now (work is not getting done / the SLO budget is burning at both
# horizons), "ticket" for the creeping kinds.
_SEVERITY = {
    "queue-depth-surge": "ticket",
    "goodput-collapse": "page",
    "frag-creep": "ticket",
    "hazard-spike": "ticket",
    "slo-burn": "page",
}

# The declarative detector config (`watch --rules rules.json`): operators
# tune thresholds without code.  Omitting a detector key (or setting it
# to false/null) disables that detector; unknown detectors or knob names
# are rejected at load, not silently ignored.
DEFAULT_RULES: dict = {
    "window_s": 300.0,
    # trailing windows feeding the goodput-collapse baseline (windows
    # spent in an active collapse are excluded, so the baseline does not
    # decay toward the outage it is measuring)
    "baseline_windows": 6,
    # flight-recorder ring size (raw events kept for the incident dump)
    "ring": 512,
    "detectors": {
        "queue-depth-surge": {"min_pending": 8.0, "surge_factor": 2.0},
        "goodput-collapse": {"collapse_frac": 0.5, "min_velocity": 0.05},
        "frag-creep": {"frag_threshold": 0.5, "windows": 3},
        "hazard-spike": {"hazard_threshold": 1.0},
        "slo-burn": {
            "wait_slo_s": 3600.0,
            "target": 0.95,
            "fast_burn": 10.0,
            "slow_burn": 2.0,
            "slow_windows": 12,
        },
    },
}


def load_rules(source=None) -> dict:
    """The effective rules dict: :data:`DEFAULT_RULES` overlaid with a
    JSON file (path) or a dict.  Unknown top-level keys, unknown
    detector names, unknown knob names, and non-positive windows are
    rejected — a typo'd threshold must not silently run the defaults."""
    rules = copy.deepcopy(DEFAULT_RULES)
    if source is None:
        return rules
    if isinstance(source, (str, Path)):
        try:
            doc = json.loads(Path(source).read_text())
        except OSError as e:
            raise ValueError(f"cannot read rules file {source}: {e}") from None
        except json.JSONDecodeError as e:
            raise ValueError(f"rules file {source} is not JSON: {e}") from None
    else:
        doc = source
    if not isinstance(doc, dict):
        raise ValueError("rules must be a JSON object")
    unknown = sorted(set(doc) - set(DEFAULT_RULES))
    if unknown:
        raise ValueError(
            f"unknown rules keys {unknown}; known: {sorted(DEFAULT_RULES)}"
        )
    if "window_s" in doc:
        v = float(doc["window_s"])
        if not v > 0:
            raise ValueError(f"rules.window_s must be > 0, got {doc['window_s']}")
        rules["window_s"] = v
    for key in ("baseline_windows", "ring"):
        if key in doc:
            # whole windows/records only: int(0.5) would silently yield
            # 0 and disable the detector/recorder the knob configures
            v = doc[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"rules.{key} must be an integer >= 1, got {v!r}"
                )
            rules[key] = v
    dets = doc.get("detectors")
    if dets is not None:
        if not isinstance(dets, dict):
            raise ValueError("rules.detectors must be an object")
        bad = sorted(set(dets) - set(DETECTORS))
        if bad:
            raise ValueError(
                f"unknown detectors {bad}; known: {sorted(DETECTORS)}"
            )
        for name in sorted(dets):
            cfg = dets[name]
            if cfg in (None, False):
                rules["detectors"].pop(name, None)
                continue
            if not isinstance(cfg, dict):
                raise ValueError(
                    f"rules.detectors[{name!r}] must be an object, "
                    "false, or null"
                )
            base = dict(DEFAULT_RULES["detectors"][name])
            bad_keys = sorted(set(cfg) - set(base))
            if bad_keys:
                raise ValueError(
                    f"unknown keys {bad_keys} for detector {name!r}; "
                    f"known: {sorted(base)}"
                )
            for k in sorted(cfg):
                base[k] = float(cfg[k])
            rules["detectors"][name] = base
    return rules


def rules_digest(rules: dict) -> str:
    """Stable 12-hex digest of the effective rules (sorted-key JSON) —
    stamped into the side-stream header so an alert sequence is
    auditable against the exact thresholds that produced it."""
    blob = json.dumps(rules, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


# --------------------------------------------------------------------- #
# the alert side stream


class AlertStream:
    """The alert side stream: JSONL records in the main stream's shape
    (``{"t", "event", ...}``) behind their OWN versioned header
    (``{"schema": ALERTS_SCHEMA, "stream": "alerts", ...}``), flushed
    per record (alerts are rare and a tailing pager must see them now).
    With no path, records are only collected in memory.

    **Pluggable sinks** (ISSUE 18): :meth:`subscribe` registers an
    in-memory callback invoked with every record the instant it is
    written — the serving daemon's SSE fan-out attaches here and sees
    exactly the record sequence the file tee would, without a file tee.
    Sinks are delivery only: they must not mutate the record, and the
    written sequence never depends on who is subscribed."""

    def __init__(self, path=None, *, sinks=()):
        self.records: List[dict] = []
        self._sinks = list(sinks)
        self._fh = None
        if path is not None:
            p = Path(path)
            if p.parent and not p.parent.exists():
                p.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(p, "w")

    def subscribe(self, sink) -> None:
        """Attach one callback (``sink(rec)``) to every future write."""
        self._sinks.append(sink)

    def write_header(self, meta: dict) -> None:
        self._write({"schema": ALERTS_SCHEMA, "stream": "alerts", **meta})

    def event(self, kind: str, t: float, job=None, **extra) -> dict:
        """One side-stream record (mirrors ``MetricsLog.event``'s
        signature so the contract linter's GS3xx schema rules cover this
        emitter exactly like the engine's)."""
        rec: dict = {"t": t, "event": kind}
        if job is not None:
            rec["job"] = job
        rec.update(extra)
        self._write(rec)
        return rec

    def _write(self, rec: dict) -> None:
        self.records.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        for sink in self._sinks:
            sink(rec)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


# --------------------------------------------------------------------- #
# rolling per-job state


@dataclass
class _WJob:
    """One active job's rolling state (the O(active-jobs) part)."""

    chips: int
    submit_t: float
    state: str = "queued"          # queued | running | suspended
    cause: Optional[str] = None    # open wait interval's blame (ISSUE 5)
    alloc: int = 0
    speed: float = 0.0
    loc: float = 1.0
    static_loc: float = 1.0
    slow: float = 1.0
    gpu: bool = False
    started: bool = False


class Watcher:
    """The incremental analyzer: feed records (in stream order), collect
    alerts.  Evaluation happens at sim-time window boundaries only, so
    the alert sequence is a pure function of (records, rules) whatever
    wall-clock cadence delivered them."""

    def __init__(
        self,
        rules: Optional[dict] = None,
        *,
        alerts: Optional[AlertStream] = None,
        flight_dir=None,
        snapshot=None,
        registry=None,
        history=None,
        source: str = "",
    ):
        self.rules = rules if rules is not None else load_rules()
        self.w = float(self.rules["window_s"])
        self.sink = alerts if alerts is not None else AlertStream()
        self.flight_dir = Path(flight_dir) if flight_dir else None
        self.snapshot = Path(snapshot) if snapshot else None
        self._history = history
        self._reg_alerts = None
        if registry is not None:
            self._reg_alerts = registry.counter(
                "watch_alerts_total",
                "watchtower detections by detector (ISSUE 15)",
                labelnames=("detector",),
            )
        self.header: Optional[RunHeader] = None
        self.source = source
        self._header_out = False

        # stream-wide state
        self.ring: deque = deque(maxlen=int(self.rules["ring"]))
        self.n_events = 0
        self.end_t = 0.0
        self.anomalies = 0
        self.counts: Dict[str, int] = {}
        self.alerts: List[dict] = []
        self.alert_counts: Dict[str, int] = {}
        self._seq = 0

        # O(active) job state + aggregate rates (piecewise-constant
        # between records; every mutation goes rates-off -> edit ->
        # rates-on, so the aggregates track the active set exactly)
        self._jobs: Dict[str, _WJob] = {}
        self._used = 0
        self._running = 0
        self._pending = 0
        self._vel = 0.0          # sum of running effective rates
        self._toll_rate = 0.0    # speed x (1 - static_loc), TPU multislice
        self._gpu_rate = 0.0     # speed x (1 - static_loc), GPU gangs
        self._cont_rate = 0.0    # speed x (static_loc - loc): DCN contention
        self._strag_rate = 0.0   # speed x loc x (1 - slow)
        self._share_rate = 0.0   # (1 - speed)
        self._cause_n: Dict[str, int] = {}  # waiting jobs per blame cause

        # window accumulators (reset at each boundary)
        self._wend: Optional[float] = None
        self._last_t: Optional[float] = None
        self._occ_int = 0.0
        self._pend_int = 0.0
        self._vel_int = 0.0
        self._leg_int: Dict[str, float] = {}
        self._wait_int: Dict[str, float] = {}
        self._win_waits: List[float] = []
        self._win_breached = 0
        self._win_lost = 0.0
        self._win_revocations = 0
        self._win_faults = 0
        self._win_frag: Optional[float] = None
        self._win_hazard: Optional[float] = None
        self._win_pend_start = 0

        # trailing-window memory
        self._vel_hist: deque = deque(maxlen=int(self.rules["baseline_windows"]))
        slo = self.rules["detectors"].get("slo-burn") or {}
        self._slo_hist: deque = deque(maxlen=int(slo.get("slow_windows", 12)))
        # sample observations are piecewise-constant signals: a window
        # containing no `sample` record (capture's --sample-interval
        # longer than — or misaligned with — window_s) HOLDS the last
        # observation instead of reading as healthy, else frag-creep /
        # hazard-spike go silently dead under coarse sampling
        self._frag_held: Optional[float] = None
        self._hazard_held: Optional[float] = None
        self._frag_streak = 0
        self._active_alerts: set = set()
        self.windows = 0

    # ------------------------------------------------------------------ #
    # aggregate-rate bookkeeping

    def _rates(self, j: _WJob, sign: float) -> None:
        self._vel += sign * j.speed * j.loc * j.slow
        if j.speed != 1.0:
            self._share_rate += sign * (1.0 - j.speed)
        if j.static_loc != 1.0:
            amt = sign * j.speed * (1.0 - j.static_loc)
            if j.gpu:
                self._gpu_rate += amt
            else:
                self._toll_rate += amt
        if j.loc != j.static_loc:
            self._cont_rate += sign * j.speed * (j.static_loc - j.loc)
        if j.slow != 1.0:
            self._strag_rate += sign * j.speed * j.loc * (1.0 - j.slow)

    def _cause(self, j: _WJob, cause: Optional[str]) -> None:
        """Move a waiting job's open blame cause (attribution-armed
        streams carry it on arrival/preempt/revoke; bare streams bucket
        under 'unattributed')."""
        if j.cause is not None:
            self._cause_n[j.cause] = self._cause_n.get(j.cause, 0) - 1
        j.cause = cause
        if cause is not None:
            self._cause_n[cause] = self._cause_n.get(cause, 0) + 1

    def _integrate(self, t: float) -> None:
        last = self._last_t
        if last is None:
            self._last_t = t
            return
        dt = t - last
        if dt <= 0.0:
            return
        self._occ_int += self._used * dt
        self._pend_int += self._pending * dt
        self._vel_int += self._vel * dt
        li = self._leg_int
        if self._cont_rate:
            li["dcn-contention"] = li.get("dcn-contention", 0.0) + self._cont_rate * dt
        if self._toll_rate:
            li["multislice-toll"] = li.get("multislice-toll", 0.0) + self._toll_rate * dt
        if self._gpu_rate:
            li["gpu-locality"] = li.get("gpu-locality", 0.0) + self._gpu_rate * dt
        if self._strag_rate:
            li["straggler"] = li.get("straggler", 0.0) + self._strag_rate * dt
        if self._share_rate > 0.0:
            li["policy-share"] = li.get("policy-share", 0.0) + self._share_rate * dt
        wi = self._wait_int
        for cause in sorted(self._cause_n):
            n = self._cause_n[cause]
            if n > 0:
                wi[cause] = wi.get(cause, 0.0) + n * dt
        self._last_t = t

    # ------------------------------------------------------------------ #
    # record ingestion

    def feed(self, rec: dict, raw: Optional[str] = None) -> List[dict]:
        """Absorb one stream record; returns the alerts any window
        boundaries it crossed fired (possibly empty)."""
        self.ring.append(raw if raw is not None else json.dumps(rec))
        if "schema" in rec and "event" not in rec:
            # identity header: adopt, but never refuse — the watchtower
            # is an operator tool and bare streams must still watch
            try:
                self.header = RunHeader.from_record(rec)
            except ValueError:
                self.anomalies += 1
            return []
        kind = rec.get("event")
        if kind is None:
            self.anomalies += 1
            return []
        t = float(rec.get("t", 0.0))
        fired = self._advance_to(t)
        self.n_events += 1
        self.end_t = max(self.end_t, t)
        self.counts[kind] = self.counts.get(kind, 0) + 1
        self._apply(kind, rec, t)
        return fired

    def _advance_to(self, t: float) -> List[dict]:
        if self._wend is None:
            # windows anchor at sim t=0 whatever the first record's time,
            # so two watchers of one stream agree on every boundary
            self._wend = self.w
            while self._wend <= t - self.w:
                self._wend += self.w  # skip genuinely empty leading span
            self._last_t = min(t, self._wend - self.w)
        fired: List[dict] = []
        while t >= self._wend:
            self._integrate(self._wend)
            fired.extend(self._close_window(self._wend))
            self._wend += self.w
        self._integrate(t)
        return fired

    def _get(self, rec: dict) -> Optional[_WJob]:
        j = self._jobs.get(rec.get("job"))
        if j is None:
            self.anomalies += 1
        return j

    def _apply(self, kind: str, rec: dict, t: float) -> None:
        if kind == "arrival":
            job_id = rec.get("job")
            if job_id is None or job_id in self._jobs:
                self.anomalies += 1
                return
            j = _WJob(chips=int(rec.get("chips", 0)), submit_t=t)
            self._jobs[job_id] = j
            self._pending += 1
            self._cause(j, rec.get("cause") or "unattributed")
        elif kind == "start":
            j = self._get(rec)
            if j is None or j.state == "running":
                return
            self._cause(j, None)
            j.state = "running"
            j.alloc = int(rec.get("chips", j.chips))
            j.speed = float(rec.get("speed", 1.0))
            j.loc = float(rec.get("locality", 1.0))
            j.static_loc = j.loc
            j.gpu = str(rec.get("track", "")).startswith("gpu/")
            j.slow = float(rec.get("slow_factor", 1.0))
            self._used += j.alloc
            self._running += 1
            self._pending -= 1
            self._rates(j, +1.0)
            if not j.started:
                j.started = True
                wait = t - j.submit_t
                self._win_waits.append(wait)
                slo = self.rules["detectors"].get("slo-burn")
                if slo is not None and wait > slo["wait_slo_s"]:
                    self._win_breached += 1
        elif kind in ("preempt", "revoke"):
            j = self._get(rec)
            if j is None or j.state != "running":
                return
            self._rates(j, -1.0)
            self._used -= j.alloc
            self._running -= 1
            self._pending += 1
            j.alloc = 0
            j.speed = 0.0
            j.loc = j.static_loc = j.slow = 1.0
            j.state = (
                "suspended"
                if kind == "preempt" and rec.get("suspend", True)
                else "queued"
            )
            self._cause(j, rec.get("cause") or "unattributed")
            if kind == "revoke":
                self._win_revocations += 1
                self._win_lost += float(rec.get("lost_work", 0.0))
        elif kind in ("finish", "cutoff"):
            # cutoff is a horizon-terminal record: for the watcher both
            # mean "this job leaves the rolling state for good"
            j = self._get(rec)
            if j is None:
                return
            if j.state == "running":
                self._rates(j, -1.0)
                self._used -= j.alloc
                self._running -= 1
            else:
                self._pending -= 1
                self._cause(j, None)
            del self._jobs[rec["job"]]
        elif kind == "speed":
            j = self._get(rec)
            if j is None or j.state != "running":
                return
            self._rates(j, -1.0)
            j.speed = float(rec.get("speed", j.speed))
            self._rates(j, +1.0)
        elif kind == "slow":
            j = self._get(rec)
            if j is None or j.state != "running":
                return
            self._rates(j, -1.0)
            j.slow = float(rec.get("slow_factor", j.slow))
            self._rates(j, +1.0)
        elif kind == "net":
            j = self._get(rec)
            if j is None or j.state != "running":
                return
            self._rates(j, -1.0)
            j.loc = float(rec.get("locality", j.loc))
            self._rates(j, +1.0)
        elif kind in ("migrate", "resize", "rebind"):
            j = self._get(rec)
            if j is None or j.state != "running":
                return
            self._rates(j, -1.0)
            new_chips = int(rec.get("chips", j.alloc))
            self._used += new_chips - j.alloc
            j.alloc = new_chips
            j.speed = float(rec.get("speed", j.speed))
            j.loc = float(rec.get("locality", j.loc))
            j.static_loc = j.loc
            if "track" in rec:
                j.gpu = str(rec.get("track", "")).startswith("gpu/")
            j.slow = float(rec.get("slow_factor", 1.0))
            self._rates(j, +1.0)
        elif kind == "fault":
            self._win_faults += 1
        elif kind == "sample":
            frag = rec.get("frag")
            if frag is not None:
                f = float(frag)
                if self._win_frag is None or f > self._win_frag:
                    self._win_frag = f
            pods = rec.get("pods")
            if pods:
                for p in pods:
                    h = p.get("hazard")
                    if h is not None:
                        h = float(h)
                        if self._win_hazard is None or h > self._win_hazard:
                            self._win_hazard = h
        # reject / repair / warn / reroute / netlink / cache / alert:
        # no rolling state to move

    # ------------------------------------------------------------------ #
    # window evaluation

    def _blame_run(self) -> Tuple[str, Dict[str, float]]:
        """Blame for a running-side detection (goodput-collapse): the
        window's dominant slowdown leg via the PR-5 leg vocabulary —
        fault rollback first (revocations erase work outright), else the
        largest integrated stretch leg."""
        legs = {k: self._leg_int[k] for k in sorted(self._leg_int)}
        if self._win_revocations:
            legs["fault-outage"] = self._win_lost
            return "fault-outage", legs
        best, best_v = "unknown", 0.0
        for k in sorted(legs):
            if legs[k] > best_v:
                best, best_v = k, legs[k]
        return best, legs

    def _blame_wait(self) -> Tuple[str, Dict[str, float]]:
        """Blame for a queue-side detection (surge / slo-burn): the
        dominant integrated wait cause (job-seconds queued per PR-5
        blame cause; 'unattributed' on captures without --attrib)."""
        legs = {k: self._wait_int[k] for k in sorted(self._wait_int)}
        best, best_v = "unknown", 0.0
        for k in sorted(legs):
            if legs[k] > best_v:
                best, best_v = k, legs[k]
        return best, legs

    def _fire(
        self,
        detector: str,
        t_end: float,
        value: float,
        threshold: float,
        baseline: Optional[float],
        cause: str,
        legs: Dict[str, float],
        p99_wait_s: Optional[float] = None,
    ) -> Optional[dict]:
        if detector in self._active_alerts:
            return None
        self._active_alerts.add(detector)
        self._seq += 1
        extra = {}
        if baseline is not None:
            extra["baseline"] = baseline
        extra["cause"] = cause
        extra["legs"] = {k: legs[k] for k in sorted(legs)}
        if p99_wait_s is not None:
            extra["p99_wait_s"] = p99_wait_s
        if self.flight_dir is not None:
            # flight recorder: dump the last-N raw events verbatim and
            # pin the watched run's newest engine snapshot (+ sidecar)
            # so `whatif` restores straight into the pre-incident state
            self.flight_dir.mkdir(parents=True, exist_ok=True)
            name = f"alert-{self._seq:04d}.events.jsonl"
            with open(self.flight_dir / name, "w") as f:
                for line in self.ring:
                    f.write(line if line.endswith("\n") else line + "\n")
            extra["events_file"] = name
            if self.snapshot is not None and self.snapshot.exists():
                # copy ORDER matters against a live engine replacing
                # both files: snapshot first, sidecar second, so the
                # pinned pair is (snap N, meta >= N) — snapshot_t then
                # never understates the pinned state's instant and
                # `whatif --resume <pin> --at <snapshot_t>` always lands
                # at-or-after the restored clock.  snapshot_t is read
                # from the COPY, never the (possibly newer) live file.
                pin = f"alert-{self._seq:04d}.snap"
                shutil.copyfile(self.snapshot, self.flight_dir / pin)
                extra["snapshot_file"] = pin
                meta = Path(str(self.snapshot) + ".meta.json")
                if meta.exists():
                    pinned_meta = self.flight_dir / (pin + ".meta.json")
                    shutil.copyfile(meta, pinned_meta)
                    try:
                        extra["snapshot_t"] = float(
                            json.loads(pinned_meta.read_text()).get("t", 0.0)
                        )
                    except (ValueError, TypeError):
                        pass
        self._emit_header()
        severity = _SEVERITY[detector]
        alert = self.sink.event(
            "alert", t_end, None,
            detector=detector, severity=severity, window_s=self.w,
            value=value, threshold=threshold, seq=self._seq, **extra,
        )
        self.alerts.append(alert)
        self.alert_counts[detector] = self.alert_counts.get(detector, 0) + 1
        if self._reg_alerts is not None:
            self._reg_alerts.labels(detector).inc()
        if self._history is not None:
            h = self.header
            self._history.append(
                "watch",
                run_id=h.run_id if h else "",
                config_hash=h.config_hash if h else "",
                policy=h.policy if h else "",
                seed=h.seed if h else None,
                label=detector,
                metrics={
                    "t": t_end, "value": value, "threshold": threshold,
                    "window_s": self.w, "severity": severity,
                    "cause": cause, "seq": self._seq,
                },
            )
        return alert

    def _close_window(self, wend: float) -> List[dict]:
        self.windows += 1
        W = self.w
        dets = self.rules["detectors"]
        out: List[dict] = []
        vel = self._vel_int / W
        # the window's exact p99 queueing delay (jobs that started in it)
        p99 = (
            exact_quantile(self._win_waits, 0.99)
            if self._win_waits else None
        )

        def settle(detector: str, condition: bool, *fire_args, **fire_kw) -> None:
            if condition:
                alert = self._fire(detector, wend, *fire_args, **fire_kw)
                if alert is not None:
                    out.append(alert)
            else:
                self._active_alerts.discard(detector)

        cfg = dets.get("queue-depth-surge")
        if cfg is not None:
            floor = max(cfg["min_pending"],
                        cfg["surge_factor"] * max(1.0, self._win_pend_start))
            cond = self._pending >= floor
            cause, legs = self._blame_wait()
            settle("queue-depth-surge", cond, float(self._pending), floor,
                   float(self._win_pend_start), cause, legs,
                   p99_wait_s=p99)

        cfg = dets.get("goodput-collapse")
        if cfg is not None:
            baseline = (
                sum(self._vel_hist) / len(self._vel_hist)
                if self._vel_hist else None
            )
            cond = (
                baseline is not None
                and baseline >= cfg["min_velocity"]
                and vel <= cfg["collapse_frac"] * baseline
                and (self._pending > 0 or self._running > 0)
            )
            cause, legs = self._blame_run()
            settle(
                "goodput-collapse", cond, vel,
                (cfg["collapse_frac"] * baseline) if baseline is not None
                else cfg["collapse_frac"],
                baseline, cause, legs,
            )
            if "goodput-collapse" not in self._active_alerts:
                # collapse windows stay out of their own baseline
                self._vel_hist.append(vel)
        else:
            self._vel_hist.append(vel)

        # sample-carried signals hold their last observation through
        # windows the sampler skipped (piecewise-constant, like every
        # other integrated signal here)
        if self._win_frag is not None:
            self._frag_held = self._win_frag
        if self._win_hazard is not None:
            self._hazard_held = self._win_hazard

        cfg = dets.get("frag-creep")
        if cfg is not None:
            frag = self._frag_held
            if frag is not None and frag >= cfg["frag_threshold"]:
                self._frag_streak += 1
            else:
                self._frag_streak = 0
            cond = self._frag_streak >= cfg["windows"]
            settle("frag-creep", cond,
                   frag if frag is not None else 0.0,
                   cfg["frag_threshold"], float(self._frag_streak),
                   "fragmentation", {})

        cfg = dets.get("hazard-spike")
        if cfg is not None:
            hz = self._hazard_held
            cond = hz is not None and hz >= cfg["hazard_threshold"]
            settle("hazard-spike", cond, hz if hz is not None else 0.0,
                   cfg["hazard_threshold"], None, "hazard", {})

        cfg = dets.get("slo-burn")
        if cfg is not None:
            # started jobs breach by measured first wait; jobs still
            # waiting for their FIRST start past the SLO count too —
            # during a full outage nothing starts, and a burn detector
            # that only samples starts would read a dead cluster as a
            # healthy one.  Already-started jobs sitting requeued are
            # excluded: their submit-relative age is not a queueing
            # delay (the first-start semantics `_win_waits` uses)
            overage = 0
            for job_id in sorted(self._jobs):
                j = self._jobs[job_id]
                if not j.started and \
                        (wend - j.submit_t) > cfg["wait_slo_s"]:
                    overage += 1
            total = len(self._win_waits) + overage
            breached = self._win_breached + overage
            budget = max(1e-9, 1.0 - cfg["target"])
            fast = (breached / total / budget) if total else 0.0
            self._slo_hist.append((total, breached))
            slow_total = sum(n for n, _ in self._slo_hist)
            slow_breached = sum(b for _, b in self._slo_hist)
            slow = (slow_breached / slow_total / budget) if slow_total else 0.0
            cond = fast >= cfg["fast_burn"] and slow >= cfg["slow_burn"]
            cause, legs = self._blame_wait()
            settle("slo-burn", cond, fast, cfg["fast_burn"], slow,
                   cause, legs, p99_wait_s=p99)

        # reset window accumulators
        self._occ_int = self._pend_int = self._vel_int = 0.0
        self._leg_int = {}
        self._wait_int = {}
        self._win_waits = []
        self._win_breached = 0
        self._win_lost = 0.0
        self._win_revocations = 0
        self._win_faults = 0
        self._win_frag = None
        self._win_hazard = None
        self._win_pend_start = self._pending
        return out

    def _emit_header(self) -> None:
        """Write the side stream's versioned header once — at the first
        alert, or (zero-alert watches) at :meth:`finish`, so an
        all-clear run still leaves the documented audit trail (run
        identity + ``rules_hash``) instead of an empty headerless file
        indistinguishable from a watcher that never ran."""
        if self._header_out:
            return
        self._header_out = True
        h = self.header
        self.sink.write_header({
            "run_id": h.run_id if h else "",
            "policy": h.policy if h else "",
            "seed": h.seed if h else None,
            "config_hash": h.config_hash if h else "",
            "source": self.source,
            "window_s": self.w,
            "rules_hash": rules_digest(self.rules),
        })

    # ------------------------------------------------------------------ #

    def finish(self) -> dict:
        """End of stream: the summary document.  The final *partial*
        window is deliberately not evaluated — its statistics cover less
        than one window of sim time, and every drive mode ends at the
        same last record, so all three modes agree on the alert tail."""
        self._emit_header()
        self.sink.close()
        h = self.header
        return {
            "events": self.n_events,
            "end_t": self.end_t,
            "windows": self.windows,
            "window_s": self.w,
            "alerts": len(self.alerts),
            "alerts_by_detector": dict(sorted(self.alert_counts.items())),
            "active": sorted(self._active_alerts),
            "anomalies": self.anomalies,
            "jobs_active": len(self._jobs),
            "run_id": h.run_id if h else "",
            "policy": h.policy if h else "",
            "config_hash": h.config_hash if h else "",
            "rules_hash": rules_digest(self.rules),
        }


# --------------------------------------------------------------------- #
# the self-SLO watchdog (ISSUE 18): the burn-rate machinery pointed at
# the twin's own serving telemetry


# The serving daemon's own SLO (the "observer observes itself" half of
# ISSUE 18).  Windows are counted in *observations* (served queries,
# rejections, errors), not wall or sim time: the alert sequence is then
# a pure function of the observation sequence — the same determinism
# contract the stream detectors keep, with the observation index as the
# clock.
SELF_SLO_DEFAULTS: dict = {
    # a query slower than this breaches the latency SLO
    "latency_slo_ms": 500.0,
    # the availability target the error budget derives from
    "target": 0.95,
    # fast/slow burn multiples, à la SRE multi-window alerting (the
    # same knobs the stream slo-burn detector uses)
    "fast_burn": 10.0,
    "slow_burn": 2.0,
    # observations per window / trailing windows in the slow horizon
    "window_queries": 20,
    "slow_windows": 12,
}


class SelfSLO:
    """Multi-window burn-rate watchdog over the serving daemon's OWN
    latency / rejection / error series (ISSUE 18): the PR-15 slo-burn
    arithmetic — error-budget burn over the last window AND over a
    trailing slow horizon, latched on the rising edge — pointed at the
    twin itself, so the daemon pages about its own degradation through
    the exact same surfaces cluster incidents use: the alert side
    stream (``sink``), the ``watch_alerts_total{detector}`` family, and
    one history row (kind ``watch``, label ``self-slo-burn``).

    An observation breaches when it was a rejection (admission queue
    full) or an error, or when its latency exceeds ``latency_slo_ms``.
    Every ``window_queries`` observations the window closes:
    ``fast = breached/total/budget`` over the window, ``slow`` over the
    trailing ``slow_windows`` windows, and the alert fires when both
    exceed their burn thresholds — a blip neither pages nor hides a
    slow leak, exactly like the stream detector.  ``t`` on a self alert
    is the observation index (this watchdog's clock); the window length
    rides the schema-additive ``window_queries`` key."""

    detector = "self-slo-burn"

    def __init__(
        self,
        cfg: Optional[dict] = None,
        *,
        sink: AlertStream,
        registry=None,
        history=None,
        run_meta: Optional[dict] = None,
    ):
        self.cfg = dict(SELF_SLO_DEFAULTS)
        unknown = sorted(set(cfg or ()) - set(SELF_SLO_DEFAULTS))
        if unknown:
            raise ValueError(
                f"unknown self-SLO keys {unknown}; "
                f"known: {sorted(SELF_SLO_DEFAULTS)}"
            )
        for k, v in (cfg or {}).items():
            self.cfg[k] = (
                int(v) if k in ("window_queries", "slow_windows")
                else float(v)
            )
        if self.cfg["window_queries"] < 1:
            raise ValueError(
                f"self-SLO window_queries must be >= 1, "
                f"got {self.cfg['window_queries']}"
            )
        if self.cfg["slow_windows"] < 1:
            raise ValueError(
                f"self-SLO slow_windows must be >= 1, "
                f"got {self.cfg['slow_windows']}"
            )
        if not 0.0 <= self.cfg["target"] < 1.0:
            raise ValueError(
                f"self-SLO target must be in [0, 1), got {self.cfg['target']}"
            )
        self.sink = sink
        self._reg_alerts = None
        if registry is not None:
            self._reg_alerts = registry.counter(
                "watch_alerts_total",
                "watchtower detections by detector (ISSUE 15)",
                labelnames=("detector",),
            )
        self._history = history
        self._meta = dict(run_meta or {})
        self.observations = 0
        self.windows = 0
        self.alerts: List[dict] = []
        self.active = False
        self._seq = 0
        self._n = 0            # observations in the open window
        self._breached = 0
        self._rej = 0          # rejection/error breaches (window)
        self._lat = 0          # latency breaches (window)
        self._hist: deque = deque(maxlen=int(self.cfg["slow_windows"]))

    def observe(
        self,
        latency_ms: Optional[float] = None,
        *,
        rejected: bool = False,
        error: bool = False,
    ) -> List[dict]:
        """Absorb one serving observation; returns the alerts fired by
        any window it closed (possibly empty)."""
        self.observations += 1
        self._n += 1
        if rejected or error:
            self._breached += 1
            self._rej += 1
        elif latency_ms is not None and \
                latency_ms > self.cfg["latency_slo_ms"]:
            self._breached += 1
            self._lat += 1
        if self._n >= int(self.cfg["window_queries"]):
            return self._close_window()
        return []

    def _close_window(self) -> List[dict]:
        self.windows += 1
        budget = max(1e-9, 1.0 - self.cfg["target"])
        fast = self._breached / self._n / budget
        self._hist.append((self._n, self._breached))
        slow_total = sum(n for n, _ in self._hist)
        slow_breached = sum(b for _, b in self._hist)
        slow = (slow_breached / slow_total / budget) if slow_total else 0.0
        cond = fast >= self.cfg["fast_burn"] and slow >= self.cfg["slow_burn"]
        out: List[dict] = []
        if cond and not self.active:
            self.active = True
            self._seq += 1
            # blame the dominant breach mode: saturation (rejections /
            # errors) vs slow serving — the serving twin's two legs
            legs: Dict[str, float] = {}
            if self._rej:
                legs["serve-rejection"] = float(self._rej)
            if self._lat:
                legs["serve-latency"] = float(self._lat)
            cause = (
                "serve-rejection" if self._rej >= self._lat and self._rej
                else "serve-latency"
            )
            alert = self.sink.event(
                "alert", float(self.observations), None,
                detector=self.detector, severity="page",
                window_queries=int(self.cfg["window_queries"]),
                value=fast, threshold=self.cfg["fast_burn"],
                baseline=slow, cause=cause,
                legs={k: legs[k] for k in sorted(legs)},
                seq=self._seq,
            )
            self.alerts.append(alert)
            if self._reg_alerts is not None:
                self._reg_alerts.labels(self.detector).inc()
            if self._history is not None:
                self._history.append(
                    "watch",
                    run_id=self._meta.get("run_id", ""),
                    config_hash=self._meta.get("config_hash", ""),
                    policy=self._meta.get("policy", ""),
                    seed=self._meta.get("seed"),
                    label=self.detector,
                    metrics={
                        "t": float(self.observations), "value": fast,
                        "threshold": self.cfg["fast_burn"],
                        "window_queries": int(self.cfg["window_queries"]),
                        "severity": "page", "cause": cause,
                        "seq": self._seq,
                    },
                )
            out.append(alert)
        elif not cond:
            self.active = False  # re-arm only after a clean window
        self._n = self._breached = self._rej = self._lat = 0
        return out


# --------------------------------------------------------------------- #
# drive modes: batch / replay / follow


def iter_stream(path) -> Iterator[Tuple[int, str, dict]]:
    """One-shot iteration over a finished events.jsonl(.gz) file —
    the batch drive mode.  Exactly analyze.py's shared drive loop,
    re-exported under the watch vocabulary."""
    return iter_jsonl_items(path)


def replay_stream(
    path, *, speed: float = 0.0, sleep=time.sleep
) -> Iterator[Tuple[int, str, dict]]:
    """Pace a finished stream as-if-live by sim time: with ``speed`` sim
    seconds per wall second, delivery sleeps between records so the
    operator sees the incident unfold; ``speed=0`` (the default) paces
    nothing.  Pacing only delays *delivery* — alert content is keyed to
    sim time alone, so any speed produces the batch mode's exact alert
    sequence (the determinism contract)."""
    last_t: Optional[float] = None
    for item in iter_stream(path):
        rec = item[2]
        t = rec.get("t")
        if speed > 0.0 and t is not None:
            t = float(t)
            if last_t is not None and t > last_t:
                sleep((t - last_t) / speed)
            last_t = t
        yield item


def follow_stream(
    path,
    *,
    poll_s: float = 0.5,
    idle_timeout_s: Optional[float] = None,
    max_wall_s: Optional[float] = None,
) -> Iterator[Tuple[int, str, dict]]:
    """Tail a growing events.jsonl: poll for appended bytes, parse the
    complete records, RETAIN a mid-record truncated tail until the
    writer completes it (the cursor re-reads it whole — never skipped).
    Stops after ``idle_timeout_s`` seconds without growth, or
    ``max_wall_s`` seconds total; both None tails forever.  Gzip streams
    cannot be tailed (no stable append offset) — use ``--replay``."""
    if str(path).endswith(".gz"):
        raise StreamError(
            f"{path}: gzip streams cannot be followed (no stable append "
            "offset); decompress first or use --replay"
        )
    cursor = StreamCursor(name=str(path))
    fh = None
    start = time.monotonic()  # lint: allow[GS101] follow-mode polling is wall-clock by design; alert content derives from sim time only
    last_growth = start
    try:
        while True:
            if fh is None and os.path.exists(path):
                fh = open(path, "r")
            grew = False
            if fh is not None:
                while True:
                    chunk = fh.read(1 << 16)
                    if not chunk:
                        break
                    grew = True
                    for item in cursor.feed(chunk):
                        yield item
            now = time.monotonic()  # lint: allow[GS101] same wall-clock poll loop as above
            if grew:
                last_growth = now
                continue
            if max_wall_s is not None and now - start >= max_wall_s:
                break
            if idle_timeout_s is not None and \
                    now - last_growth >= idle_timeout_s:
                break
            time.sleep(poll_s)
    finally:
        if fh is not None:
            fh.close()
    # a tail fragment the writer completed without a final newline is a
    # whole record; a fragment it never finished is dropped (strict=False
    # — the stream simply ends there for this watcher)
    for item in cursor.finish(strict=False):
        yield item


def run_watch(
    stream: Iterator[Tuple[int, str, dict]],
    watcher: Watcher,
    on_alert=None,
) -> dict:
    """Drive one watcher over one record stream; returns the summary.
    ``on_alert`` (e.g. a print) sees each alert the moment its window
    closes — the live half of the loop."""
    for _, raw, rec in stream:
        for alert in watcher.feed(rec, raw):
            if on_alert is not None:
                on_alert(alert)
    return watcher.finish()
