"""Cross-run regression diff over two analyzed event streams (ISSUE 3).

Answers "did my change regress the scheduler?" the way CI wants it
answered: metric by metric, with polarity-aware relative thresholds and an
exit code — 0 when run B is within threshold of run A everywhere, nonzero
past any threshold, refusal (``SchemaError``) when the two streams are not
comparable in the first place.

Comparability is the header contract (obs/analyze.py): both streams must
carry a schema-1 header, and their ``seed`` and ``config_hash`` must match
— the config hash covers cluster + trace + fault spec but *not* the
policy, so the two intended uses both work out of the box:

- **policy A vs policy B** on the same seeded world (headers match,
  ``policy`` differs and is reported);
- **pre-change vs post-change** at the same seed (everything matches).

Comparing runs of *different worlds* is almost always a mistake (the
deltas measure the worlds, not the scheduler) and is refused unless
``allow_mismatch=True`` / ``--allow-mismatch``.

Only metrics in :data:`GATED_METRICS` can fail the gate; everything else
in the summary is reported as informational.  Polarity matters: avg JCT
going *up* is a regression, mean occupancy going *down* is.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from gpuschedule_tpu.obs.analyze import RunAnalysis, SchemaError

# Gate-able metrics and their polarity: +1 means "bigger is worse" (a
# bigger B regresses), -1 means "smaller is worse".  Metrics absent here
# are informational — reported, never gating (preemption counts, say, are
# a policy's mechanism, not its quality).
GATED_METRICS: Dict[str, int] = {
    "avg_jct": +1,
    "makespan": +1,
    "wait_p50": +1,
    "wait_p95": +1,
    "wait_p99": +1,
    "jct_p50": +1,
    "jct_p95": +1,
    "jct_p99": +1,
    "slowdown_p95": +1,
    "goodput_lost_chip_s": +1,
    "goodput_restart_overhead_chip_s": +1,
    "num_finished": -1,
    "mean_occupancy": -1,
    "useful_frac": -1,
}

DEFAULT_THRESHOLD = 0.05  # 5% relative worsening

# deltas below this absolute size never gate: float dust on near-zero
# baselines (a lost_chip_s of 1e-9 vs 0.0) is not a regression signal
ABS_FLOOR = 1e-9


def flatten_metrics(analysis: RunAnalysis) -> Dict[str, Optional[float]]:
    """One flat {metric: value} view of an analysis: the summary scalars
    plus the distribution quantiles under ``<dist>_<quantile>`` keys."""
    out: Dict[str, Optional[float]] = {}
    for k, v in analysis.summary().items():
        out[k] = float(v) if isinstance(v, (int, float)) else None
    for dist, block in analysis.distributions().items():
        for q in ("p50", "p95", "p99", "mean"):
            v = block.get(q)
            out[f"{dist}_{q}"] = float(v) if v is not None else None
    return out


@dataclass
class MetricDiff:
    metric: str
    a: Optional[float]
    b: Optional[float]
    delta: Optional[float]        # b - a
    rel: Optional[float]          # (b - a) / |a|; None when undefined
    gated: bool
    threshold: Optional[float]    # the threshold applied (gated rows only)
    regressed: bool

    def to_json(self) -> dict:
        return {
            "metric": self.metric, "a": self.a, "b": self.b,
            "delta": self.delta, "rel": self.rel, "gated": self.gated,
            "threshold": self.threshold, "regressed": self.regressed,
        }


@dataclass
class CompareResult:
    run_a: dict                   # header summaries for the report/CLI
    run_b: dict
    rows: List[MetricDiff] = field(default_factory=list)

    @property
    def regressions(self) -> List[MetricDiff]:
        return [r for r in self.rows if r.regressed]

    @property
    def ok(self) -> bool:
        return not self.regressions

    @property
    def exit_code(self) -> int:
        """The CI contract: 0 identical-or-within-threshold, 1 regressed."""
        return 0 if self.ok else 1

    def to_json(self) -> dict:
        return {
            "run_a": self.run_a,
            "run_b": self.run_b,
            "ok": self.ok,
            "regressions": [r.metric for r in self.regressions],
            "rows": [r.to_json() for r in self.rows],
        }

    def format_table(self) -> str:
        """Human-readable diff, regressions first, informational rows after."""

        def fmt(v: Optional[float]) -> str:
            if v is None:
                return "-"
            if v != v:  # nan
                return "nan"
            return f"{v:.6g}"

        lines = [
            f"A: {_ident(self.run_a)}",
            f"B: {_ident(self.run_b)}",
            f"{'metric':32s} {'A':>12s} {'B':>12s} {'delta':>12s} "
            f"{'rel':>8s}  verdict",
        ]
        ordered = sorted(
            self.rows, key=lambda r: (not r.regressed, not r.gated, r.metric)
        )
        for r in ordered:
            rel = "-" if r.rel is None else f"{r.rel:+.2%}"
            verdict = (
                "REGRESSED" if r.regressed
                else ("ok" if r.gated else "info")
            )
            lines.append(
                f"{r.metric:32s} {fmt(r.a):>12s} {fmt(r.b):>12s} "
                f"{fmt(r.delta):>12s} {rel:>8s}  {verdict}"
            )
        lines.append(
            f"=> {'OK' if self.ok else 'REGRESSED'} "
            f"({len(self.regressions)} of {sum(1 for r in self.rows if r.gated)} "
            f"gated metrics past threshold)"
        )
        return "\n".join(lines)


def _ident(meta: dict) -> str:
    return (
        f"policy={meta.get('policy') or '?'} seed={meta.get('seed')} "
        f"config={meta.get('config_hash') or '?'} run_id={meta.get('run_id') or '?'}"
    )


def check_comparable(
    a: RunAnalysis, b: RunAnalysis, *, allow_mismatch: bool = False
) -> None:
    """Refuse un-comparable stream pairs (missing headers, different
    schema, different seeded world) instead of diffing garbage."""
    for name, an in (("A", a), ("B", b)):
        if an.header is None:
            raise SchemaError(
                f"run {name} has no stream header; capture it with run "
                f"identity (CLI --events) — refusing to compare"
            )
    if allow_mismatch:
        return
    ha, hb = a.header, b.header
    mismatched = [
        k for k, va, vb in (
            ("seed", ha.seed, hb.seed),
            ("config_hash", ha.config_hash, hb.config_hash),
        )
        if va != vb
    ]
    if mismatched:
        raise SchemaError(
            "runs are not comparable: "
            + ", ".join(
                f"{k} {getattr(ha, k)!r} != {getattr(hb, k)!r}"
                for k in mismatched
            )
            + " — the deltas would measure different worlds, not the "
            "scheduler (pass --allow-mismatch to override)"
        )


def compare_runs(
    a: RunAnalysis,
    b: RunAnalysis,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    per_metric: Optional[Dict[str, float]] = None,
    allow_mismatch: bool = False,
) -> CompareResult:
    """Diff run B against baseline run A metric by metric.

    ``threshold`` is the default relative-worsening gate; ``per_metric``
    overrides it for individual metrics (``{"wait_p99": 0.01}``).  A
    negative threshold demands *improvement* — handy for asserting a
    change helped, and for forcing a nonzero exit in smoke tests.
    """
    check_comparable(a, b, allow_mismatch=allow_mismatch)
    per_metric = per_metric or {}
    ma, mb = flatten_metrics(a), flatten_metrics(b)
    rows: List[MetricDiff] = []
    for metric in sorted(set(ma) | set(mb)):
        va, vb = ma.get(metric), mb.get(metric)
        polarity = GATED_METRICS.get(metric)
        gated = polarity is not None
        thr = per_metric.get(metric, threshold) if gated else None
        if va is None or vb is None or va != va or vb != vb:
            rows.append(MetricDiff(metric, va, vb, None, None, gated, thr, False))
            continue
        delta = vb - va
        rel = (delta / abs(va)) if va != 0.0 else (
            0.0 if delta == 0.0 else math.copysign(math.inf, delta)
        )
        regressed = False
        if gated:
            worsening = rel * polarity  # >0 means B is worse than A
            # ABS_FLOOR only suppresses float dust for ordinary positive
            # thresholds; a negative threshold *demands improvement*, so an
            # unchanged metric (delta == 0) must fail it
            regressed = worsening > thr and (thr < 0 or abs(delta) > ABS_FLOOR)
        rows.append(MetricDiff(metric, va, vb, delta, rel, gated, thr, regressed))
    return CompareResult(
        run_a=a.header.to_json() if a.header else {},
        run_b=b.header.to_json() if b.header else {},
        rows=rows,
    )


@dataclass
class MatrixResult:
    """An n-way policy x metric comparison (ISSUE 5 satellite — the
    ROADMAP "compare diffs exactly two runs" omission, retired).

    Unlike the two-run gate, the matrix ranks: for every gated metric the
    best and worst run are marked (polarity-aware — best avg_jct is the
    smallest, best num_finished the largest).  Informational metrics are
    listed unranked.  There is no pass/fail here; gating stays the
    two-run form's job, so its exit-code contract is untouched."""

    runs: List[dict]                       # header summaries, column order
    labels: List[str]                      # unique column labels
    metrics: Dict[str, List[Optional[float]]] = field(default_factory=dict)
    best: Dict[str, Optional[int]] = field(default_factory=dict)
    worst: Dict[str, Optional[int]] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "runs": self.runs,
            "labels": self.labels,
            "metrics": {
                m: {
                    "values": vals,
                    "best": self.best.get(m),
                    "worst": self.worst.get(m),
                    "gated": m in GATED_METRICS,
                }
                for m, vals in self.metrics.items()
            },
        }

    def format_table(self) -> str:
        """Text matrix, one column per run: gated metrics first, best
        value marked ``*`` and worst ``!`` (never color- or
        position-only; the legend line spells it out)."""

        def fmt(v: Optional[float]) -> str:
            if v is None:
                return "-"
            if v != v:
                return "nan"
            return f"{v:.6g}"

        # 14 fits "-1.23457e+06" plus the best/worst mark and a gap
        width = max(14, max((len(l) for l in self.labels), default=12) + 2)
        lines = [
            f"{len(self.labels)}-way compare (* best, ! worst per gated metric)"
        ]
        for i, (label, run) in enumerate(zip(self.labels, self.runs)):
            lines.append(f"  col {i + 1}: {label} — {_ident(run)}")
        lines.append(
            "metric".ljust(32)
            + "".join(label.rjust(width) for label in self.labels)
        )
        ordered = sorted(
            self.metrics, key=lambda m: (m not in GATED_METRICS, m)
        )
        for m in ordered:
            cells = []
            for i, v in enumerate(self.metrics[m]):
                mark = (
                    "*" if self.best.get(m) == i
                    else ("!" if self.worst.get(m) == i else " ")
                )
                cells.append(f"{fmt(v)}{mark}".rjust(width))
            lines.append(m.ljust(32) + "".join(cells))
        return "\n".join(lines)


def _unique_labels(analyses: Sequence[RunAnalysis]) -> List[str]:
    """Column labels: the policy name, disambiguated with the run_id when
    two runs share one (pre-vs-post runs of the same policy), and with
    the column index when even the run_ids collide (run_id is
    deterministic, so same-policy same-world captures all share it)."""
    policies = [
        (a.header.policy or f"run{i + 1}") if a.header else f"run{i + 1}"
        for i, a in enumerate(analyses)
    ]
    labels = []
    for i, p in enumerate(policies):
        if policies.count(p) > 1:
            rid = analyses[i].header.run_id if analyses[i].header else ""
            labels.append(f"{p}#{i + 1}" if not rid else f"{p}@{rid[-6:]}")
        else:
            labels.append(p)
    dupes = {label for label in labels if labels.count(label) > 1}
    return [
        f"{label}#{i + 1}" if label in dupes else label
        for i, label in enumerate(labels)
    ]


def compare_matrix(
    analyses: Sequence[RunAnalysis], *, allow_mismatch: bool = False
) -> MatrixResult:
    """Build the n-way policy x metric matrix over ``analyses`` (>= 2).

    Every run must be comparable with the first — same seeded world
    (seed + config_hash), the exact rule the two-run gate applies —
    unless ``allow_mismatch``.  Best/worst are only awarded on gated
    metrics where at least two values exist and they actually differ
    (an all-equal row has no winner)."""
    analyses = list(analyses)
    if len(analyses) < 2:
        raise ValueError("compare_matrix needs at least two runs")
    for other in analyses[1:]:
        check_comparable(analyses[0], other, allow_mismatch=allow_mismatch)
    flats = [flatten_metrics(a) for a in analyses]
    names = sorted(set().union(*flats))
    metrics: Dict[str, List[Optional[float]]] = {}
    best: Dict[str, Optional[int]] = {}
    worst: Dict[str, Optional[int]] = {}
    for m in names:
        vals = [f.get(m) for f in flats]
        metrics[m] = vals
        polarity = GATED_METRICS.get(m)
        best[m] = worst[m] = None
        if polarity is None:
            continue
        present = [(v, i) for i, v in enumerate(vals)
                   if v is not None and v == v]
        if len(present) < 2 or all(v == present[0][0] for v, _ in present):
            continue
        # polarity +1: bigger is worse -> best is the minimum
        ranked = sorted(present, key=lambda p: (polarity * p[0], p[1]))
        best[m], worst[m] = ranked[0][1], ranked[-1][1]
    return MatrixResult(
        runs=[a.header.to_json() if a.header else {} for a in analyses],
        labels=_unique_labels(analyses),
        metrics=metrics,
        best=best,
        worst=worst,
    )


def write_matrix_json(result: MatrixResult, path) -> None:
    with open(path, "w") as f:
        json.dump(result.to_json(), f, indent=2, sort_keys=True)


def parse_thresholds(specs) -> tuple:
    """CLI ``--threshold`` values: a bare float sets the default gate, a
    ``metric=float`` pair overrides one metric; repeatable.  Returns
    ``(default, per_metric)``."""
    default = DEFAULT_THRESHOLD
    per_metric: Dict[str, float] = {}
    for spec in specs or []:
        k, sep, v = str(spec).partition("=")
        try:
            if sep:
                per_metric[k] = float(v)
            else:
                default = float(k)
        except ValueError:
            raise ValueError(
                f"--threshold wants FLOAT or METRIC=FLOAT, got {spec!r}"
            ) from None
    unknown = sorted(set(per_metric) - set(GATED_METRICS))
    if unknown:
        raise ValueError(
            f"--threshold for non-gated metrics {unknown}; gated metrics: "
            f"{sorted(GATED_METRICS)}"
        )
    return default, per_metric


def write_compare_json(result: CompareResult, path) -> None:
    with open(path, "w") as f:
        json.dump(result.to_json(), f, indent=2, sort_keys=True)
