"""Self-contained HTML run report (ISSUE 3 tentpole).

Renders one :class:`~gpuschedule_tpu.obs.analyze.RunAnalysis` as a single
HTML file with **inline CSS/SVG only — zero network fetches, zero
dependencies**: open it from disk on an air-gapped box and everything is
there.  Panels:

- a KPI row (finished jobs, avg JCT, p99 wait, mean occupancy, useful
  goodput share);
- chip-occupancy and pending-queue time series (two stacked single-series
  charts sharing a time axis — never a dual-axis chart);
- wait/JCT CDFs with exact quantiles;
- the fault panel: goodput decomposition as a part-to-whole stacked bar
  plus the per-kind attribution table (hidden for fault-free runs);
- table views of every chart's data (distributions, slowest jobs), so no
  value is reachable only through color.

Charts follow the dataviz reference palette (validated ordering; series
identity always has a non-color channel: direct labels, legends, and the
table views).  Light and dark mode are both selected via CSS custom
properties — the dark values are their own steps, not an automatic flip.
Per-mark hover carries exact values via native SVG ``<title>`` tooltips.
"""

from __future__ import annotations

import heapq
import html
import math
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from gpuschedule_tpu.obs.analyze import RunAnalysis

# Plot geometry (CSS pixels inside the SVG viewBox).
_W, _H = 860, 220
_ML, _MR, _MT, _MB = 56, 16, 14, 30
_MAX_PTS = 400  # series are decimated to this many points before drawing


# --------------------------------------------------------------------- #
# formatting

def _fmt_dur(s: Optional[float]) -> str:
    if s is None:
        return "–"
    if s != s:
        return "nan"
    if s < 120:
        return f"{s:.0f} s"
    if s < 2 * 3600:
        return f"{s / 60:.1f} min"
    if s < 48 * 3600:
        return f"{s / 3600:.1f} h"
    return f"{s / 86400:.1f} d"


def _fmt_num(v: Optional[float]) -> str:
    if v is None:
        return "–"
    if v != v:
        return "nan"
    a = abs(v)
    if a >= 1e9:
        return f"{v / 1e9:.1f}B"
    if a >= 1e6:
        return f"{v / 1e6:.1f}M"
    if a >= 1e4:
        return f"{v / 1e3:.1f}K"
    if a >= 100 or v == int(v):
        return f"{v:,.0f}"
    return f"{v:.2f}"


def _fmt_pct(v: Optional[float]) -> str:
    return "–" if v is None else f"{100.0 * v:.1f}%"


def _esc(s: object) -> str:
    return html.escape(str(s), quote=True)


def _nice_ticks(lo: float, hi: float, n: int = 5) -> List[float]:
    """Round tick positions covering [lo, hi] (1/2/5 ladder)."""
    if hi <= lo:
        return [lo]
    raw = (hi - lo) / max(1, n)
    mag = 10 ** math.floor(math.log10(raw))
    step = next(
        (m * mag for m in (1, 2, 5, 10) if m * mag >= raw), 10 * mag
    )
    t = math.ceil(lo / step) * step
    out = []
    while t <= hi + 1e-9 * step:
        out.append(t)
        t += step
    return out or [lo]


def _decimate(pts: Sequence[Tuple[float, float]], cap: int = _MAX_PTS):
    if len(pts) <= cap:
        return list(pts)
    stride = max(1, len(pts) // cap)
    out = list(pts[::stride])
    if out[-1] != pts[-1]:
        out.append(pts[-1])
    return out


# --------------------------------------------------------------------- #
# SVG builders

def _time_axis(t_max: float) -> Tuple[float, str]:
    """Pick a time unit for the x axis; returns (divisor, unit label)."""
    if t_max >= 2 * 86400:
        return 86400.0, "days"
    if t_max >= 2 * 3600:
        return 3600.0, "hours"
    if t_max >= 120:
        return 60.0, "minutes"
    return 1.0, "seconds"


def _xy(t, v, t_max, v_max):
    x = _ML + (t / t_max if t_max > 0 else 0.0) * (_W - _ML - _MR)
    y = _MT + (1.0 - (v / v_max if v_max > 0 else 0.0)) * (_H - _MT - _MB)
    return x, y


def _grid_and_axes(t_max: float, v_max: float, unit_div: float,
                   unit: str, y_fmt=_fmt_num) -> List[str]:
    parts = []
    for yt in _nice_ticks(0.0, v_max, 4):
        _, y = _xy(0.0, yt, t_max, v_max)
        parts.append(
            f'<line class="grid" x1="{_ML}" y1="{y:.1f}" '
            f'x2="{_W - _MR}" y2="{y:.1f}"/>'
        )
        parts.append(
            f'<text class="tick" x="{_ML - 6}" y="{y + 3:.1f}" '
            f'text-anchor="end">{_esc(y_fmt(yt))}</text>'
        )
    for xt in _nice_ticks(0.0, t_max / unit_div, 6):
        x, _ = _xy(xt * unit_div, 0.0, t_max, v_max)
        parts.append(
            f'<text class="tick" x="{x:.1f}" y="{_H - _MB + 16}" '
            f'text-anchor="middle">{_esc(_fmt_num(xt))}</text>'
        )
    parts.append(
        f'<line class="axis" x1="{_ML}" y1="{_H - _MB}" '
        f'x2="{_W - _MR}" y2="{_H - _MB}"/>'
    )
    parts.append(
        f'<text class="tick" x="{_W - _MR}" y="{_H - 4}" '
        f'text-anchor="end">sim time ({unit})</text>'
    )
    return parts


def _mark_lines(
    marks: Optional[Sequence[Tuple[float, str]]], t_max: float, v_max: float
) -> List[str]:
    """Vertical event ticks on a time chart (the Alerts panel's timeline
    marks, ISSUE 15): a dashed line at each (t, label) with a native
    tooltip — identity never color-alone (the label rides the title and
    the per-detector table repeats every value)."""
    parts: List[str] = []
    for t, label in marks or ():
        x, _ = _xy(t, 0.0, t_max, v_max)
        parts.append(
            f'<line class="mark" x1="{x:.1f}" y1="{_MT}" '
            f'x2="{x:.1f}" y2="{_H - _MB}">'
            f"<title>{_esc(label)} at t = {_esc(_fmt_dur(t))}</title></line>"
        )
    return parts


def _step_series_chart(
    pts: Sequence[Tuple[float, float]],
    *,
    series_var: str,
    label: str,
    t_max: float,
    v_max: Optional[float] = None,
    cap_line: Optional[float] = None,
    area: bool = True,
    hover_fmt=_fmt_num,
    marks: Optional[Sequence[Tuple[float, str]]] = None,
) -> str:
    """One single-series step-after chart (line + optional 10% wash).
    Single series: the panel title names it, so no legend box."""
    pts = _decimate(pts)
    if not pts:
        return '<p class="empty">no samples</p>'
    vmax = v_max if v_max is not None else max(v for _, v in pts)
    if cap_line is not None:
        vmax = max(vmax, cap_line)
    vmax = vmax or 1.0
    unit_div, unit = _time_axis(t_max)
    parts = ['<svg viewBox="0 0 %d %d" role="img" aria-label="%s">'
             % (_W, _H, _esc(label))]
    parts += _grid_and_axes(t_max, vmax, unit_div, unit)
    # step-after path
    path = []
    for i, (t, v) in enumerate(pts):
        x, y = _xy(t, v, t_max, vmax)
        if i == 0:
            path.append(f"M{x:.1f},{y:.1f}")
        else:
            _, py = _xy(pts[i - 1][0], pts[i - 1][1], t_max, vmax)
            path.append(f"L{x:.1f},{py:.1f} L{x:.1f},{y:.1f}")
    d = " ".join(path)
    if area:
        x0, y0 = _xy(pts[0][0], 0.0, t_max, vmax)
        xn, _ = _xy(pts[-1][0], 0.0, t_max, vmax)
        parts.append(
            f'<path d="{d} L{xn:.1f},{y0:.1f} L{x0:.1f},{y0:.1f} Z" '
            f'fill="var({series_var})" opacity="0.1" stroke="none"/>'
        )
    if cap_line is not None:
        _, cy = _xy(0.0, cap_line, t_max, vmax)
        parts.append(
            f'<line class="cap" x1="{_ML}" y1="{cy:.1f}" '
            f'x2="{_W - _MR}" y2="{cy:.1f}"/>'
            f'<text class="tick" x="{_ML + 4}" y="{cy - 4:.1f}">'
            f"capacity {_esc(_fmt_num(cap_line))}</text>"
        )
    parts.append(
        f'<path d="{d}" fill="none" stroke="var({series_var})" '
        f'stroke-width="2" stroke-linejoin="round" stroke-linecap="round"/>'
    )
    parts += _mark_lines(marks, t_max, vmax)
    # hover layer: one invisible hit band per decimated sample with a
    # native tooltip (self-contained; no script needed)
    band = (_W - _ML - _MR) / max(1, len(pts))
    for t, v in pts:
        x, _ = _xy(t, v, t_max, vmax)
        parts.append(
            f'<rect class="hit" x="{x - band / 2:.1f}" y="{_MT}" '
            f'width="{band:.1f}" height="{_H - _MT - _MB}">'
            f"<title>t = {_esc(_fmt_dur(t))}\n{_esc(label)}: "
            f"{_esc(hover_fmt(v))}</title></rect>"
        )
    parts.append("</svg>")
    return "".join(parts)


_SERIES_VARS = ("--series-1", "--series-2", "--series-3", "--series-4",
                "--series-5")


def _multi_step_chart(
    series: List[Tuple[str, List[Tuple[float, float]]]],
    *,
    label: str,
    t_max: float,
    v_max: float = 1.0,
    y_fmt=_fmt_pct,
    cap_line: Optional[float] = None,
    marks: Optional[Sequence[Tuple[float, str]]] = None,
) -> str:
    """Several step-after series on one axis (the network panel's link-
    utilization view; the occupancy panel's demand-vs-physical overlay).
    Identity is never color-alone: each line ends in a direct label and
    carries a native-tooltip ``<title>``."""
    series = [(n, pts) for n, pts in series if pts]
    if not series:
        return '<p class="empty">no samples</p>'
    if cap_line is not None:
        v_max = max(v_max, cap_line)
    unit_div, unit = _time_axis(t_max)
    parts = ['<svg viewBox="0 0 %d %d" role="img" aria-label="%s">'
             % (_W, _H, _esc(label))]
    parts += _grid_and_axes(t_max, v_max, unit_div, unit, y_fmt=y_fmt)
    if cap_line is not None:
        _, cy = _xy(0.0, cap_line, t_max, v_max)
        parts.append(
            f'<line class="cap" x1="{_ML}" y1="{cy:.1f}" '
            f'x2="{_W - _MR}" y2="{cy:.1f}"/>'
            f'<text class="tick" x="{_ML + 4}" y="{cy - 4:.1f}">'
            f"capacity {_esc(_fmt_num(cap_line))}</text>"
        )
    for i, (name, pts) in enumerate(series):
        var = _SERIES_VARS[i % len(_SERIES_VARS)]
        pts = _decimate(pts)
        path = []
        for j, (t, v) in enumerate(pts):
            x, y = _xy(t, min(v, v_max), t_max, v_max)
            if j == 0:
                path.append(f"M{x:.1f},{y:.1f}")
            else:
                _, py = _xy(pts[j - 1][0], min(pts[j - 1][1], v_max), t_max, v_max)
                path.append(f"L{x:.1f},{py:.1f} L{x:.1f},{y:.1f}")
        d = " ".join(path)
        parts.append(
            f'<path d="{d}" fill="none" stroke="var({var})" '
            f'stroke-width="2" stroke-linejoin="round" stroke-linecap="round">'
            f"<title>{_esc(name)}</title></path>"
        )
        ex, ey = _xy(pts[-1][0], min(pts[-1][1], v_max), t_max, v_max)
        parts.append(
            f'<circle cx="{ex:.1f}" cy="{ey:.1f}" r="3.5" fill="var({var})" '
            f'stroke="var(--surface-1)" stroke-width="2"/>'
            f'<text class="dlabel" x="{min(ex + 6, _W - 90):.1f}" '
            f'y="{ey - 5:.1f}">{_esc(name)}</text>'
        )
    parts += _mark_lines(marks, t_max, v_max)
    parts.append("</svg>")
    return "".join(parts)


def _cdf_points(values: Sequence[float]) -> List[Tuple[float, float]]:
    s = sorted(values)
    n = len(s)
    return [(v, (i + 1) / n) for i, v in enumerate(s)]


def _cdf_chart(series: List[Tuple[str, str, List[float]]], label: str) -> str:
    """Multi-series CDF: x = seconds (log-ish linear), y = fraction.
    ``series`` rows are (name, css-var, values).  Legend + direct end
    labels carry identity alongside color."""
    series = [(n, c, v) for n, c, v in series if v]
    if not series:
        return '<p class="empty">no finished jobs</p>'
    x_max = max(max(v) for _, _, v in series) or 1.0
    unit_div, unit = _time_axis(x_max)
    parts = ['<svg viewBox="0 0 %d %d" role="img" aria-label="%s">'
             % (_W, _H, _esc(label))]
    for frac in (0.25, 0.5, 0.75, 1.0):
        _, y = _xy(0.0, frac, x_max, 1.0)
        parts.append(
            f'<line class="grid" x1="{_ML}" y1="{y:.1f}" '
            f'x2="{_W - _MR}" y2="{y:.1f}"/>'
            f'<text class="tick" x="{_ML - 6}" y="{y + 3:.1f}" '
            f'text-anchor="end">{int(frac * 100)}%</text>'
        )
    for xt in _nice_ticks(0.0, x_max / unit_div, 6):
        x, _ = _xy(xt * unit_div, 0.0, x_max, 1.0)
        parts.append(
            f'<text class="tick" x="{x:.1f}" y="{_H - _MB + 16}" '
            f'text-anchor="middle">{_esc(_fmt_num(xt))}</text>'
        )
    parts.append(
        f'<line class="axis" x1="{_ML}" y1="{_H - _MB}" '
        f'x2="{_W - _MR}" y2="{_H - _MB}"/>'
        f'<text class="tick" x="{_W - _MR}" y="{_H - 4}" '
        f'text-anchor="end">{_esc(unit)}</text>'
    )
    for name, var, values in series:
        pts = _decimate(_cdf_points(values))
        d = " ".join(
            ("M" if i == 0 else "L") + f"{_xy(v, f, x_max, 1.0)[0]:.1f},"
            f"{_xy(v, f, x_max, 1.0)[1]:.1f}"
            for i, (v, f) in enumerate(pts)
        )
        parts.append(
            f'<path d="{d}" fill="none" stroke="var({var})" '
            f'stroke-width="2" stroke-linejoin="round" stroke-linecap="round">'
            f"<title>{_esc(name)}</title></path>"
        )
        ex, ey = _xy(pts[-1][0], pts[-1][1], x_max, 1.0)
        parts.append(
            f'<circle cx="{ex:.1f}" cy="{ey:.1f}" r="4" fill="var({var})" '
            f'stroke="var(--surface-1)" stroke-width="2"/>'
            f'<text class="dlabel" x="{min(ex + 6, _W - 60):.1f}" '
            f'y="{ey - 6:.1f}">{_esc(name)}</text>'
        )
    parts.append("</svg>")
    return "".join(parts)


def _stacked_bar(
    legs: List[Tuple[str, float]],
    *,
    label: str,
    unit: str = "chip-s",
    empty_note: str = "nothing to decompose",
) -> str:
    """Part-to-whole: one horizontal stacked bar, 2px surface gaps
    between segments, labels inside where they fit, legend below so
    identity is never color-alone.  Negative legs (an elastic-speedup
    ``policy-share``) cannot be drawn as area; they are skipped in the
    bar but still listed in the legend with their sign."""
    total = sum(v for _, v in legs if v > 0)
    if total <= 0:
        return f'<p class="empty">{_esc(empty_note)}</p>'
    w, y0, bh = 860, 8, 24
    colored = [
        (name, v, _SERIES_VARS[i % len(_SERIES_VARS)])
        for i, (name, v) in enumerate(legs)
    ]
    # legend wraps into rows (the JCT decomposition can carry 8 legs —
    # a single 860px row would clip entries past the viewBox edge) and
    # the viewBox grows to fit every row
    lw = 210
    per_row = max(1, w // lw)
    legend_rows = (len(colored) + per_row - 1) // per_row
    h = y0 + bh + 10 + legend_rows * 16 + 4
    parts = [f'<svg viewBox="0 0 {w} {h}" role="img" '
             f'aria-label="{_esc(label)}">']
    x = 0.0
    for name, v, var in colored:
        seg = (v / total) * (w - 4)
        if seg <= 0:
            continue
        parts.append(
            f'<rect x="{x:.1f}" y="{y0}" width="{max(0.0, seg - 2):.1f}" '
            f'height="{bh}" rx="4" fill="var({var})">'
            f"<title>{_esc(name)}: {_esc(_fmt_num(v))} {_esc(unit)} "
            f"({_esc(_fmt_pct(v / total))})</title></rect>"
        )
        if seg > 150:  # label inside only when it comfortably fits
            parts.append(
                f'<text class="inbar" x="{x + 8:.1f}" y="{y0 + 16}">'
                f"{_esc(name)} {_esc(_fmt_pct(v / total))}</text>"
            )
        x += seg
    for i, (name, v, var) in enumerate(colored):
        # legend: identity never color-alone; wrapped so every entry
        # stays inside the viewBox
        lx = (i % per_row) * lw
        ly = y0 + bh + 10 + (i // per_row) * 16
        parts.append(
            f'<rect x="{lx:.1f}" y="{ly}" width="10" height="10" '
            f'rx="2" fill="var({var})"/>'
            f'<text class="tick" x="{lx + 14:.1f}" y="{ly + 9}">'
            f"{_esc(name)} {_esc(_fmt_num(v))} {_esc(unit)}</text>"
        )
    parts.append("</svg>")
    return "".join(parts)


def _stacked_goodput_bar(gp: dict) -> str:
    """The goodput decomposition as a part-to-whole stacked bar."""
    if gp["total_chip_s"] <= 0:
        return '<p class="empty">no service accrued</p>'
    return _stacked_bar(
        [
            ("useful", gp["useful_chip_s"]),
            ("lost", gp["lost_chip_s"]),
            ("restart overhead", gp["restart_overhead_chip_s"]),
        ],
        label="goodput",
    )


# --------------------------------------------------------------------- #
# HTML assembly

_CSS = """
:root { color-scheme: light dark; }
body {
  margin: 0; padding: 24px;
  font-family: system-ui, -apple-system, "Segoe UI", sans-serif;
  background: var(--page); color: var(--text-primary);
}
.viz-root {
  --surface-1: #fcfcfb; --page: #f9f9f7;
  --text-primary: #0b0b0b; --text-secondary: #52514e; --muted: #898781;
  --grid: #e1e0d9; --baseline: #c3c2b7;
  --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
  --series-4: #9556c7; --series-5: #c23f87;
  --border: rgba(11,11,11,0.10);
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root {
    --surface-1: #1a1a19; --page: #0d0d0d;
    --text-primary: #ffffff; --text-secondary: #c3c2b7; --muted: #898781;
    --grid: #2c2c2a; --baseline: #383835;
    --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
    --series-4: #a365d6; --series-5: #d052a0;
    --border: rgba(255,255,255,0.10);
  }
}
:root[data-theme="dark"] .viz-root {
  --surface-1: #1a1a19; --page: #0d0d0d;
  --text-primary: #ffffff; --text-secondary: #c3c2b7; --muted: #898781;
  --grid: #2c2c2a; --baseline: #383835;
  --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
  --series-4: #a365d6; --series-5: #d052a0;
  --border: rgba(255,255,255,0.10);
}
body { background: var(--page); }
h1 { font-size: 20px; margin: 0 0 4px; }
h2 { font-size: 15px; margin: 24px 0 8px; }
.meta { color: var(--text-secondary); font-size: 13px; margin-bottom: 16px; }
.meta code { background: none; color: inherit; }
.panel {
  background: var(--surface-1); border: 1px solid var(--border);
  border-radius: 8px; padding: 16px; margin-bottom: 16px;
}
.kpis { display: flex; flex-wrap: wrap; gap: 12px; }
.tile {
  background: var(--surface-1); border: 1px solid var(--border);
  border-radius: 8px; padding: 12px 16px; min-width: 130px; flex: 1;
}
.tile .label { font-size: 12px; color: var(--text-secondary); }
.tile .value { font-size: 26px; font-weight: 600; margin-top: 2px; }
.tile .sub { font-size: 12px; color: var(--muted); margin-top: 2px; }
svg { width: 100%; height: auto; display: block; }
svg .grid { stroke: var(--grid); stroke-width: 1; }
svg .axis { stroke: var(--baseline); stroke-width: 1; }
svg .cap { stroke: var(--baseline); stroke-width: 1; stroke-dasharray: none; }
svg .mark { stroke: var(--series-2); stroke-width: 1.5; stroke-dasharray: 4 3; }
svg .tick { fill: var(--muted); font-size: 11px; }
svg .dlabel { fill: var(--text-secondary); font-size: 12px; }
svg .inbar { fill: #ffffff; font-size: 12px; }
svg .hit { fill: transparent; }
svg .hit:hover { fill: var(--text-primary); fill-opacity: 0.05; }
table { border-collapse: collapse; font-size: 13px; width: 100%; }
th, td { text-align: right; padding: 4px 10px; border-bottom: 1px solid var(--grid); }
th:first-child, td:first-child { text-align: left; }
th { color: var(--text-secondary); font-weight: 600; }
td { font-variant-numeric: tabular-nums; }
.empty { color: var(--muted); font-size: 13px; }
.integrity { color: var(--muted); font-size: 12px; margin-top: 16px; }
"""


def _tile(label: str, value: str, sub: str = "") -> str:
    sub_html = f'<div class="sub">{_esc(sub)}</div>' if sub else ""
    return (
        f'<div class="tile"><div class="label">{_esc(label)}</div>'
        f'<div class="value">{_esc(value)}</div>{sub_html}</div>'
    )


def _dist_table(dists: dict) -> str:
    rows = []
    fmt = {
        "wait": _fmt_dur, "run": _fmt_dur, "jct": _fmt_dur,
        "slowdown": lambda v: "–" if v is None else f"{v:.2f}x",
        "preempt_count": _fmt_num, "fault_count": _fmt_num,
    }
    for name, block in dists.items():
        f = fmt.get(name, _fmt_num)
        rows.append(
            f"<tr><td>{_esc(name)}</td><td>{block['n']}</td>"
            + "".join(
                f"<td>{_esc(f(block[q]))}</td>"
                for q in ("mean", "p50", "p95", "p99", "max")
            )
            + "</tr>"
        )
    return (
        "<table><thead><tr><th>metric</th><th>n</th><th>mean</th>"
        "<th>p50</th><th>p95</th><th>p99</th><th>max</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _fault_kind_table(attribution: dict) -> str:
    rows = []
    for kind, row in attribution["kinds"].items():
        rows.append(
            f"<tr><td>{_esc(kind)}</td><td>{row['faults']}</td>"
            f"<td>{row['revocations']}</td>"
            f"<td>{row.get('warned_revocations', 0)}</td>"
            f"<td>{_esc(_fmt_dur(row['lost_work_s']))}</td>"
            f"<td>{_esc(_fmt_num(row['lost_chip_s']))}</td>"
            f"<td>{_esc(_fmt_dur(row['restore_charged_s']))}</td></tr>"
        )
    return (
        "<table><thead><tr><th>fault kind</th><th>outages</th>"
        "<th>revocations</th><th>warned</th><th>work lost</th>"
        "<th>chip-s lost</th><th>restore charged</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _domain_table(domains: dict) -> str:
    """Per-domain outage table (correlated ``domain`` faults): which
    hosts/racks/pods went down, how often, and for how long."""
    rows = []
    for scope, row in domains.items():
        rows.append(
            f"<tr><td>{_esc(scope)}</td><td>{_esc(row.get('level') or '–')}</td>"
            f"<td>{row['outages']}</td>"
            f"<td>{_esc(_fmt_dur(row['down_s']))}</td></tr>"
        )
    return (
        "<table><thead><tr><th>failure domain</th><th>level</th>"
        "<th>outages</th><th>down time</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _net_jobs_table(net: dict) -> str:
    rows = []
    for j in net["jobs"]:
        share = j["mean_share"]
        rows.append(
            f"<tr><td>{_esc(j['job_id'])}</td><td>{j['chips']}</td>"
            f"<td>{_esc(_fmt_num(j['mean_bw_gbps']))}</td>"
            f"<td>{_esc(_fmt_num(j['demand_gbps']))}</td>"
            f"<td>{_esc(_fmt_pct(share))}</td>"
            f"<td>{j['net_updates']}</td></tr>"
        )
    if not rows:
        return '<p class="empty">no multislice job was priced</p>'
    return (
        "<table><thead><tr><th>job</th><th>chips</th>"
        "<th>mean bw (Gbps)</th><th>demand (Gbps)</th><th>mean share</th>"
        "<th>re-prices</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _net_links_table(analysis: RunAnalysis, net: dict) -> str:
    rows = []
    for name, info in net["links"].items():
        rows.append(
            f"<tr><td>{_esc(name)}</td>"
            f"<td>{_esc(_fmt_pct(info['mean_util']))}</td>"
            f"<td>{_esc(_fmt_num(info['last_capacity_gbps']))}</td>"
            f"<td>{info['samples']}</td></tr>"
        )
    return (
        "<table><thead><tr><th>link</th><th>mean util</th>"
        "<th>capacity (Gbps)</th><th>changes</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _slowest_jobs_table(analysis: RunAnalysis, n: int = 10) -> str:
    # heapq.nlargest == sorted(..., reverse=True)[:n] (documented, ties
    # broken identically) without materializing the full finished list —
    # the bounded-memory analyzer (ISSUE 9) streams jobs from its spill
    # store, and this table must not pull them all back into RAM
    worst = heapq.nlargest(
        n,
        (r for r in analysis.jobs if r.finished and r.jct() is not None),
        key=lambda r: r.jct(),
    )
    if not worst:
        return '<p class="empty">no finished jobs</p>'
    # straggler slowdown column (ISSUE 6): only when the run attributed
    # any time to a degraded chip — fault-free reports keep their shape
    stragglers = any(r.delay_legs.get("straggler") for r in analysis.jobs)
    rows = []
    for r in worst:
        straggler_cell = (
            f"<td>{_esc(_fmt_dur(r.delay_legs.get('straggler', 0.0)))}</td>"
            if stragglers else ""
        )
        rows.append(
            f"<tr><td>{_esc(r.job_id)}</td><td>{r.chips}</td>"
            f"<td>{_esc(_fmt_dur(r.wait()))}</td>"
            f"<td>{_esc(_fmt_dur(r.jct()))}</td>"
            f"<td>{'–' if r.slowdown() is None else f'{r.slowdown():.1f}x'}</td>"
            f"<td>{r.preempts}</td><td>{r.faults}</td>"
            f"{straggler_cell}"
            f"<td>{_esc(r.end_state)}</td></tr>"
        )
    straggler_head = "<th>straggler</th>" if stragglers else ""
    return (
        "<table><thead><tr><th>job</th><th>chips</th><th>wait</th>"
        "<th>JCT</th><th>slowdown</th><th>preempts</th><th>faults</th>"
        f"{straggler_head}<th>end</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _cache_table(cache_stats: dict) -> str:
    """The Engine-health cache hit-rate table (ISSUE 10): one row per
    unified cache with its hit/miss/other counts and the hit rate over
    hits + misses (invalidate/fallback are listed but excluded from the
    rate — they are lifecycle events, not lookups)."""
    rows = []
    for name in sorted(cache_stats):
        outcomes = cache_stats[name] or {}
        hit = float(outcomes.get("hit", 0))
        miss = float(outcomes.get("miss", 0))
        other = {
            k: v for k, v in sorted(outcomes.items())
            if k not in ("hit", "miss")
        }
        lookups = hit + miss
        rate = (hit / lookups) if lookups > 0 else None
        other_s = (
            ", ".join(f"{k} {_fmt_num(float(v))}" for k, v in other.items())
            or "–"
        )
        rows.append(
            f"<tr><td>{_esc(name)}</td><td>{_esc(_fmt_num(hit))}</td>"
            f"<td>{_esc(_fmt_num(miss))}</td>"
            f"<td>{_esc(_fmt_pct(rate))}</td>"
            f"<td>{_esc(other_s)}</td></tr>"
        )
    return (
        "<table><thead><tr><th>cache</th><th>hits</th><th>misses</th>"
        "<th>hit rate</th><th>other events</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
    )


def _engine_health_panel(
    analysis: RunAnalysis, selfprof: Optional[dict]
) -> str:
    """The engine's view of itself (ISSUE 10): where the replay's *wall*
    time went (the self-profile phase decomposition) and whether the
    PR-7/9 caches are still earning their keep (hit-rate table from the
    run's trailing ``cache`` record).  Absent when the run carried
    neither signal."""
    cache_stats = getattr(analysis, "cache_stats", None) or {}
    if not selfprof and not cache_stats:
        return ""
    parts = ['<h2>Engine health</h2>\n<div class="panel">']
    if selfprof:
        phases = selfprof.get("phases", {})
        # pipeline order (obs/selfprof.py PHASES), not the JSON
        # document's alphabetical key order; unknown names trail
        from gpuschedule_tpu.obs.selfprof import PHASES as _PHASE_ORDER

        ordered = [p for p in _PHASE_ORDER if p in phases] + [
            p for p in sorted(phases) if p not in _PHASE_ORDER
        ]
        legs = [
            (name, float(phases[name].get("total_s", 0.0)))
            for name in ordered
        ]
        total = selfprof.get("total_wall_s")
        batches = selfprof.get("batches")
        meta = []
        if total is not None:
            meta.append(f"replay wall time {_esc(_fmt_dur(float(total)))}")
        if batches:
            meta.append(f"{int(batches):,} batches")
            if total:
                meta.append(f"{_esc(_fmt_num(batches / total))} batches/s")
        parts.append(
            f'<p class="meta">{" · ".join(meta)} — wall-clock phase '
            f"decomposition (run --self-profile)</p>"
        )
        parts.append(_stacked_bar(
            legs, label="replay wall time by phase", unit="s",
            empty_note="no wall time recorded",
        ))
    if cache_stats:
        parts.append(
            '<p class="meta">engine cache telemetry '
            "(engine_cache_events)</p>"
        )
        parts.append(_cache_table(cache_stats))
    parts.append("</div>")
    return "\n".join(parts)


def _occupancy_chart(
    analysis: RunAnalysis,
    occ_pts: List[Tuple[float, float]],
    t_max: float,
    total_chips: Optional[int],
    alert_marks: Optional[List[Tuple[float, str]]] = None,
) -> str:
    """The occupancy panel's chart: the demand series alone (historic
    view), or — when the run carried cluster ``sample`` events — demand
    overlaid on *physical* occupancy.  Demand above physical is overlay
    packing made visible (the ROADMAP PR-3 demand-only omission,
    retired); physical above zero while demand gaps are health holes.
    ``alert_marks`` (the watchtower's detections, ISSUE 15) draw as
    dashed timeline ticks."""
    phys_pts = [(t, float(u)) for t, u, _, _ in analysis.sample_series]
    if not phys_pts:
        return _step_series_chart(
            occ_pts, series_var="--series-1", label="chips allocated",
            t_max=t_max,
            cap_line=float(total_chips) if total_chips else None,
            marks=alert_marks,
        )
    v_max = max(
        max((v for _, v in occ_pts), default=1.0),
        max(v for _, v in phys_pts),
        1.0,
    )
    return _multi_step_chart(
        [("demand", occ_pts), ("physical", phys_pts)],
        label="chip occupancy: demand vs physical",
        t_max=t_max, v_max=v_max, y_fmt=_fmt_num,
        cap_line=float(total_chips) if total_chips else None,
        marks=alert_marks,
    )


def _alerts_panel(alerts: List[dict]) -> str:
    """The watchtower panel (ISSUE 15): one row per alert (time,
    detector, severity, value vs threshold, blamed cause) plus a
    per-detector rollup — the table half of the occupancy chart's
    timeline ticks, so no detection is reachable only through a mark."""
    rows = []
    per: dict = {}
    for a in alerts:
        det = str(a.get("detector", "?"))
        per[det] = per.get(det, 0) + 1
        rows.append(
            f"<tr><td>{_esc(_fmt_dur(float(a.get('t', 0.0))))}</td>"
            f"<td>{_esc(det)}</td>"
            f"<td>{_esc(a.get('severity', '–'))}</td>"
            f"<td>{_esc(_fmt_num(a.get('value')))}</td>"
            f"<td>{_esc(_fmt_num(a.get('threshold')))}</td>"
            f"<td>{_esc(a.get('cause', '–'))}</td></tr>"
        )
    rollup = " · ".join(
        f"{det} ×{n}" for det, n in sorted(per.items())
    )
    return f"""
<h2>Alerts</h2>
<div class="panel">
  <p class="meta">{len(alerts)} watchtower detections — {_esc(rollup)}</p>
  <table><thead><tr><th>t</th><th>detector</th><th>severity</th>
  <th>value</th><th>threshold</th><th>blamed cause</th></tr></thead>
  <tbody>{''.join(rows)}</tbody></table>
</div>"""


def render_report(
    analysis: RunAnalysis,
    *,
    title: Optional[str] = None,
    selfprof: Optional[dict] = None,
    alerts: Optional[List[dict]] = None,
) -> str:
    """The whole report as one HTML string (write it anywhere; it never
    references the network or the filesystem).  ``selfprof`` (the
    summary block of a ``run --self-profile`` document, via
    ``report --selfprof``) adds the wall-clock phase bar to the
    Engine-health panel; ``alerts`` (the watchtower side stream, via
    ``report --alerts``) adds timeline ticks on the occupancy chart and
    the per-detector Alerts panel (ISSUE 15)."""
    h = analysis.header
    s = analysis.summary()
    dists = analysis.distributions()
    attribution = analysis.fault_attribution()
    gp = attribution["goodput"]
    title = title or (
        f"Run report — {h.policy or 'unknown policy'}" if h else "Run report"
    )
    meta_bits = []
    if h is not None:
        meta_bits = [
            f"run <code>{_esc(h.run_id or '?')}</code>",
            f"policy <code>{_esc(h.policy or '?')}</code>",
            f"seed <code>{_esc(h.seed)}</code>",
            f"config <code>{_esc(h.config_hash or '?')}</code>",
            f"schema {h.schema}",
        ]
    meta_bits.append(f"{analysis.num_events:,} events")
    meta_bits.append(f"span {_fmt_dur(analysis.end_t)}")

    t_max = analysis.end_t or 1.0
    occ_pts = [(t, float(used)) for t, used, _, _ in analysis.util_series]
    pend_pts = [(t, float(p)) for t, _, _, p in analysis.util_series]
    total_chips = h.total_chips if h else None

    # one streaming pass for the CDF inputs: only the float values stay
    # resident, never the records — the bounded-memory analyzer (ISSUE 9)
    # may be feeding jobs from its spill store, and materializing the
    # finished list here would defeat it.  Same values in the same jobs
    # order as the old list comprehensions, so the charts are byte-equal.
    waits: List[float] = []
    jcts: List[float] = []
    for r in analysis.jobs:
        if r.finished:
            w = r.wait()
            if w is not None:
                waits.append(w)
            j = r.jct()
            if j is not None:
                jcts.append(j)

    kpis = [
        _tile("Finished jobs", _fmt_num(s["num_finished"]),
              f"{s['num_unfinished']} unfinished · {s['num_rejected']} rejected"),
        _tile("Avg JCT", _fmt_dur(s["avg_jct"]),
              f"p99 {_fmt_dur(dists['jct']['p99'])}"),
        _tile("p99 wait", _fmt_dur(dists["wait"]["p99"]),
              f"p50 {_fmt_dur(dists['wait']['p50'])}"),
        _tile("Mean occupancy", _fmt_pct(s["mean_occupancy"]),
              (f"physical {_fmt_pct(s['mean_phys_occupancy'])} · "
               if s.get("mean_phys_occupancy") is not None else "")
              + f"frag {_fmt_pct(s['mean_fragmentation'])}"),
        _tile("Useful goodput", _fmt_pct(s["useful_frac"]),
              f"{_fmt_num(gp['total_chip_s'])} chip-s total"),
    ]

    alert_marks = [
        (float(a.get("t", 0.0)), f"{a.get('detector', '?')} alert")
        for a in (alerts or [])
    ]
    alerts_panel = _alerts_panel(alerts) if alerts else ""

    net = analysis.network()
    # three-way net-degraded split (ISSUE 15): rendered whenever any job
    # ran below locality 1.0 — with or without the contention model
    # (network() already derived it; don't rescan the job list)
    split = net["net_degraded_split"]
    split_panel = ""
    if split:
        split_panel = (
            '<p class="meta">net-degraded stretch by segment</p>'
            + _stacked_bar(
                sorted(split.items()), label="net-degraded split", unit="s",
                empty_note="no net-degraded time",
            )
        )
    net_panel = ""
    if analysis.net_links:
        max_links = 6  # core + 5 busiest uplinks; the table lists them all
        by_load = sorted(
            analysis.net_links,
            key=lambda n: (n != "core", -(analysis.net_link_means.get(n) or 0.0), n),
        )
        link_series = [
            # same 0/0 rule as LinkSample.util: a dead link carrying no
            # traffic is 0% (idle), not 100% — the table agrees
            (name, [(t, (u / c) if c > 0 else (1.0 if u > 0 else 0.0))
                    for t, u, c in analysis.net_links[name]])
            for name in by_load[:max_links]
        ]
        dropped = len(analysis.net_links) - len(link_series)
        drop_note = (
            f'<p class="meta">{dropped} more uplinks in the table below</p>'
            if dropped > 0 else ""
        )
        net_panel = f"""
<h2>Network</h2>
<div class="panel">
  <p class="meta">{s['net_reprices']} bandwidth re-prices ·
  {len(net['jobs'])} multislice jobs priced</p>
  {_multi_step_chart(link_series, label='link utilization', t_max=t_max)}
  {drop_note}
  {_net_links_table(analysis, net)}
  {_net_jobs_table(net)}
  {split_panel}
</div>"""
    elif split_panel:
        # no contention model, but static tolls / GPU tiers stretched
        # run time: the split still gets its panel
        net_panel = f"""
<h2>Network</h2>
<div class="panel">
  {split_panel}
</div>"""

    # Attribution panel (ISSUE 5): where wait and JCT time went, cause by
    # cause — rendered only for attribution-armed captures.
    attrib_panel = ""
    legs = analysis.delay_by_cause()
    if legs:
        at = analysis.attribution()
        wait_total = sum(at["wait_s"].values())
        cause_rows = "".join(
            f"<tr><td>{_esc(k)}</td>"
            f"<td>{_esc(_fmt_dur(v))}</td>"
            f"<td>{_esc(_fmt_pct(v / wait_total) if wait_total > 0 else '–')}</td>"
            f"<td>{_esc(_fmt_num(at['chip_demand_wait_s'].get(k)))}</td></tr>"
            for k, v in at["wait_s"].items()
        )
        run_rows = "".join(
            f"<tr><td>{_esc(k)}</td><td>{_esc(_fmt_dur(v))}</td>"
            f"<td>–</td><td>–</td></tr>"
            for k, v in at["run_s"].items()
        )
        jct_legs = [(k, v) for k, v in (*at["wait_s"].items(),
                                        *at["run_s"].items())]
        attrib_panel = f"""
<h2>Attribution — why was time lost?</h2>
<div class="panel">
  <p class="meta">per-cause wait across all jobs (the blame decomposition;
  legs sum to the analyzer's wait exactly)</p>
  {_stacked_bar(list(at['wait_s'].items()), label='wait by cause',
                unit='s', empty_note='no job ever waited')}
  <p class="meta">full JCT decomposition: waits + work + slowdown
  stretches + restart overhead</p>
  {_stacked_bar(jct_legs, label='time by leg', unit='s')}
  <table><thead><tr><th>leg</th><th>seconds</th><th>share of wait</th>
  <th>chip-demand-s</th></tr></thead>
  <tbody>{cause_rows}{run_rows}</tbody></table>
  <p class="meta">decomposition residuals: wait
  {at['max_wait_residual']:.2e} · JCT {at['max_jct_residual']:.2e}</p>
</div>"""

    fault_panel = ""
    pro = getattr(analysis, "proactive", None) or {}
    if s["faults"] or s["revocations"] or gp["lost_chip_s"] > 0 or pro:
        kinds = attribution["kinds"]
        lost_total = sum(k["lost_work_s"] for k in kinds.values())
        lost_warned = sum(k.get("lost_work_warned_s", 0.0)
                          for k in kinds.values())
        n_warned = sum(k.get("warned_revocations", 0) for k in kinds.values())
        warned_note = ""
        if n_warned:
            # priced recovery (ISSUE 6): how much of the rollback an
            # emergency checkpoint caught vs what unwarned revocations
            # forfeited
            warned_note = (
                f" · {n_warned} warned revocations lost "
                f"{_esc(_fmt_dur(lost_warned))} vs "
                f"{_esc(_fmt_dur(lost_total - lost_warned))} unwarned"
            )
        domains = attribution.get("domains") or {}
        domain_table = (
            f"<p class=\"meta\">correlated domain outages</p>"
            f"{_domain_table(domains)}" if domains else ""
        )
        proactive_note = ""
        if pro.get("migrations"):
            # hazard-driven checkpoint-then-migrate (ISSUE 8): what the
            # moves insured against vs what they cost — avoided-loss
            # measurable against lost-work in one line
            proactive_note = (
                f"<p class=\"meta\">proactive migration: "
                f"{int(pro['migrations'])} moves avoided "
                f"{_esc(_fmt_dur(pro.get('avoided_s', 0.0)))} of exposed "
                f"work for {_esc(_fmt_dur(pro.get('overhead_s', 0.0)))} "
                f"checkpoint+restore overhead paid</p>"
            )
        fault_panel = f"""
<h2>Faults</h2>
<div class="panel">
  <p class="meta">{s['faults']} outages · {s['revocations']} revocations ·
  {s['repairs']} repairs · {_esc(_fmt_dur(lost_total))} work
  lost{warned_note}</p>
  {_stacked_goodput_bar(gp)}
  {proactive_note}
  {_fault_kind_table(attribution)}
  {domain_table}
</div>"""

    integrity = (
        f"stream integrity: max analyzer-vs-engine progress drift "
        f"{analysis.max_progress_drift:.2e}"
        + (
            f" · {analysis.counts.get('anomalies', 0)} anomalies"
            if analysis.counts.get("anomalies") else ""
        )
    )

    return f"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{_esc(title)}</title>
<style>{_CSS}</style>
</head>
<body class="viz-root">
<h1>{_esc(title)}</h1>
<p class="meta">{' · '.join(meta_bits)}</p>
<div class="kpis">{''.join(kpis)}</div>

<h2>Chip occupancy</h2>
<div class="panel">
{_occupancy_chart(analysis, occ_pts, t_max, total_chips, alert_marks)}
</div>
{alerts_panel}

<h2>Pending queue</h2>
<div class="panel">
{_step_series_chart(pend_pts, series_var='--series-2', label='jobs waiting',
                    t_max=t_max, area=False)}
</div>

<h2>Wait &amp; completion-time CDF</h2>
<div class="panel">
{_cdf_chart([('wait', '--series-1', waits), ('JCT', '--series-2', jcts)],
            'wait and JCT CDF')}
</div>
{attrib_panel}
{net_panel}
{fault_panel}
{_engine_health_panel(analysis, selfprof)}
<h2>Distributions</h2>
<div class="panel">{_dist_table(dists)}</div>

<h2>Slowest jobs</h2>
<div class="panel">{_slowest_jobs_table(analysis)}</div>

<p class="integrity">{_esc(integrity)}</p>
</body>
</html>
"""


def write_report(
    analysis: RunAnalysis,
    path,
    *,
    title: Optional[str] = None,
    selfprof: Optional[dict] = None,
    alerts: Optional[List[dict]] = None,
) -> Path:
    out = Path(path)
    if out.parent and not out.parent.exists():
        out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(render_report(
        analysis, title=title, selfprof=selfprof, alerts=alerts
    ))
    return out
