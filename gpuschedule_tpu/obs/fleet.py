"""Cross-process observability: trace-context propagation + federation.

The ISSUE 16 layer that makes the pooled twin observable end to end.
Every observability surface built before it — tracer spans, metrics
registry, selfprof phases, Perfetto export — is single-process, while the
system's parallelism lives in :class:`~gpuschedule_tpu.sim.pool.WorkerPool`
child processes whose restore/fork/replay time, crashes, and retries were
invisible except as a terse ``retry_log``.  This module closes that gap
with three pieces:

**Trace-context propagation.**  A :class:`FleetCollector` on the parent
side hands every pool task a picklable :class:`TaskContext` envelope
``(trace_id, parent_span_id, task)``.  The pool ships each task through
:func:`run_task`, which arms a per-task :class:`WorkerTelemetry` harness
in the child — a child :class:`~gpuschedule_tpu.obs.tracer.Tracer`, a
child :class:`~gpuschedule_tpu.obs.metrics.MetricsRegistry`, and (when the
task attaches one) a :class:`~gpuschedule_tpu.obs.selfprof.PhaseProfiler`
— and returns the telemetry alongside the result.  Task code reaches the
active harness through :func:`task_span` / :func:`task_profiler` /
:func:`active`; all three are no-ops costing one module-global read when
no harness is armed, so the disarmed path stays byte-identical.

**Deterministic federation.**  The collector keys every returned payload
by *task index*, not arrival order: worker registries merge into the
parent's via :meth:`MetricsRegistry.merge` in task order, selfprof blocks
merge per worker via :func:`~gpuschedule_tpu.obs.selfprof.merge_profiles`,
and the merged document is a pure function of the payloads — adversarial
completion order cannot change a byte of it.  The retry discipline is
structural: telemetry only travels with a *successful* result, so a
crashed attempt's partial telemetry dies with its process, a raised
attempt's telemetry is never returned, and a retired incarnation's late
success is dropped by the pool before it reaches the collector.  Nothing
double-counts, nothing is lost.

**One merged Perfetto document.**  :meth:`FleetCollector.document` emits
a single Chrome/Perfetto trace: the parent's enqueue/dispatch/reassemble
spans on process 1, one named process per worker, and every worker span
carrying ``trace_id`` / ``parent_span_id`` args linking it back to the
parent query — load it in ui.perfetto.dev and the whole fleet is one
timeline.  Per-process clocks are not comparable across processes (each
anchors at its own first-task origin), which is the standard multi-process
Chrome-trace situation; within a process, spans lay out in real order.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from gpuschedule_tpu.obs.metrics import MetricsRegistry
from gpuschedule_tpu.obs.tracer import NULL_SPAN, Tracer

# --------------------------------------------------------------------- #
# the propagated envelope


@dataclass(frozen=True)
class TaskContext:
    """The picklable trace-context envelope every fleet task ships:
    which trace it belongs to, which parent span dispatched it, and its
    task index (the deterministic federation key)."""

    trace_id: str
    parent_span_id: str
    task: int


# --------------------------------------------------------------------- #
# worker side: the per-task telemetry harness

# lint: allow[GS601] deliberately process-local: the active per-task harness of THIS worker process (ISSUE 16)
_ACTIVE: Optional["WorkerTelemetry"] = None
# lint: allow[GS601] deliberately process-local: one wall anchor per worker process so its tasks lay out sequentially on one track (ISSUE 16)
_PROC_ORIGIN: Optional[float] = None


class WorkerTelemetry:
    """One task's child telemetry: a tracer anchored at the worker
    process's first-task origin, a fresh registry, and an optional
    self-profiler the task may attach.  ``payload()`` is the picklable
    blob that rides home with the result."""

    def __init__(self, ctx: TaskContext):
        global _PROC_ORIGIN
        if _PROC_ORIGIN is None:
            # lint: allow[GS101] the wall anchor of this worker's trace track; replay output never reads it
            _PROC_ORIGIN = time.perf_counter()
        self.ctx = ctx
        self.tracer = Tracer(enabled=True, origin=_PROC_ORIGIN)
        self.registry = MetricsRegistry()
        self.profiler = None

    def attach_profiler(self):
        """A fresh :class:`PhaseProfiler` for this task (idempotent per
        task) — sweep cells hand it to their ``Simulator`` so every cell
        returns an engine-phase profile."""
        if self.profiler is None:
            from gpuschedule_tpu.obs.selfprof import PhaseProfiler

            self.profiler = PhaseProfiler()
        return self.profiler

    def payload(self) -> dict:
        prof = None
        if self.profiler is not None and self.profiler.total_wall_s > 0:
            prof = self.profiler.profile()
        return {
            "trace_id": self.ctx.trace_id,
            "parent_span_id": self.ctx.parent_span_id,
            "task": self.ctx.task,
            "spans": _span_events(self.tracer, {
                "trace_id": self.ctx.trace_id,
                "parent_span_id": self.ctx.parent_span_id,
                "task": self.ctx.task,
            }),
            "registry": self.registry.snapshot(),
            "selfprof": prof,
        }


def active() -> Optional[WorkerTelemetry]:
    """The harness of the task currently executing in THIS process, or
    ``None`` — the one-global-read hook instrumented task code keys on."""
    return _ACTIVE


def task_span(name: str, **attrs):
    """A span on the active harness's tracer; :data:`NULL_SPAN` (free)
    when no harness is armed — call sites stay branch-free."""
    t = _ACTIVE
    if t is None:
        return NULL_SPAN
    return t.tracer.span(name, **attrs)


def task_profiler():
    """A :class:`PhaseProfiler` attached to the active harness, or
    ``None`` when no harness is armed (the default-off path)."""
    t = _ACTIVE
    if t is None:
        return None
    return t.attach_profiler()


def run_task(fn, ctx: TaskContext, args: tuple) -> dict:
    """The module-level (picklable) wrapper the pool ships when a fleet
    collector is armed: arm a harness, run the task under a root span,
    and return ``{"result", "telemetry"}``.  Exceptions propagate with
    the harness already disarmed — a failed attempt returns no telemetry,
    which is the whole retry discipline."""
    global _ACTIVE
    telem = WorkerTelemetry(ctx)
    _ACTIVE = telem
    try:
        with telem.tracer.span("task", cat="fleet", task=ctx.task):
            result = fn(*args)
    finally:
        _ACTIVE = None
    return {"result": result, "telemetry": telem.payload()}


def _span_events(tracer: Tracer, extra_args: dict) -> List[dict]:
    """Serialize a tracer's spans to plain Chrome ``X`` events (ts/dur in
    µs), each stamped with ``extra_args`` — the propagated trace context.
    Sorted by (ts, depth, name) so the serialization is a pure function
    of the spans."""
    events = []
    for sp in sorted(
        tracer.spans, key=lambda s: (s.wall_start, s.depth, s.name)
    ):
        args: Dict[str, Any] = dict(sp.attrs)
        if sp.sim_start is not None:
            args["sim_start_s"] = sp.sim_start
        if sp.sim_end is not None:
            args["sim_end_s"] = sp.sim_end
        args.update(extra_args)
        events.append({
            "name": sp.name,
            "cat": sp.cat or "span",
            "ph": "X",
            "ts": round(max(0.0, sp.wall_start) * 1e6, 3),
            "dur": round(max(0.0, sp.wall_dur) * 1e6, 3),
            "args": args,
        })
    return events


# --------------------------------------------------------------------- #
# parent side: the collector


class FleetCollector:
    """Parent-side half of the layer: mints task envelopes, records the
    parent span tree (enqueue → dispatch → reassemble), absorbs worker
    payloads keyed by task index, and federates them into one registry /
    selfprof block / Perfetto document.

    ``registry`` is the collector's parent-side registry — hand it to
    :class:`WorkerPool` so ``pool_worker_respawns_total`` and
    ``pool_task_retries_total`` land next to the federated worker
    families in the merged document.
    """

    def __init__(self, trace_id, *, parent: str = "parent"):
        self.trace_id = str(trace_id)
        self.parent = parent
        self.tracer = Tracer(enabled=True)
        self.registry = MetricsRegistry()
        self._telemetry: Dict[int, dict] = {}
        self._worker_of: Dict[int, Any] = {}

    # -- parent spans / envelopes -------------------------------------- #

    def span(self, name: str, **attrs):
        """One parent-side span; its ``span_id`` arg is the name worker
        spans link back to via ``parent_span_id``."""
        return self.tracer.span(
            name, cat="fleet", trace_id=self.trace_id, span_id=name, **attrs
        )

    def envelope(self, task: int) -> TaskContext:
        return TaskContext(self.trace_id, "dispatch", int(task))

    def task(self, fn, idx: int, args: tuple):
        """The pool adapter: ``(wrapped_fn, wrapped_args)`` for task
        ``idx`` — what :meth:`WorkerPool.map` ships when armed."""
        return run_task, (fn, self.envelope(idx), tuple(args))

    # -- absorption ----------------------------------------------------- #

    def absorb(self, idx: int, worker, payload: dict):
        """Record one successful task's telemetry (keyed by task index —
        arrival order is irrelevant) and unwrap its result."""
        self._telemetry[idx] = payload["telemetry"]
        self._worker_of[idx] = worker
        return payload["result"]

    def run_local(self, fn, idx: int, args: tuple):
        """The serial counterpart of a pooled task: run ``fn`` in-process
        under the same harness, absorb under worker key ``"local"``."""
        return self.absorb(idx, "local", run_task(fn, self.envelope(idx), args))

    # -- federation ------------------------------------------------------ #

    @staticmethod
    def worker_key(worker) -> str:
        return "worker-local" if worker == "local" else f"worker-{worker}"

    def merge_into(self, registry: MetricsRegistry) -> MetricsRegistry:
        """Fold every absorbed worker registry into ``registry`` in task
        order — counter sums, bucket-wise histograms, label-family union
        (see :meth:`MetricsRegistry.merge`)."""
        for idx in sorted(self._telemetry):
            registry.merge(self._telemetry[idx]["registry"])
        return registry

    def merged_registry(self) -> MetricsRegistry:
        """Parent-side counters plus all worker registries, merged fresh
        (safe to call repeatedly — never mutates ``self.registry``)."""
        return self.merge_into(MetricsRegistry().merge(self.registry))

    def profiles(self) -> Dict[str, List[dict]]:
        """Selfprof blocks grouped by worker key, each worker's blocks in
        task order — the :func:`merge_profiles` input."""
        per: Dict[str, List[dict]] = {}
        for idx in sorted(self._telemetry):
            block = self._telemetry[idx].get("selfprof")
            if block:
                key = self.worker_key(self._worker_of[idx])
                per.setdefault(key, []).append(block)
        return per

    def worker_events(self) -> Dict[str, List[dict]]:
        per: Dict[str, List[dict]] = {}
        for idx in sorted(self._telemetry):
            key = self.worker_key(self._worker_of[idx])
            per.setdefault(key, []).extend(self._telemetry[idx]["spans"])
        return per

    # -- the merged document --------------------------------------------- #

    def document(self) -> dict:
        """One merged Perfetto/Chrome trace document: parent process +
        one named process per worker, plus the federated ``registry`` and
        per-worker ``selfprof`` blocks (Perfetto ignores extra keys)."""
        from gpuschedule_tpu.obs.perfetto import fleet_trace_events

        workers = self.worker_events()
        doc: dict = {
            "traceEvents": fleet_trace_events(
                _span_events(self.tracer, {}), workers,
                parent_name=self.parent,
            ),
            "displayTimeUnit": "ms",
            "otherData": {
                "clock": "wall",
                "exporter": "gpuschedule_tpu.obs.fleet",
                "trace_id": self.trace_id,
            },
            "federation": {
                "tasks": len(self._telemetry),
                "workers": sorted(workers),
            },
        }
        reg_json = self.merged_registry().to_json()
        if reg_json:
            doc["registry"] = reg_json
        prof = self.profiles()
        if prof:
            from gpuschedule_tpu.obs.selfprof import merge_profiles

            doc["selfprof"] = merge_profiles(prof)
        return doc

    def write(self, path) -> dict:
        doc = self.document()
        out = Path(path)
        if out.parent and not out.parent.exists():
            out.parent.mkdir(parents=True, exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        return doc
