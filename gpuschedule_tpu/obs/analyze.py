"""Run analytics: streaming per-job lifecycle reconstruction from the
JSONL event log (ISSUE 3 tentpole).

The Philly study draws its conclusions from *derived* analytics —
queueing-delay distributions, utilization over time, failure attribution —
not raw traces.  This module is that layer for our event streams: a
single-pass analyzer that replays the ``MetricsLog`` transition log
through per-job state machines

    submit -> queued -> running -> (preempt | migrate | resize | rebind |
    fault-revoke)* -> done / failed / killed   (or rejected / cut off)

in O(active jobs) working state, validating every transition, and derives

- wait / run / JCT / slowdown / preemption-count distributions with exact
  p50/p95/p99 (``obs.metrics.exact_quantile``, numpy-equivalent);
- demand-occupancy and fragmentation time series (time-weighted means are
  integrated incrementally, exact under sample decimation);
- a fault-attribution table (per fault kind: outages, revocations, lost
  work, lost chip-seconds, restore cost charged) whose goodput
  decomposition **closes bit-exactly against SimResult.goodput**: every
  per-job lifecycle event carries the engine's cumulative progress
  snapshot (``"prog"``, exact floats, sim/engine.py), and
  :meth:`RunAnalysis.goodput` sums the per-job legs in arrival order —
  the same order and the same arithmetic ``SimResult`` uses.

Streams are versioned: the first record must be a schema header
(``{"schema": 1, "run_id", "seed", "policy", "config_hash", ...}``,
written by ``MetricsLog(run_meta=...)``).  A missing or mismatched header
raises :class:`SchemaError`; a second header mid-stream means two runs
were concatenated and raises :class:`StreamError` — both instead of
silently producing garbage (ISSUE 3 satellite).

Pure stdlib, jax-free, streaming: a Philly-scale events.jsonl never needs
to be held in memory (per-*finished*-job output records are kept — the
same footprint as jobs.csv — but full event payloads are not).
"""

from __future__ import annotations

import gzip
import hashlib
import json
import math
import os
import sqlite3
import tempfile
from pathlib import Path
from dataclasses import dataclass, field, fields as dataclass_fields
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from gpuschedule_tpu.obs.metrics import quantile_sorted

# The event-stream schema version this analyzer understands.  Kept as the
# reader's own constant (the writer's is sim/metrics.py:EVENT_SCHEMA;
# tests pin the two equal) so the obs layer never imports the sim package
# at module load.
SCHEMA_VERSION = 1

# Analyzer lifecycle states (strings, not the sim's JobState enum: the
# analyzer must work on a bare JSONL file with no sim objects in sight).
QUEUED, RUNNING, SUSPENDED = "queued", "running", "suspended"
TERMINAL_STATES = ("done", "failed", "killed", "rejected")

# Causal-attribution leg names (ISSUE 5) — the reader's own copy of
# sim/job.py's WAIT_CAUSES / RUN_LEGS (same no-sim-import rule as
# SCHEMA_VERSION; tests pin the two equal).  WAIT_CAUSES blame queued/
# suspended intervals; RUN_LEGS split running time into the work-
# equivalent and its slowdown stretches.
WAIT_CAUSES = (
    "admission", "capacity", "fault-outage", "net-outage", "policy-preempt"
)
RUN_LEGS = ("work", "policy-share", "net-degraded", "straggler", "overhead")

_QUANTS = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


class SchemaError(ValueError):
    """The stream's header is missing, unversioned, or from a schema this
    analyzer does not understand."""


class StreamError(ValueError):
    """The stream is structurally invalid: an impossible lifecycle
    transition, non-monotonic time, or two concatenated runs."""


class StreamCursor:
    """Incremental JSONL ingestion (ISSUE 15): feed text chunks as they
    arrive and get back complete parsed records.

    The one invariant that makes a *growing* file tailable: a trailing
    line that has not yet received its newline is **retained, not
    parsed and not skipped** — mid-record truncation is the normal state
    of a stream another process is still appending to, so the fragment
    waits in the cursor and is re-read whole once the writer completes
    it.  A *complete* line that fails to parse is corruption and raises
    :class:`StreamError` immediately.

    One cursor serves every ingestion mode: ``analyze_file`` (one-shot,
    both memory modes) drives it to :meth:`finish`, where a leftover
    fragment IS corruption; ``watch --follow`` feeds whatever bytes the
    poll loop found and simply keeps going.

    Yields ``(lineno, raw_line, record)`` tuples so tailing consumers
    (the watchtower's flight recorder) can keep the writer's exact bytes
    without re-serializing."""

    def __init__(self, name: str = "<stream>"):
        self.name = name
        self.lineno = 0
        self._pending = ""

    @property
    def pending(self) -> str:
        """The retained (newline-less) tail fragment, if any."""
        return self._pending

    def _parse(self, line: str) -> Optional[Tuple[int, str, dict]]:
        self.lineno += 1
        stripped = line.strip()
        if not stripped:
            return None
        try:
            return (self.lineno, line, json.loads(stripped))
        except json.JSONDecodeError as e:
            raise StreamError(
                f"{self.name}:{self.lineno}: truncated or corrupt JSONL "
                f"record ({e}) — was the writer killed mid-record?"
            ) from None

    def feed(self, chunk: str) -> List[Tuple[int, str, dict]]:
        """Absorb one text chunk; return the complete records it closed.
        One split per chunk (never a per-line re-slice of the remaining
        buffer) keeps ingestion linear in the stream length — this is
        the hot path of every ``analyze``/``report``/``compare``
        invocation, not just the tail loop."""
        out: List[Tuple[int, str, dict]] = []
        lines = (self._pending + chunk).split("\n")
        self._pending = lines.pop()
        for line in lines:
            item = self._parse(line)
            if item is not None:
                out.append(item)
        return out

    def finish(self, *, strict: bool = True) -> List[Tuple[int, str, dict]]:
        """End of stream.  A retained fragment is parsed if it is a whole
        record (the writer just never wrote the final newline); a
        fragment that does not parse raises under ``strict`` (one-shot
        readers: the file is truncated) and is dropped otherwise (a tail
        the live writer never completed before the watcher gave up)."""
        tail, self._pending = self._pending, ""
        if not tail.strip():
            return []
        if strict:
            item = self._parse(tail)
            return [item] if item is not None else []
        try:
            return [x for x in (self._parse(tail),) if x is not None]
        except StreamError:
            return []


def iter_jsonl_items(path) -> Iterator[Tuple[int, str, dict]]:
    """One-shot streaming iteration over an events.jsonl(.gz) file via
    :class:`StreamCursor` — the same incremental reader the watchtower
    tails with, driven to completion: unreadable files and truncated or
    corrupt records raise :class:`StreamError` (the CLI's exit-2
    "not comparable" bucket, never a raw traceback).  Yields
    ``(lineno, raw_line, record)`` so consumers that need the writer's
    exact bytes (the watchtower's flight recorder) share this one
    drive loop."""
    opener = gzip.open if str(path).endswith(".gz") else open
    cursor = StreamCursor(name=str(path))
    try:
        with opener(path, "rt") as f:
            while True:
                chunk = f.read(1 << 16)
                if not chunk:
                    break
                for item in cursor.feed(chunk):
                    yield item
        for item in cursor.finish():
            yield item
    except (OSError, EOFError) as e:
        # gzip corruption raises BadGzipFile (an OSError) or EOFError
        raise StreamError(f"cannot read event stream {path}: {e}") from None


def iter_jsonl_records(path) -> Iterator[dict]:
    """:func:`iter_jsonl_items` without the raw-byte plumbing — the
    record view ``analyze_file`` and the report/compare CLIs consume."""
    return (rec for _, _, rec in iter_jsonl_items(path))


def config_hash(config: dict) -> str:
    """Stable 12-hex-digit digest of a run configuration (sorted-key JSON
    over the given mapping).  The CLI hashes the *experiment* config —
    cluster + trace + fault spec, deliberately **not** the policy — so two
    runs are header-compatible for ``compare`` exactly when they replayed
    the same world, whichever policy scheduled it."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


@dataclass
class RunHeader:
    """The stream's identity record (first line of events.jsonl)."""

    schema: int
    run_id: str = ""
    seed: Optional[int] = None
    policy: str = ""
    config_hash: str = ""
    total_chips: Optional[int] = None
    extra: dict = field(default_factory=dict)

    _KNOWN = ("schema", "run_id", "seed", "policy", "config_hash", "total_chips")

    @classmethod
    def from_record(cls, rec: dict) -> "RunHeader":
        schema = rec.get("schema")
        if not isinstance(schema, int):
            raise SchemaError(f"header schema must be an int, got {schema!r}")
        if schema != SCHEMA_VERSION:
            raise SchemaError(
                f"event stream is schema {schema}; this analyzer understands "
                f"schema {SCHEMA_VERSION} — re-capture the stream or use a "
                f"matching version"
            )
        return cls(
            schema=schema,
            run_id=str(rec.get("run_id", "")),
            seed=rec.get("seed"),
            policy=str(rec.get("policy", "")),
            config_hash=str(rec.get("config_hash", "")),
            total_chips=rec.get("total_chips"),
            extra={k: v for k, v in rec.items() if k not in cls._KNOWN},
        )

    def to_json(self) -> dict:
        out = {
            "schema": self.schema, "run_id": self.run_id, "seed": self.seed,
            "policy": self.policy, "config_hash": self.config_hash,
            "total_chips": self.total_chips,
        }
        out.update(self.extra)
        return out


@dataclass
class JobRecord:
    """One job's reconstructed lifecycle (the analyzer's jobs.csv row)."""

    job_id: str
    order: int                    # arrival order == trace submit order
    submit_t: float
    chips: int = 0                # requested gang size
    duration: Optional[float] = None
    status: Optional[str] = None
    first_start_t: Optional[float] = None
    end_t: Optional[float] = None
    end_state: Optional[str] = None   # done/failed/killed/rejected; None = unfinished
    starts: int = 0
    preempts: int = 0
    migrations: int = 0
    rebinds: int = 0
    faults: int = 0
    # shared-fabric contention (net/): how often this job's bandwidth was
    # re-priced, its time-integrated allocated bandwidth (Gbps x s while
    # running), and its offered demand — the bandwidth-share table inputs
    net_updates: int = 0
    bw_gbps_s: float = 0.0
    demand_gbps: Optional[float] = None
    # adaptive routing (ISSUE 8): how often this flow's weighted uplink
    # set changed (a degraded sibling shed onto survivors, or healed)
    reroutes: int = 0
    run_time: float = 0.0         # seconds spent RUNNING
    queue_time: float = 0.0       # seconds QUEUED after submit (incl. requeues)
    suspended_time: float = 0.0   # seconds SUSPENDED (preempted with resume intent)
    # exact cumulative legs from the engine's last "prog" snapshot
    work: float = 0.0
    service: float = 0.0
    lost_service: float = 0.0
    overhead_service: float = 0.0
    lost_work: float = 0.0
    # causal attribution (ISSUE 5): the engine's exact cumulative per-leg
    # seconds, adopted from event "blame" snapshots (empty when the run
    # was captured without attribution)
    delay_legs: Dict[str, float] = field(default_factory=dict)
    # three-way split of the folded net-degraded stretch (ISSUE 15,
    # retiring the PR-5 omission): the analyzer derives it from the
    # locality ladder the stream already carries — placement events
    # (start/migrate/resize/rebind) carry the allocation's STATIC factor,
    # `net` re-prices carry the DYNAMIC one, and the `track` prefix says
    # whether a static toll is the multislice DCN term or a GPU locality
    # tier.  Keys: `dcn-contention` (speed x (static - dynamic)),
    # `multislice-toll` / `gpu-locality` (speed x (1 - static)).  Empty
    # whenever every factor was 1.0.
    net_legs: Dict[str, float] = field(default_factory=dict)

    def wait(self) -> Optional[float]:
        if self.first_start_t is None:
            return None
        return self.first_start_t - self.submit_t

    def jct(self) -> Optional[float]:
        if self.end_t is None:
            return None
        return self.end_t - self.submit_t

    def slowdown(self) -> Optional[float]:
        j = self.jct()
        if j is None or not self.duration:
            return None
        return j / max(self.duration, 1e-9)

    def mean_bw_gbps(self) -> Optional[float]:
        """Time-weighted mean allocated DCN bandwidth while running (None
        for jobs the contention model never priced)."""
        if not self.net_updates or self.run_time <= 0.0:
            return None
        return self.bw_gbps_s / self.run_time

    # ---- causal decompositions (ISSUE 5) ----------------------------- #

    def wait_legs(self) -> Dict[str, float]:
        """The queued-interval blame legs alone (WAIT_CAUSES keys)."""
        return {
            k: self.delay_legs[k]
            for k in sorted(self.delay_legs)
            if k in WAIT_CAUSES
        }

    def run_legs(self) -> Dict[str, float]:
        """The running-interval slowdown legs alone (RUN_LEGS keys)."""
        return {
            k: self.delay_legs[k]
            for k in sorted(self.delay_legs)
            if k not in WAIT_CAUSES
        }

    def attributed_wait(self) -> float:
        """This job's wait as the decomposition's own arithmetic states
        it: the ordered (sorted-key) sum of the blame legs.  The per-job
        closure is definitional — ``sum(wait_legs().values())`` IS this
        number — while the analyzer's independently integrated
        ``queue_time + suspended_time`` cross-checks it to float dust
        (``wait_residual``)."""
        total = 0.0
        for k in sorted(self.delay_legs):
            if k in WAIT_CAUSES:
                total += self.delay_legs[k]
        return total

    def attributed_jct(self) -> float:
        """All legs summed (sorted keys): waits + work + slowdown
        stretches + overhead — the slowdown decomposition's JCT."""
        total = 0.0
        for k in sorted(self.delay_legs):
            total += self.delay_legs[k]
        return total

    def wait_residual(self) -> Optional[float]:
        """Attributed wait minus the analyzer's own state integration
        (float re-association dust on healthy streams; a large value
        means the stream is missing a transition)."""
        if not self.delay_legs:
            return None
        return self.attributed_wait() - (self.queue_time + self.suspended_time)

    def jct_residual(self) -> Optional[float]:
        """Attributed JCT minus ``end_t - submit_t`` for finished jobs
        (same dust-vs-missing-transition meaning as wait_residual)."""
        j = self.jct()
        if j is None or not self.delay_legs:
            return None
        return self.attributed_jct() - j

    @property
    def finished(self) -> bool:
        return self.end_state in ("done", "failed", "killed")

    def to_json(self) -> dict:
        return {
            "job_id": self.job_id, "submit_t": self.submit_t,
            "chips": self.chips, "wait": self.wait(), "jct": self.jct(),
            "run_time": self.run_time, "queue_time": self.queue_time,
            "suspended_time": self.suspended_time,
            "slowdown": self.slowdown(), "end_state": self.end_state,
            "starts": self.starts, "preempts": self.preempts,
            "migrations": self.migrations, "faults": self.faults,
            "work": self.work, "service": self.service,
            "lost_service": self.lost_service,
            "overhead_service": self.overhead_service,
            "lost_work": self.lost_work,
            "net_updates": self.net_updates,
            "mean_bw_gbps": self.mean_bw_gbps(),
            "demand_gbps": self.demand_gbps,
            **({"reroutes": self.reroutes} if self.reroutes else {}),
            **({"delay_legs": dict(self.delay_legs)} if self.delay_legs else {}),
            **({"net_legs": dict(self.net_legs)} if self.net_legs else {}),
        }


# --------------------------------------------------------------------- #
# bounded-memory spill store (ISSUE 9 streaming analyzer)

_REC_FIELDS = tuple(f.name for f in dataclass_fields(JobRecord))


class JobSpill:
    """Disk spill for finished :class:`JobRecord` rows — the bounded-
    memory analyzer's job store (ISSUE 9).

    Finished records leave RAM as soon as their job leaves the active
    set; a sqlite temp file keeps them keyed by arrival ``order`` (so
    every aggregate still sums in arrival order — the bit-exact closure
    arithmetic) alongside the finished-job metric columns the exact-
    quantile second pass sorts server-side.  Rows round-trip through
    JSON, which reproduces every float bit-for-bit, and ``delay_legs``
    key order (the engine's accrual order — it IS the emitted-dict
    order) survives because JSON objects preserve insertion order."""

    def __init__(self) -> None:
        # spill-flush telemetry (ISSUE 10): batched INSERT count — the
        # analyzer-side analogue of the engine caches' hit counters
        self.flushes = 0
        self._dir = tempfile.TemporaryDirectory(prefix="gstpu-analyze-")
        self._db = sqlite3.connect(os.path.join(self._dir.name, "jobs.sqlite"))
        self._db.execute("PRAGMA journal_mode=OFF")
        self._db.execute("PRAGMA synchronous=OFF")
        self._db.execute(
            "CREATE TABLE jobs ("
            "ord INTEGER PRIMARY KEY, fin INTEGER, wait REAL, run_time REAL,"
            "jct REAL, slowdown REAL, preempts REAL, faults REAL, data TEXT)"
        )
        self._buf: List[tuple] = []
        self.count = 0

    def add(self, rec: JobRecord) -> None:
        state = {name: getattr(rec, name) for name in _REC_FIELDS}
        fin = rec.finished
        self._buf.append((
            rec.order, 1 if fin else 0,
            rec.wait() if fin else None,
            rec.run_time if fin else None,
            rec.jct() if fin else None,
            rec.slowdown() if fin else None,
            float(rec.preempts) if fin else None,
            float(rec.faults) if fin else None,
            json.dumps(state),
        ))
        self.count += 1
        if len(self._buf) >= 512:
            self.flush()

    def flush(self) -> None:
        if self._buf:
            self.flushes += 1
            self._db.executemany(
                "INSERT INTO jobs VALUES (?,?,?,?,?,?,?,?,?)", self._buf
            )
            self._buf.clear()

    @staticmethod
    def _load(data: str) -> JobRecord:
        state = json.loads(data)
        state["delay_legs"] = dict(state.get("delay_legs") or {})
        return JobRecord(**state)

    def iter_records(self) -> Iterator[JobRecord]:
        self.flush()
        cur = self._db.execute("SELECT data FROM jobs ORDER BY ord")
        for (data,) in cur:
            yield self._load(data)

    def get(self, order: int) -> JobRecord:
        self.flush()
        row = self._db.execute(
            "SELECT data FROM jobs WHERE ord = ?", (order,)
        ).fetchone()
        if row is None:
            raise IndexError(order)
        return self._load(row[0])

    def sorted_metric(self, column: str) -> Tuple[int, Iterator[float]]:
        """(count, ascending iterator) over one finished-job metric —
        sqlite's external sort keeps the analyzer's resident memory flat
        however long the stream was."""
        self.flush()
        (n,) = self._db.execute(
            f"SELECT COUNT(*) FROM jobs WHERE fin = 1 AND {column} IS NOT NULL"
        ).fetchone()
        cur = self._db.execute(
            f"SELECT {column} FROM jobs "
            f"WHERE fin = 1 AND {column} IS NOT NULL ORDER BY {column}"
        )
        return n, (v for (v,) in cur)


class SpilledJobs(Sequence):
    """Arrival-order view over a :class:`JobSpill` with the list surface
    :class:`RunAnalysis` consumers use (iteration, ``len``, indexing).
    Each pass re-reads the store, so any number of aggregate scans run at
    constant resident memory."""

    def __init__(self, spill: JobSpill):
        self._spill = spill

    def __len__(self) -> int:
        return self._spill.count

    def __iter__(self) -> Iterator[JobRecord]:
        return self._spill.iter_records()

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self[i] for i in range(*index.indices(len(self)))]
        n = len(self)
        if index < 0:
            index += n
        if not 0 <= index < n:
            raise IndexError(index)
        return self._spill.get(index)


@dataclass
class _Active:
    """Per-job in-flight reconstruction state (the O(active jobs) part)."""

    rec: JobRecord
    state: str = QUEUED
    t_state: float = 0.0       # when the current state was entered
    cause: Optional[str] = None  # blame of the open queued interval (ISSUE 5)
    chips_alloc: int = 0
    speed: float = 0.0
    locality: float = 1.0
    # the net-degraded split's inputs (ISSUE 15): the STATIC locality of
    # the current placement (what the last start/migrate/resize/rebind
    # carried — `net` re-prices move `locality` but never this), and
    # whether that placement is a GPU gang (track prefix), which names
    # the static toll's cause
    static_loc: float = 1.0
    gpu: bool = False
    slow: float = 1.0          # straggler multiplier (faults/, ISSUE 6)
    overhead_left: float = 0.0
    t_prog: float = 0.0        # time of the last adopted snapshot
    bw_gbps: float = 0.0       # current net/ bandwidth allocation
    t_bw: float = 0.0          # time the current allocation was set
    # priced checkpoint writes (ISSUE 6): per-job write cost and period
    # from the arrival record, so the drift guard can mirror the engine's
    # work/overhead split
    ckpt_w: float = 0.0
    ckpt_every: float = math.inf


def _stat_block(values: Sequence[float]) -> dict:
    """Exact distribution summary for one metric: n/mean/max + p50/p95/p99.
    One sort serves every quantile (Philly-scale lists are large)."""
    if not values:
        return {"n": 0, "mean": None, "max": None,
                **{name: None for name, _ in _QUANTS}}
    s = sorted(float(v) for v in values)
    return {
        "n": len(s),
        "mean": sum(s) / len(s),
        "max": s[-1],
        **{name: quantile_sorted(s, q) for name, q in _QUANTS},
    }


def _stat_block_sorted(n: int, ascending: Iterable[float]) -> dict:
    """:func:`_stat_block` from a pre-sorted value stream of known length
    (the spill store's server-side ORDER BY): one pass captures the sum
    (same ascending addition order as the in-memory sort), the max (last
    value), and the straddling order statistics each quantile needs —
    then interpolates with :func:`quantile_sorted`'s exact formula, so
    the result dict is bit-identical to the in-memory one."""
    if n == 0:
        return {"n": 0, "mean": None, "max": None,
                **{name: None for name, _ in _QUANTS}}
    wanted: Dict[int, float] = {}
    for _, q in _QUANTS:
        h = (n - 1) * q
        i = int(math.floor(h))
        wanted[i] = 0.0
        if i + 1 < n:
            wanted[i + 1] = 0.0
    total = 0.0
    last = 0.0
    for idx, v in enumerate(ascending):
        v = float(v)
        total += v
        if idx in wanted:
            wanted[idx] = v
        last = v
    out = {"n": n, "mean": total / n, "max": last}
    for name, q in _QUANTS:
        h = (n - 1) * q
        i = int(math.floor(h))
        g = h - i
        if g == 0.0 or i + 1 >= n:
            out[name] = float(wanted[i])
        else:
            a, b = float(wanted[i]), float(wanted[i + 1])
            # numpy _lerp anchor switch — quantile_sorted's own formula
            out[name] = b - (b - a) * (1.0 - g) if g >= 0.5 else a + (b - a) * g
    return out


@dataclass
class RunAnalysis:
    """Everything :func:`analyze_events` derives from one stream."""

    header: Optional[RunHeader]
    jobs: List[JobRecord]                       # arrival order
    num_events: int = 0
    end_t: float = 0.0
    counts: Dict[str, int] = field(default_factory=dict)
    util_series: List[Tuple[float, int, int, int]] = field(default_factory=list)
    fault_kinds: Dict[str, dict] = field(default_factory=dict)
    fault_timeline: List[dict] = field(default_factory=list)
    mean_occupancy: Optional[float] = None      # time-weighted used/total
    mean_fragmentation: Optional[float] = None  # time-weighted free/total while demand waits
    mean_pending: float = 0.0                   # time-weighted queue length
    max_progress_drift: float = 0.0             # analyzer-vs-engine integration check
    # shared-fabric telemetry (net/): per-link load series reconstructed
    # from "netlink" events — (t, used_gbps, capacity_gbps) change points —
    # and the exact time-weighted mean utilization per link
    net_links: Dict[str, List[Tuple[float, float, float]]] = field(
        default_factory=dict)
    net_link_means: Dict[str, float] = field(default_factory=dict)
    # cluster-side sampling (ISSUE 5): periodic ``sample`` events as
    # (t, physical_used, unhealthy, pending) change points, plus the exact
    # time-weighted mean *physical* occupancy — the series the report
    # overlays on the demand series (divergence = overlay packing; the
    # ROADMAP PR-3 demand-only-occupancy omission, retired)
    sample_series: List[Tuple[float, int, int, int]] = field(
        default_factory=list)
    # proactive checkpoint-and-migrate (ISSUE 8): aggregate of the
    # ``proactive`` payloads riding migrate events — moves taken, the
    # work a revocation at each move instant would have rolled back
    # (avoided loss), and the write+restore overhead actually paid.
    # Empty when the run never migrated proactively.
    proactive: Dict[str, float] = field(default_factory=dict)
    mean_phys_occupancy: Optional[float] = None
    # engine cache telemetry (ISSUE 10): the trailing ``cache`` record a
    # cache-telemetry-armed run emits — {cache: {outcome: count}}; empty
    # for runs captured without the flag.  The report's Engine-health
    # panel renders the hit-rate table from it.
    cache_stats: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # bounded-memory mode (ISSUE 9): the spill store behind ``jobs`` when
    # the stream was analyzed with one — distributions() then sorts each
    # metric server-side instead of materializing value lists
    _spill: Optional[JobSpill] = field(
        default=None, repr=False, compare=False)
    # memoized derived views (report/compare each read them several times;
    # at Philly scale recomputing means redundant full scans and sorts)
    _goodput_cache: Optional[Dict[str, float]] = field(
        default=None, repr=False, compare=False)
    _dist_cache: Optional[Dict[str, dict]] = field(
        default=None, repr=False, compare=False)
    _delay_cache: Optional[Dict[str, float]] = field(
        default=None, repr=False, compare=False)
    _attrib_cache: Optional[dict] = field(
        default=None, repr=False, compare=False)

    # ------------------------------------------------------------------ #
    def goodput(self) -> Dict[str, float]:
        """The fault-attribution closure: per-job service legs (engine
        snapshots, exact floats) summed in arrival order with the same
        arithmetic ``SimResult`` uses — equal to ``SimResult.goodput`` to
        the last float (the golden tests pin this for all eight policies)."""
        if self._goodput_cache is not None:
            return dict(self._goodput_cache)
        attained = sum(r.service for r in self.jobs)
        lost = sum(r.lost_service for r in self.jobs)
        overhead = sum(r.overhead_service for r in self.jobs)
        self._goodput_cache = {
            "useful_chip_s": attained - lost,
            "lost_chip_s": lost,
            "restart_overhead_chip_s": overhead,
            "total_chip_s": attained + overhead,
        }
        return dict(self._goodput_cache)

    def distributions(self) -> Dict[str, dict]:
        """Wait/run/JCT/slowdown/preempt-count distributions over finished
        jobs, with exact p50/p95/p99 (numpy-equivalent linear quantiles).
        In bounded-memory mode each metric streams from the spill store's
        server-side sort — identical floats (same ascending multiset, same
        ordered sum, same interpolation), no value lists in RAM."""
        if self._dist_cache is not None:
            return self._dist_cache
        if self._spill is not None:
            self._dist_cache = {
                name: _stat_block_sorted(*self._spill.sorted_metric(col))
                for name, col in (
                    ("wait", "wait"), ("run", "run_time"), ("jct", "jct"),
                    ("slowdown", "slowdown"), ("preempt_count", "preempts"),
                    ("fault_count", "faults"),
                )
            }
            return self._dist_cache
        fin = [r for r in self.jobs if r.finished]
        waits = [w for w in (r.wait() for r in fin) if w is not None]
        slow = [s for s in (r.slowdown() for r in fin) if s is not None]
        self._dist_cache = {
            "wait": _stat_block(waits),
            "run": _stat_block([r.run_time for r in fin]),
            "jct": _stat_block([j for j in (r.jct() for r in fin) if j is not None]),
            "slowdown": _stat_block(slow),
            "preempt_count": _stat_block([float(r.preempts) for r in fin]),
            "fault_count": _stat_block([float(r.faults) for r in fin]),
        }
        return self._dist_cache

    def delay_by_cause(self) -> Dict[str, float]:
        """The wait/slowdown-decomposition closure (ISSUE 5): per-leg
        seconds summed over jobs in arrival order with sorted keys per
        job — the engine's exact floats (adopted from event ``blame``
        snapshots) added with the same arithmetic ``SimResult`` uses, so
        this equals ``SimResult.delay_by_cause`` to the last float for
        all eight policies, with and without faults/net (the golden
        attribution tests pin it).  Empty for attribution-free runs."""
        if self._delay_cache is not None:
            return dict(self._delay_cache)
        out: Dict[str, float] = {}
        for r in self.jobs:
            for k in sorted(r.delay_legs):
                out[k] = out.get(k, 0.0) + r.delay_legs[k]
        self._delay_cache = out
        return dict(out)

    def attribution(self) -> dict:
        """The cluster lost-time-by-cause table: where the cluster's time
        went, cause by cause.

        - ``wait_s`` / ``run_s``: the per-leg aggregate in seconds
          (``delay_by_cause`` split into blame causes vs running legs);
        - ``chip_demand_wait_s``: blame legs weighted by each job's
          requested gang (chip-demand-seconds stuck in queue per cause);
        - ``lost_chip_s`` / ``restart_overhead_chip_s``: the fault and
          overhead legs in chip-seconds, taken verbatim from
          :meth:`goodput` — which is exactly ``SimResult.goodput``, so
          the table *closes against SimResult's own arithmetic*;
        - residuals: ``max_wait_residual`` / ``max_jct_residual``, the
          worst per-job gap between the decomposition totals and the
          independently reconstructed wait/JCT (float dust on healthy
          streams).

        Memoized like goodput/distributions: report + to_json each read
        it, and every computation rescans the full job list."""
        if self._attrib_cache is not None:
            return dict(self._attrib_cache)
        legs = self.delay_by_cause()
        gp = self.goodput()
        chip_wait: Dict[str, float] = {}
        for r in self.jobs:
            for k in sorted(r.delay_legs):
                if k in WAIT_CAUSES:
                    chip_wait[k] = chip_wait.get(k, 0.0) + r.chips * r.delay_legs[k]
        wait_res = [abs(v) for v in (r.wait_residual() for r in self.jobs)
                    if v is not None]
        jct_res = [abs(v) for v in (r.jct_residual() for r in self.jobs)
                   if v is not None]
        self._attrib_cache = {
            "wait_s": {k: v for k, v in sorted(legs.items())
                       if k in WAIT_CAUSES},
            "run_s": {k: v for k, v in sorted(legs.items())
                      if k not in WAIT_CAUSES},
            "chip_demand_wait_s": dict(sorted(chip_wait.items())),
            "lost_chip_s": gp["lost_chip_s"],
            "restart_overhead_chip_s": gp["restart_overhead_chip_s"],
            "max_wait_residual": max(wait_res, default=0.0),
            "max_jct_residual": max(jct_res, default=0.0),
        }
        return dict(self._attrib_cache)

    def fault_attribution(self) -> dict:
        """Per-fault-kind attribution plus the exact goodput closure.

        ``kinds[kind].lost_chip_s`` sums per-revocation snapshot deltas, so
        the per-kind split telescopes to the per-job totals only up to
        float re-association; ``closure_residual`` reports that gap (zero
        or ~1e-9-relative), while ``goodput`` itself is exact."""
        gp = self.goodput()
        kinds_lost = sum(k["lost_chip_s"] for k in self.fault_kinds.values())
        return {
            "kinds": {k: dict(v) for k, v in sorted(self.fault_kinds.items())},
            "goodput": gp,
            "kinds_lost_chip_s": kinds_lost,
            "closure_residual": kinds_lost - gp["lost_chip_s"],
            "domains": self.domain_outages(),
        }

    def domain_outages(self) -> Dict[str, dict]:
        """The per-domain outage table (correlated ``kind="domain"``
        faults, ISSUE 6): scope label -> hierarchy level, outage count,
        and total down seconds.  Permanent outages (duration ``"inf"``)
        and outages still open at the stream's end are capped at the
        observed horizon, so ``down_s`` is the downtime the replay
        actually saw."""
        out: Dict[str, dict] = {}
        for f in self.fault_timeline:
            if f.get("kind") != "domain":
                continue
            scope = str(f.get("scope"))
            row = out.setdefault(scope, {
                "level": f.get("level"), "outages": 0, "down_s": 0.0,
            })
            row["outages"] += 1
            d = f.get("duration")
            horizon = max(0.0, self.end_t - float(f.get("t", 0.0)))
            if d is None or d == "inf":
                dur = horizon
            else:
                dur = min(float(d), horizon)
            row["down_s"] += dur
        return dict(sorted(out.items()))

    def net_degraded_split(self) -> Dict[str, float]:
        """The folded ``net-degraded`` leg split three ways (ISSUE 15,
        retiring the PR-5 omission): per-segment seconds summed over jobs
        in arrival order with sorted keys — ``dcn-contention`` (the gap
        between the placement's static factor and the ``net``-repriced
        dynamic one), ``multislice-toll`` (the static DCN term a
        multislice gang pays even on an idle fabric), ``gpu-locality``
        (scattered-gang placement tiers).  Derived by the analyzer from
        the stream's locality ladder — no new event fields, so historical
        streams split retroactively.  On attribution-armed runs the three
        segments sum to ``delay_by_cause()['net-degraded']`` up to float
        re-association.  Empty when no job ever ran below locality 1.0."""
        out: Dict[str, float] = {}
        for r in self.jobs:
            for k in sorted(r.net_legs):
                out[k] = out.get(k, 0.0) + r.net_legs[k]
        return out

    def network(self) -> dict:
        """The network panel's data: per-link utilization series/means and
        the per-job bandwidth-share table (jobs the contention model
        priced at least once).  Empty links + jobs means the run had no
        net model (or no multislice job ever ran)."""
        jobs = []
        for r in self.jobs:
            if not r.net_updates:
                continue
            mean_bw = r.mean_bw_gbps()
            jobs.append({
                "job_id": r.job_id,
                "chips": r.chips,
                "net_updates": r.net_updates,
                "mean_bw_gbps": mean_bw,
                "demand_gbps": r.demand_gbps,
                "mean_share": (
                    mean_bw / r.demand_gbps
                    if mean_bw is not None and r.demand_gbps else None
                ),
            })
        return {
            "links": {
                name: {
                    "mean_util": self.net_link_means.get(name),
                    "samples": len(series),
                    "last_capacity_gbps": series[-1][2] if series else None,
                }
                for name, series in sorted(self.net_links.items())
            },
            "jobs": jobs,
            "net_degraded_split": self.net_degraded_split(),
        }

    def summary(self) -> Dict[str, object]:
        """Headline scalars (the compare surface).  avg_jct and makespan
        use SimResult's exact formulas so the two cross-check bit-for-bit."""
        fin = [r for r in self.jobs if r.finished]
        jcts = [j for j in (r.jct() for r in fin) if j is not None]
        makespan = (
            max(r.end_t for r in fin) - min(r.submit_t for r in fin)
            if fin else 0.0
        )
        states = {s: 0 for s in TERMINAL_STATES}
        for r in self.jobs:
            if r.end_state is not None:
                states[r.end_state] = states.get(r.end_state, 0) + 1
        gp = self.goodput()
        useful_frac = (
            gp["useful_chip_s"] / gp["total_chip_s"]
            if gp["total_chip_s"] > 0 else None
        )
        return {
            "num_jobs": len(self.jobs),
            "num_finished": len(fin),
            "num_unfinished": sum(
                1 for r in self.jobs if r.end_state is None
            ),
            "num_rejected": states["rejected"],
            "num_done": states["done"],
            "num_failed": states["failed"],
            "num_killed": states["killed"],
            "avg_jct": sum(jcts) / len(jcts) if jcts else 0.0,
            "makespan": makespan,
            "mean_occupancy": self.mean_occupancy,
            "mean_fragmentation": self.mean_fragmentation,
            "mean_pending": self.mean_pending,
            "preemptions": self.counts.get("preempt", 0),
            "migrations": self.counts.get("migrate", 0),
            "faults": self.counts.get("fault", 0),
            "revocations": self.counts.get("revoke", 0),
            "repairs": self.counts.get("repair", 0),
            "net_reprices": self.counts.get("net", 0),
            "useful_frac": useful_frac,
            **{f"goodput_{k}": v for k, v in gp.items()},
            # attribution-armed runs only: the same delay_<cause>_s keys
            # SimResult.summary() emits (closure surface), plus physical
            # occupancy when the run was sampled
            **{
                f"delay_{k.replace('-', '_')}_s": v
                for k, v in sorted(self.delay_by_cause().items())
            },
            **(
                {"mean_phys_occupancy": self.mean_phys_occupancy}
                if self.mean_phys_occupancy is not None else {}
            ),
        }

    def _json_head(self) -> dict:
        """Everything :meth:`to_json` carries except the ``jobs`` array —
        the part :meth:`write_json` serializes up front (every value here
        is already aggregate-sized, never per-job)."""
        return {
            "header": self.header.to_json() if self.header else None,
            "num_events": self.num_events,
            "end_t": self.end_t,
            "summary": self.summary(),
            "distributions": self.distributions(),
            "faults": self.fault_attribution(),
            "fault_timeline": list(self.fault_timeline),
            "network": self.network(),
            "attribution": (
                self.attribution() if self.delay_by_cause() else None
            ),
            "samples": {
                "n": len(self.sample_series),
                "mean_phys_occupancy": self.mean_phys_occupancy,
            },
            "cache_stats": self.cache_stats or None,
            "max_progress_drift": self.max_progress_drift,
        }

    def to_json(self) -> dict:
        return {
            **self._json_head(),
            "jobs": [r.to_json() for r in self.jobs],
        }

    # ------------------------------------------------------------------ #

    def write_json(self, path) -> Path:
        """Write :meth:`to_json` to ``path`` byte-for-byte as
        ``json.dumps(self.to_json(), indent=2, sort_keys=True)`` would —
        but with the ``jobs`` array **streamed one record at a time**, so
        a bounded-memory analysis (the ISSUE 9 spill store) dumps a
        million-job document without ever materializing the job list or
        the document string (the last PR-9 streaming gap, ISSUE 10
        satellite).  Pinned byte-identical by tests/test_analyze_stream.

        Mechanics: the document head is serialized with the ``jobs``
        value replaced by a sentinel string, split at the sentinel, and
        each job record is serialized independently and re-indented to
        the depth the enclosing dump would have used — ``json.dumps``
        with a fixed ``indent`` is position-independent, so the splice
        reproduces the monolithic serialization exactly."""
        sentinel = "__GSTPU_JOBS_STREAM__"
        head = dict(self._json_head(), jobs=sentinel)
        text = json.dumps(head, indent=2, sort_keys=True)
        prefix, suffix = text.split(json.dumps(sentinel), 1)
        out = Path(path)
        if out.parent and not out.parent.exists():
            out.parent.mkdir(parents=True, exist_ok=True)
        with open(out, "w") as f:
            f.write(prefix)
            wrote = False
            for r in self.jobs:
                chunk = json.dumps(r.to_json(), indent=2, sort_keys=True)
                f.write("[\n" if not wrote else ",\n")
                f.write("\n".join("    " + ln for ln in chunk.splitlines()))
                wrote = True
            f.write("\n  ]" if wrote else "[]")
            f.write(suffix)
        return out


# --------------------------------------------------------------------- #

# event kind -> states it is legal to arrive from (None key: job may not
# exist yet).  Kinds touching a job not in this table are stream errors.
_LEGAL_FROM = {
    "start": (QUEUED, SUSPENDED),
    "preempt": (RUNNING,),
    "speed": (RUNNING,),
    "migrate": (RUNNING,),
    "resize": (RUNNING,),
    "rebind": (RUNNING,),
    "revoke": (RUNNING,),
    "finish": (RUNNING,),
    # cutoff also reaches queued/suspended jobs: attribution-armed runs
    # emit a horizon record for every waiting job so the stream provably
    # extends to max_time (the wait closure depends on it)
    "cutoff": (RUNNING, QUEUED, SUSPENDED),
    "net": (RUNNING,),
    # adaptive routing (ISSUE 8): the flow's weighted uplink set moved
    # onto different siblings (rate/factor changes ride "net" events)
    "reroute": (RUNNING,),
    # straggler re-price (faults/, ISSUE 6): the gang's rate changed
    # because a chip under it degraded or recovered
    "slow": (RUNNING,),
    # spot pre-revoke notice: may charge emergency-checkpoint overhead
    "warn": (RUNNING,),
}


def analyze_events(
    events: Iterable[dict],
    *,
    require_header: bool = True,
    strict: bool = True,
    drift_tol: float = 1e-5,
    max_util_samples: int = 200_000,
    spill: Optional[JobSpill] = None,
) -> RunAnalysis:
    """Single-pass lifecycle reconstruction of one event stream.

    ``require_header=False`` admits bare pre-header streams (Python-API
    captures without ``run_meta``) — ``compare`` never does, ``report``
    only with an explicit flag.  ``strict`` turns impossible transitions,
    time going backwards, and analyzer-vs-engine progress drift beyond
    ``drift_tol`` (relative) into :class:`StreamError`; non-strict mode
    tallies them in ``counts["anomalies"]`` instead.

    ``spill`` (ISSUE 9 bounded-memory mode, usually via ``analyze_file
    (low_memory=True)``): finished job records leave RAM for the given
    :class:`JobSpill` as their jobs finish, and the returned analysis's
    ``jobs`` is a lazy arrival-order view over the store — every derived
    number (aggregates, exact quantiles, report tables) is byte-identical
    to the in-memory analysis, at O(active jobs) resident memory.
    """
    header: Optional[RunHeader] = None
    jobs: List[JobRecord] = []
    n_jobs = 0
    active: Dict[str, _Active] = {}
    counts: Dict[str, int] = {}
    fault_kinds: Dict[str, dict] = {}
    fault_timeline: List[dict] = []
    util_series: List[Tuple[float, int, int, int]] = []
    stride, sample_i = 1, 0
    # net/ link telemetry: change-point series per link plus an exact
    # piecewise-constant utilization integral ([last_t, last_util, area])
    net_links: Dict[str, List[Tuple[float, float, float]]] = {}
    net_acc: Dict[str, List[float]] = {}
    # cluster samples (ISSUE 5): physical-occupancy series + its exact
    # piecewise-constant integral ([last_t, last_used, area, first_t])
    sample_series: List[Tuple[float, int, int, int]] = []
    samp_acc: Optional[List[float]] = None
    # proactive checkpoint-and-migrate aggregate (ISSUE 8)
    proactive: Dict[str, float] = {}
    # trailing engine cache-telemetry record (ISSUE 10)
    cache_stats: Dict[str, dict] = {}

    used = running_n = pending_n = 0
    last_t: Optional[float] = None
    last_used = last_pending = 0
    occ_area = frag_area = pend_area = horizon = 0.0
    max_drift = 0.0
    n_events = 0
    end_t = 0.0

    def bad(msg: str) -> None:
        if strict:
            raise StreamError(msg)
        counts["anomalies"] = counts.get("anomalies", 0) + 1

    def kind_row(kind: str) -> dict:
        row = fault_kinds.get(kind)
        if row is None:
            row = fault_kinds[kind] = {
                "faults": 0, "revocations": 0, "lost_work_s": 0.0,
                "lost_chip_s": 0.0, "restore_charged_s": 0.0,
                "warned_revocations": 0, "lost_work_warned_s": 0.0,
            }
        return row

    def adopt_snapshot(a: _Active, ev: dict, t: float, rollback: float = 0.0) -> None:
        """Take the engine's exact cumulative legs; first cross-check them
        against this analyzer's own integration of the interval since the
        previous snapshot (payload-sufficiency guard: if the stream lacked
        a transition, the drift shows it).  ``rollback`` is the work a
        revoke rolled back before its snapshot was taken."""
        nonlocal max_drift
        prog = ev.get("prog")
        if prog is None:
            return
        r = a.rec
        if a.state == RUNNING:
            dt = t - a.t_prog
            burn = min(a.overhead_left, dt)
            e = a.speed * a.locality * a.slow
            run = dt - burn
            if a.ckpt_w > 0.0 and e > 0.0 and 0.0 < a.ckpt_every < math.inf:
                # priced checkpoint writes: mirror the engine's steady-
                # state write-share split (sim/job.py advance)
                run -= run * (e * a.ckpt_w) / (a.ckpt_every + e * a.ckpt_w)
            expect = r.work + e * run - rollback
            drift = abs(expect - prog["work"]) / (1.0 + abs(expect))
            if drift > max_drift:
                max_drift = drift
            if drift > drift_tol:
                bad(
                    f"progress drift {drift:.3e} for {r.job_id} at t={t} "
                    f"(expected work {expect}, snapshot {prog['work']}): "
                    "the stream is missing a transition"
                )
            if run > 0.0:
                # net-degraded three-way split (ISSUE 15): the same
                # productive span the engine's RUN_LEGS arithmetic
                # charges, split along the locality ladder — the static
                # toll (placement-carried factor) vs the contention gap
                # (static minus the `net`-repriced dynamic factor)
                if a.static_loc != 1.0:
                    nl = r.net_legs
                    key = "gpu-locality" if a.gpu else "multislice-toll"
                    nl[key] = (
                        nl.get(key, 0.0)
                        + a.speed * (1.0 - a.static_loc) * run
                    )
                if a.locality != a.static_loc:
                    nl = r.net_legs
                    nl["dcn-contention"] = (
                        nl.get("dcn-contention", 0.0)
                        + a.speed * (a.static_loc - a.locality) * run
                    )
        r.work = prog["work"]
        r.service = prog["service"]
        r.lost_service = prog["lost_service"]
        r.overhead_service = prog["overhead_service"]
        r.lost_work = prog["lost_work"]
        a.overhead_left = prog.get("overhead_left", 0.0)
        a.t_prog = t

    def leave_state(a: _Active, t: float) -> None:
        """Charge the time spent in the state being left to its bucket."""
        dt = t - a.t_state
        if a.state == RUNNING:
            a.rec.run_time += dt
        elif a.state == QUEUED:
            a.rec.queue_time += dt
        else:
            a.rec.suspended_time += dt

    def settle_bw(a: _Active, t: float) -> None:
        """Integrate the job's current bandwidth allocation up to ``t``
        (piecewise-constant between net events, exact)."""
        if a.bw_gbps > 0.0 and t > a.t_bw:
            a.rec.bw_gbps_s += a.bw_gbps * (t - a.t_bw)
        a.t_bw = t

    def adopt_blame(a: _Active, ev: dict) -> None:
        """Take the engine's exact cumulative attribution legs (ISSUE 5) —
        the ``blame`` analogue of the ``prog`` adoption above: snapshots
        replace the analyzer's view wholesale, so every adopted float is
        the engine's own."""
        blame = ev.get("blame")
        if blame is not None:
            a.rec.delay_legs = dict(blame)

    def sample(t: float) -> None:
        """Integrate occupancy/fragmentation/pending exactly (piecewise-
        constant), store a decimation-capped series for the report."""
        nonlocal last_t, last_used, last_pending, occ_area, frag_area
        nonlocal pend_area, horizon, stride, sample_i
        total = header.total_chips if header else None
        if last_t is not None and t > last_t:
            dt = t - last_t
            horizon += dt
            pend_area += last_pending * dt
            if total:
                occ_area += (last_used / total) * dt
                if last_pending > 0:
                    frag_area += (max(0, total - last_used) / total) * dt
        last_t, last_used, last_pending = t, used, pending_n
        if sample_i % stride == 0:
            util_series.append((t, used, running_n, pending_n))
            if len(util_series) > max_util_samples:
                del util_series[::2]
                stride *= 2
        sample_i += 1

    for rec_i, ev in enumerate(events):
        if "schema" in ev:
            if rec_i == 0:
                header = RunHeader.from_record(ev)
                continue
            raise StreamError(
                "second header record mid-stream: this file concatenates "
                "two runs — analyze them separately"
            )
        if rec_i == 0 and require_header:
            raise SchemaError(
                "event stream has no schema header; re-capture with "
                "run identity (CLI --events does) or pass "
                "require_header=False for bare streams"
            )
        kind = ev.get("event")
        if kind is None:
            bad(f"record {rec_i} has no 'event' field")
            continue
        t = float(ev.get("t", 0.0))
        if t < end_t:
            bad(f"time went backwards at record {rec_i}: {end_t} -> {t}")
        end_t = max(end_t, t)
        n_events += 1
        counts[kind] = counts.get(kind, 0) + 1

        if kind == "arrival":
            if ev.get("job") is None or ev.get("job") in active:
                bad(f"bad/duplicate arrival for {ev.get('job')!r}")
                continue
            rec = JobRecord(
                job_id=ev["job"], order=n_jobs, submit_t=t,
                chips=int(ev.get("chips", 0)),
                duration=ev.get("duration"), status=ev.get("status"),
            )
            n_jobs += 1
            if spill is None:
                jobs.append(rec)
            active[rec.job_id] = _Active(
                rec=rec, state=QUEUED, t_state=t, t_prog=t,
                cause=ev.get("cause"),
                ckpt_w=float(ev.get("ckpt_write_s", 0.0)),
                ckpt_every=float(ev.get("ckpt_every", math.inf)),
            )
            pending_n += 1
            sample(t)
            continue
        if kind == "reject":
            if ev.get("job") is None:
                bad("reject without a job id")
                continue
            rec = JobRecord(
                job_id=ev["job"], order=n_jobs, submit_t=t,
                chips=int(ev.get("chips", 0)), end_t=t, end_state="rejected",
            )
            n_jobs += 1
            if spill is None:
                jobs.append(rec)
            else:
                # a reject never re-enters the stream: spill it now
                spill.add(rec)
            continue
        if kind == "fault":
            row = kind_row(str(ev.get("fault", "?")))
            row["faults"] += 1
            entry = {
                "t": t, "scope": ev.get("scope"), "kind": ev.get("fault"),
                "duration": ev.get("duration"), "fid": ev.get("fid"),
            }
            # domain hierarchy tier / degrade fraction ride along only
            # when the record carries them (domain / straggler / link
            # kinds), keeping historical timelines byte-identical
            if "level" in ev:
                entry["level"] = ev["level"]
            if "degrade" in ev:
                entry["degrade"] = ev["degrade"]
            fault_timeline.append(entry)
            continue
        if kind == "repair":
            continue
        if kind == "alert":
            # watchtower detection record (ISSUE 15, obs/watch.py):
            # alerts live in their own side stream, but a combined or
            # hand-concatenated file must analyze cleanly — counted,
            # never a lifecycle transition
            continue
        if kind == "cache":
            # trailing cache-telemetry table (ISSUE 10): the engine's
            # unified {cache: {outcome: count}} harvest — a later record
            # (one per run in practice) replaces an earlier one wholesale
            caches = ev.get("caches")
            if isinstance(caches, dict):
                cache_stats = caches
            continue
        if kind == "netlink":
            name = str(ev.get("link", "?"))
            util = float(ev.get("util", 0.0))
            acc = net_acc.get(name)
            if acc is None:
                net_acc[name] = [t, util, 0.0, t]  # last_t, last_util, area, first_t
            else:
                acc[2] += acc[1] * (t - acc[0])
                acc[0], acc[1] = t, util
            series = net_links.setdefault(name, [])
            series.append((
                t, float(ev.get("used_gbps", 0.0)),
                float(ev.get("capacity_gbps", 0.0)),
            ))
            if len(series) > max_util_samples:
                # decimate but always keep the newest sample — the report
                # reads the link's current capacity off series[-1]
                last = series[-1]
                del series[::2]
                if series[-1] != last:
                    series.append(last)
            continue
        if kind == "sample":
            # periodic cluster-side snapshot (ISSUE 5): PHYSICAL occupancy
            # — overlay guests consume no extra chips here, unlike the
            # demand series integrated from start events above; the gap
            # between the two series is the packing signal
            used_p = int(ev.get("used", 0))
            if samp_acc is None:
                # integral seeded at t=0 with occupancy 0: the cluster is
                # known-empty at run start (the engine skips the t=0
                # sample for exactly that reason), so the physical mean
                # covers the same span as the demand mean instead of
                # starting at the first sample tick
                samp_acc = [0.0, 0.0, 0.0, 0.0]
            samp_acc[2] += samp_acc[1] * (t - samp_acc[0])
            samp_acc[0], samp_acc[1] = t, float(used_p)
            sample_series.append((
                t, used_p, int(ev.get("unhealthy", 0)),
                int(ev.get("pending", 0)),
            ))
            if len(sample_series) > max_util_samples:
                last_s = sample_series[-1]
                del sample_series[::2]
                if sample_series[-1] != last_s:
                    sample_series.append(last_s)
            continue

        # ---- per-job transitions ------------------------------------- #
        a = active.get(ev.get("job"))
        if a is None:
            bad(f"{kind} for unknown/finished job {ev.get('job')!r}")
            continue
        legal = _LEGAL_FROM.get(kind)
        if legal is None:
            bad(f"unknown event kind {kind!r}")
            continue
        if a.state not in legal:
            bad(
                f"illegal transition: {kind} while {a.rec.job_id} is "
                f"{a.state} at t={t}"
            )
            continue

        if kind == "start":
            leave_state(a, t)
            adopt_snapshot(a, ev, t)
            adopt_blame(a, ev)
            a.cause = None  # the engine closed the wait interval at start
            a.rec.starts += 1
            if a.rec.first_start_t is None:
                a.rec.first_start_t = t
            a.state, a.t_state = RUNNING, t
            a.chips_alloc = int(ev.get("chips", a.rec.chips))
            a.speed = float(ev.get("speed", 1.0))
            a.locality = float(ev.get("locality", 1.0))
            # the start event carries the STATIC placement factor (the
            # engine binds it before any net re-price): the net-degraded
            # split's toll baseline; the track prefix names its cause
            a.static_loc = a.locality
            a.gpu = str(ev.get("track", "")).startswith("gpu/")
            # placement-changing events carry slow_factor only when a
            # straggler chip paces the gang; absence means full rate
            a.slow = float(ev.get("slow_factor", 1.0))
            used += a.chips_alloc
            running_n += 1
            # queued AND suspended jobs both sit in the engine's pending
            # set (demand waiting for chips), so any start drains one
            pending_n -= 1
            sample(t)
        elif kind == "preempt":
            leave_state(a, t)
            adopt_snapshot(a, ev, t)
            adopt_blame(a, ev)
            a.cause = ev.get("cause")
            settle_bw(a, t)
            a.bw_gbps = 0.0
            a.rec.preempts += 1
            used -= a.chips_alloc
            running_n -= 1
            a.chips_alloc = 0
            a.speed = 0.0
            a.slow = 1.0
            # engine semantics: suspend=True keeps resume intent (Gandiva),
            # suspend=False demotes back to the pending queue — but both
            # land in the engine's pending set, so both count as demand
            a.state = SUSPENDED if ev.get("suspend", True) else QUEUED
            a.t_state = t
            pending_n += 1
            sample(t)
        elif kind == "speed":
            adopt_snapshot(a, ev, t)
            a.speed = float(ev.get("speed", a.speed))
        elif kind == "slow":
            # straggler re-price (faults/): progress up to t accrued at
            # the OLD slow factor (adopt first), the new factor onward
            adopt_snapshot(a, ev, t)
            a.slow = float(ev.get("slow_factor", a.slow))
        elif kind == "warn":
            # spot pre-revoke notice: a saved emergency checkpoint
            # charged write overhead (the snapshot's overhead_left
            # already includes it); an unsaved notice changes nothing
            adopt_snapshot(a, ev, t)
        elif kind == "net":
            # contention re-price (net/): progress up to t accrued at the
            # OLD locality (adopt first), the new factor applies onward
            adopt_snapshot(a, ev, t)
            settle_bw(a, t)
            a.locality = float(ev.get("locality", a.locality))
            a.bw_gbps = float(ev.get("bw_gbps", 0.0))
            a.rec.net_updates += 1
            if ev.get("demand_gbps") is not None:
                a.rec.demand_gbps = float(ev["demand_gbps"])
        elif kind == "reroute":
            # route choice moved (ISSUE 8): no rate or progress change by
            # itself — share/factor changes arrive as their own "net"
            # event in the same batch
            a.rec.reroutes += 1
        elif kind in ("migrate", "resize", "rebind"):
            adopt_snapshot(a, ev, t)
            # close the bandwidth integral at the placement boundary; the
            # engine emits a follow-up "net" event (possibly bw=0) when
            # the move changed the job's flow-set membership or share
            settle_bw(a, t)
            if kind == "migrate":
                a.rec.migrations += 1
                pro = ev.get("proactive")
                if pro:
                    # hazard-driven checkpoint-then-migrate (ISSUE 8):
                    # aggregate avoided-loss vs paid-overhead for the
                    # fault panel
                    proactive["migrations"] = (
                        proactive.get("migrations", 0) + 1
                    )
                    proactive["avoided_s"] = (
                        proactive.get("avoided_s", 0.0)
                        + float(pro.get("avoided_s", 0.0))
                    )
                    proactive["overhead_s"] = (
                        proactive.get("overhead_s", 0.0)
                        + float(pro.get("write_s", 0.0))
                        + float(pro.get("restore_s", 0.0))
                    )
            elif kind == "rebind":
                a.rec.rebinds += 1
            new_chips = int(ev.get("chips", a.chips_alloc))
            used += new_chips - a.chips_alloc
            a.chips_alloc = new_chips
            a.speed = float(ev.get("speed", a.speed))
            a.locality = float(ev.get("locality", a.locality))
            # placement moved: the carried locality is again the new
            # allocation's STATIC factor (the engine re-binds before
            # emitting; any net re-price follows as its own event)
            a.static_loc = a.locality
            if "track" in ev:
                a.gpu = str(ev.get("track", "")).startswith("gpu/")
            a.slow = float(ev.get("slow_factor", 1.0))
            sample(t)
        elif kind == "revoke":
            prev_lost = a.rec.lost_service
            leave_state(a, t)
            adopt_snapshot(a, ev, t, rollback=float(ev.get("lost_work", 0.0)))
            adopt_blame(a, ev)
            a.cause = ev.get("cause")
            settle_bw(a, t)
            a.bw_gbps = 0.0
            a.rec.faults += 1
            row = kind_row(str(ev.get("fault", "?")))
            row["revocations"] += 1
            row["lost_work_s"] += float(ev.get("lost_work", 0.0))
            row["lost_chip_s"] += a.rec.lost_service - prev_lost
            row["restore_charged_s"] += float(ev.get("restore", 0.0))
            if ev.get("warned"):
                # an emergency checkpoint (spot pre-revoke warning)
                # shrank this rollback: split the lost work so the
                # report can show warned vs unwarned losses
                row["warned_revocations"] += 1
                row["lost_work_warned_s"] += float(ev.get("lost_work", 0.0))
            used -= a.chips_alloc
            running_n -= 1
            a.chips_alloc = 0
            a.speed = 0.0
            a.slow = 1.0
            a.state, a.t_state = QUEUED, t
            pending_n += 1
            sample(t)
        elif kind == "finish":
            leave_state(a, t)
            adopt_snapshot(a, ev, t)
            adopt_blame(a, ev)
            settle_bw(a, t)
            a.rec.end_t = t
            a.rec.end_state = str(ev.get("end_state", "done"))
            used -= a.chips_alloc
            running_n -= 1
            del active[a.rec.job_id]
            if spill is not None:
                # the record is final: it leaves resident memory here —
                # the bounded-memory mode's whole point
                spill.add(a.rec)
            sample(t)
        elif kind == "cutoff":
            # horizon cutoff: final snapshot for a still-active job; the
            # job stays unfinished (end_state None) like its jobs.csv row.
            # For queued/suspended jobs the engine already closed the wait
            # interval into this record's blame snapshot — clear the open
            # cause so the end-of-stream close cannot double-charge it.
            leave_state(a, t)
            adopt_snapshot(a, ev, t)
            adopt_blame(a, ev)
            settle_bw(a, t)
            a.t_state = t
            if a.state != RUNNING:
                a.cause = None

    if header is None and require_header:
        # zero-record stream: the in-loop guard never saw a first record
        raise SchemaError(
            "event stream is empty and has no schema header; nothing to "
            "analyze (pass require_header=False to accept bare streams)"
        )
    sample(end_t)  # close the last integration interval
    # close open wait intervals (ISSUE 5): a job still queued/suspended
    # when the stream ends got no closing event, so charge its open
    # interval to its blame cause here — the engine performs the same
    # close at the same time with the same floats (_close_attribution),
    # which is what keeps the aggregate closure exact for unfinished jobs
    for a in active.values():
        if a.cause is not None and a.state in (QUEUED, SUSPENDED):
            dt = end_t - a.t_state
            if dt > 0.0:
                a.rec.delay_legs[a.cause] = (
                    a.rec.delay_legs.get(a.cause, 0.0) + dt
                )
    if spill is not None:
        # unfinished jobs spill after their open wait interval closed,
        # then the lazy arrival-order view replaces the in-memory list
        for a in active.values():
            spill.add(a.rec)
        spill.flush()
        jobs = SpilledJobs(spill)
    net_link_means: Dict[str, float] = {}
    for name, (last_t_l, util, area, first_t) in sorted(net_acc.items()):
        area += util * (end_t - last_t_l)  # hold the last value to the end
        span = end_t - first_t
        net_link_means[name] = area / span if span > 0 else util
    mean_phys: Optional[float] = None
    if samp_acc is not None and header and header.total_chips:
        last_t_s, last_used_s, area_s, first_t_s = samp_acc
        area_s += last_used_s * (end_t - last_t_s)  # hold last to the end
        span = end_t - first_t_s  # first_t_s is 0.0: the demand mean's span
        mean_phys = (
            (area_s / span) / header.total_chips if span > 0
            else last_used_s / header.total_chips
        )

    analysis = RunAnalysis(
        header=header,
        jobs=jobs,
        num_events=n_events,
        end_t=end_t,
        counts=counts,
        util_series=util_series,
        fault_kinds=fault_kinds,
        fault_timeline=fault_timeline,
        mean_occupancy=occ_area / horizon if horizon > 0 and header and header.total_chips else None,
        mean_fragmentation=frag_area / horizon if horizon > 0 and header and header.total_chips else None,
        mean_pending=pend_area / horizon if horizon > 0 else 0.0,
        max_progress_drift=max_drift,
        net_links=net_links,
        net_link_means=net_link_means,
        sample_series=sample_series,
        mean_phys_occupancy=mean_phys,
        proactive=proactive,
        cache_stats=cache_stats,
        _spill=spill,
    )
    return analysis


def analyze_file(path, *, low_memory: bool = False, **kwargs) -> RunAnalysis:
    """Analyze an events.jsonl file (streaming — constant memory in the
    stream length).  Unreadable files and truncated/corrupt records raise
    :class:`StreamError` — so the CLI's "not comparable" refusal path
    (exit 2) covers them, instead of a raw traceback masquerading as a
    scheduler regression (exit 1).

    Gzip-compressed streams (``*.jsonl.gz``) decompress transparently —
    multi-GB fleet logs are stored compressed, and ``report``/``compare``
    read them the same way (ISSUE 9 satellite).  ``low_memory=True``
    additionally spills finished job records to a sqlite temp store
    (:class:`JobSpill`) so the whole analysis — aggregates, exact
    quantiles, report tables — runs at O(active jobs) resident memory
    with byte-identical output (the ISSUE 9 streaming analyzer).

    Ingestion rides :func:`iter_jsonl_records` — the same incremental
    :class:`StreamCursor` machinery the live-tail watchtower
    (``obs/watch.py``) polls a growing file with, driven here in
    one-shot mode (ISSUE 15 shared-reader refactor)."""
    if low_memory:
        kwargs["spill"] = JobSpill()
    return analyze_events(iter_jsonl_records(path), **kwargs)
