"""Wall-clock phase profiler for the replay loop itself (ISSUE 10).

PRs 7 and 9 made the engine fast with caches and indexes; this module
makes a regression in any of them *diagnosable*.  Where the span tracer
(obs/tracer.py) answers "what did this one batch do", the profiler
answers the fleet-scale question: **which phase of the replay loop is the
wall time going to** — event application, the policy pass, the max-min
net re-solve, fault dispatch, metrics emission, or end-of-run analytics —
so a jobs/sec drop on a noisy box reads as "net re-solve grew 3x", not a
bare suspect number.

Design:

- the engine runs a dedicated ``_run_profiled`` loop body when a
  :class:`PhaseProfiler` is attached (``run --self-profile out.json``) —
  the disabled path never sees a clock read (the tools/check_overhead.py
  ≤2% contract extends to this knob);
- each batch's wall time is bucketed into the :data:`PHASES` with two
  ``perf_counter`` reads per segment; whatever the segments do not cover
  (heap peeks, the quiescence test, loop overhead) lands in ``other``, so
  **the phases sum to the measured total exactly** — the tier-1 smoke
  asserts it;
- alongside the totals the profiler coalesces batches into fixed-size
  chunks and records one span per phase per chunk **through the PR-1
  tracer's span machinery** (a private, always-enabled
  :class:`~gpuschedule_tpu.obs.tracer.Tracer`), giving a
  ui.perfetto.dev-loadable *wall-clock* phase track next to the existing
  sim-time tracks — phase weight over wall time, at bounded span count
  whatever the trace length.

The profile document written by :meth:`PhaseProfiler.write` is both
artifacts in one file: a Chrome trace (``traceEvents``) that Perfetto
loads directly, plus the machine-readable ``selfprof`` summary block
(phase totals/shares, batches, run identity) for trend tooling and the
report's Engine-health panel.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, Optional

from gpuschedule_tpu.obs.tracer import Tracer

# Phase keys, in the order the report's stacked bar lists them.  "other"
# is the residual (total minus every timed segment) — always present, so
# sum(phases) == total_wall_s identically.
PHASES = (
    "event_apply",      # _drain_batch minus nested fault dispatch
    "policy_schedule",  # Policy.schedule invocations
    "net_resolve",      # _net_update (poll + max-min recompute + emits)
    "fault_dispatch",   # _apply_fault / _apply_warning / repair handling
    "advance",          # progress charging + hazard wear integration
    "ledger_sync",      # v2 accounting only (ISSUE 11): the JobLedger's
                        # vectorized per-batch sync replacing the advance
                        # sweep for progress-reading policies; identically
                        # zero under v1 and under v2's fully-lazy path
    "metrics_emit",     # utilization sampling, cutoff/attribution emits
    "analytics",        # end-of-run SimResult assembly
    "other",            # loop overhead: heap peeks, quiescence, dispatch
)

# Batches per coalesced Perfetto chunk: one span per phase per chunk keeps
# the wall-time track at O(batches / chunk) spans — a million-batch replay
# exports ~4k spans per phase, loadable without pain.
_CHUNK_BATCHES = 256


class _PhaseCtx:
    """Reusable ``with profiler.phase(name):`` timer — one per phase, so
    the profiled loop allocates nothing per batch."""

    __slots__ = ("_prof", "_name", "_t0")

    def __init__(self, prof: "PhaseProfiler", name: str):
        self._prof = prof
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "_PhaseCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._prof.add(self._name, time.perf_counter() - self._t0)
        return False


class PhaseProfiler:
    """Accumulates per-phase wall time for one ``Simulator.run`` and
    exports the JSON-profile + Perfetto-wall-track document.

    One profiler instance serves one run: attach a fresh one per
    ``Simulator`` (the engine never resets it)."""

    def __init__(self, *, chunk_batches: int = _CHUNK_BATCHES):
        self.totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.batches = 0
        self.total_wall_s = 0.0
        self.meta: Dict[str, object] = {}
        self._t_run0: Optional[float] = None
        self._t_run1: Optional[float] = None
        self._chunk_batches = max(1, int(chunk_batches))
        self._chunk_t0: Optional[float] = None
        self._chunk_sums: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._chunk_n = 0
        # the PR-1 span machinery, reused verbatim on the wall clock: a
        # private always-on tracer collects one coalesced span per phase
        # per chunk; chrome_events() renders them with the same exporter
        # the `run --spans` timeline uses
        self._tracer = Tracer(enabled=True)
        self._ctx: Dict[str, _PhaseCtx] = {p: _PhaseCtx(self, p) for p in PHASES}

    # ------------------------------------------------------------------ #
    # engine-facing recording

    def start(self, **meta) -> None:
        """Stamp run identity and open the total-wall interval."""
        self.meta.update(meta)
        self._t_run0 = time.perf_counter()
        self._chunk_t0 = self._t_run0

    def phase(self, name: str) -> _PhaseCtx:
        """The reusable ``with``-timer for one phase."""
        return self._ctx[name]

    def add(self, name: str, dt: float) -> None:
        """Charge ``dt`` wall seconds to ``name`` (negative clamps to 0:
        the event-apply segment subtracts nested fault time, and two
        adjacent clock reads may land on the same counter tick)."""
        if dt < 0.0:
            dt = 0.0
        self.totals[name] += dt
        self._chunk_sums[name] += dt

    def total(self, name: str) -> float:
        return self.totals[name]

    def batch_done(self) -> None:
        """Close one engine batch; every ``chunk_batches`` batches the
        accumulated per-phase time flushes as one span per phase."""
        self.batches += 1
        self._chunk_n += 1
        if self._chunk_n >= self._chunk_batches:
            self._flush_chunk()

    def _flush_chunk(self) -> None:
        if self._chunk_n == 0 or self._chunk_t0 is None:
            return
        t0 = self._chunk_t0
        for name in PHASES:
            dt = self._chunk_sums[name]
            if dt > 0.0:
                self._tracer.record(
                    name, wall_start=t0, wall_dur=dt, cat="selfprof",
                    batches=self._chunk_n,
                )
            self._chunk_sums[name] = 0.0
        self._chunk_t0 = time.perf_counter()
        self._chunk_n = 0

    def finish(self) -> None:
        """Close the run: flush the final partial chunk, stamp the total,
        and charge the residual (un-segmented loop overhead) to
        ``other`` so the phase totals sum to the total exactly."""
        self._t_run1 = time.perf_counter()
        if self._t_run0 is None:
            self._t_run0 = self._t_run1
        self.total_wall_s = self._t_run1 - self._t_run0
        timed = sum(self.totals[p] for p in PHASES if p != "other")
        self.totals["other"] += max(0.0, self.total_wall_s - timed
                                    - self.totals["other"])
        # float dust can leave timed > total on a near-empty run; pin the
        # invariant the smoke test asserts by re-deriving the total as the
        # sum — the residual formulation makes the two agree to the ulp
        self.total_wall_s = sum(self.totals.values())
        self._flush_chunk()

    # ------------------------------------------------------------------ #
    # export

    def profile(self) -> dict:
        """The machine-readable summary block."""
        total = self.total_wall_s
        return {
            "total_wall_s": total,
            "batches": self.batches,
            "batches_per_s": (self.batches / total) if total > 0 else None,
            "phases": {
                name: {
                    "total_s": self.totals[name],
                    "share": (self.totals[name] / total) if total > 0 else 0.0,
                }
                for name in PHASES
            },
            **self.meta,
        }

    def chrome_events(self) -> list:
        """The coalesced wall-clock phase spans as Chrome trace events
        (the private tracer's exporter — one tid per thread, ts in µs)."""
        return self._tracer.chrome_events()

    def to_document(self) -> dict:
        """One JSON document that is simultaneously a loadable Chrome
        trace (``traceEvents`` on the wall clock) and the profile summary
        (``selfprof``)."""
        return {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {"clock": "wall", "exporter": "gpuschedule_tpu.obs.selfprof"},
            "selfprof": self.profile(),
        }

    def write(self, path) -> Path:
        out = Path(path)
        if out.parent and not out.parent.exists():
            out.parent.mkdir(parents=True, exist_ok=True)
        with open(out, "w") as f:
            json.dump(self.to_document(), f, indent=2, sort_keys=True)
        return out


def load_profile(path) -> dict:
    """Read back the ``selfprof`` summary block from a profile document
    (the report's ``--selfprof`` input)."""
    with open(path) as f:
        doc = json.load(f)
    prof = doc.get("selfprof")
    if not isinstance(prof, dict) or "phases" not in prof:
        raise ValueError(
            f"{path} is not a self-profile document (no 'selfprof' block "
            "with phase totals — was it written by run --self-profile?)"
        )
    return prof


def merge_profiles(per_worker) -> dict:
    """Deterministic federation of self-profile blocks keyed by worker
    (ISSUE 16): ``per_worker`` maps a worker key (e.g. ``"worker-0"``) to
    the sequence of :meth:`PhaseProfiler.profile` blocks its tasks
    produced, in task order.  Per worker, phase totals / wall totals /
    batch counts are exact sums over its blocks; the ``fleet`` block sums
    across workers (iterated in sorted key order, so the merge is a pure
    function of the inputs — arrival order never matters).  Workers with
    no profile blocks are dropped."""

    def _merged(blocks) -> dict:
        names = list(PHASES)
        for b in blocks:
            for name in b.get("phases", {}):
                if name not in names:
                    names.append(name)
        totals = {
            name: sum(
                b.get("phases", {}).get(name, {}).get("total_s", 0.0)
                for b in blocks
            )
            for name in names
        }
        total = sum(b.get("total_wall_s", 0.0) for b in blocks)
        batches = sum(b.get("batches", 0) for b in blocks)
        return {
            "total_wall_s": total,
            "batches": batches,
            "batches_per_s": (batches / total) if total > 0 else None,
            "tasks": len(blocks),
            "phases": {
                name: {
                    "total_s": totals[name],
                    "share": (totals[name] / total) if total > 0 else 0.0,
                }
                for name in names
            },
        }

    workers = {
        key: _merged(list(per_worker[key]))
        for key in sorted(per_worker)
        if per_worker[key]
    }
    flat = [b for key in sorted(per_worker) for b in per_worker[key]]
    return {"workers": workers, "fleet": _merged(flat)}
