"""Placement schemes (SURVEY.md §2 "Placement schemes", layer 7).

One knob, two cluster flavors:

- **GpuCluster** implements consolidated / random / greedy / topology
  selection natively (which GPUs a gang gets decides its NVLink locality
  tier and therefore its speed factor) — ``with_placement`` just validates
  and sets the scheme.
- **TpuCluster** slices are contiguous whatever happens, so a scheme only
  chooses WHERE the box goes: the origin-order injection point the
  allocator exposes (``hint["origin_order"]``).  ``consolidated`` packs
  toward the origin corner (the allocator default), ``random`` picks a
  random free origin (seeded, deterministic), ``spread`` packs toward the
  far corner — keeping the origin region clear for large slices.
  ``contention`` (net/) searches pods by residual DCN uplink bandwidth
  (``hint["pod_order"]``), steering gangs away from loaded uplinks.

``with_placement(cluster, scheme, seed, net=...)`` is the single entry
point the CLI and experiments use.
"""

from gpuschedule_tpu.placement.schemes import PlacedTpuCluster, with_placement

__all__ = ["with_placement", "PlacedTpuCluster"]
