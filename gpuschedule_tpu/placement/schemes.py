"""Scheme wiring for both cluster flavors."""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from gpuschedule_tpu.cluster.gpu import SCHEMES as GPU_SCHEMES
from gpuschedule_tpu.cluster.gpu import GpuCluster
from gpuschedule_tpu.cluster.tpu import TpuCluster

TPU_SCHEMES = ("consolidated", "random", "spread", "contention")

Origin = Tuple[int, ...]


class PlacedTpuCluster:
    """TpuCluster wrapper that injects an origin-order hint per scheme.

    Delegates everything else to the wrapped cluster, so it satisfies the
    ClusterBase surface (and OverlayMixin's) by forwarding.  Policy-supplied
    hints (overlay, shape, pod) always win over the scheme's origin order.

    The ``contention`` scheme is network-aware: it searches pods in order
    of residual DCN uplink bandwidth (highest first; see
    :meth:`~gpuschedule_tpu.net.model.NetModel.residual_gbps`) before the
    allocator's lexicographic origin scan, steering new gangs away from
    uplinks already loaded with multislice allreduce or ingest traffic.
    Without a :class:`~gpuschedule_tpu.net.model.NetModel` attached, every
    pod scores equally and the scheme degrades to consolidated's pod-index
    order — deterministic either way (no RNG involved).
    """

    def __init__(
        self,
        cluster: TpuCluster,
        scheme: str = "consolidated",
        seed: int = 0,
        net=None,
    ):
        if scheme not in TPU_SCHEMES:
            raise ValueError(f"unknown TPU scheme {scheme!r}; known: {TPU_SCHEMES}")
        self.inner = cluster
        self.scheme = scheme
        self.net = net
        self._rng = random.Random(seed)

    def _origin_order(self, origins: List[Origin]) -> List[Origin]:
        if self.scheme == "random":
            picked = list(origins)
            self._rng.shuffle(picked)
            return picked
        if self.scheme == "spread":
            return sorted(origins, reverse=True)  # far corner first
        return origins  # consolidated/contention: lexicographic first-fit

    def _pod_order(self, pods: List[int]) -> List[int]:
        """Contention scoring: most residual uplink bandwidth first, pod
        index as the deterministic tie-break (ties are the rule when no
        net model is attached or nothing is running)."""
        if self.net is None:
            return sorted(pods)
        return sorted(pods, key=lambda p: (-self.net.residual_gbps(p), p))

    def allocate(self, num_chips: int, *, job=None, hint: Optional[dict] = None):
        if self.scheme == "consolidated":
            merged: dict = {}
        elif self.scheme == "contention":
            merged = {"pod_order": self._pod_order}
        else:
            merged = {"origin_order": self._origin_order}
        if hint:
            merged.update(hint)  # policy hints (overlay etc.) take precedence
        return self.inner.allocate(num_chips, job=job, hint=merged or None)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def __repr__(self) -> str:
        return f"PlacedTpuCluster({self.scheme}, {self.inner!r})"


def with_placement(cluster, scheme: str, *, seed: int = 0, net=None):
    """Attach a placement scheme to a cluster (flavor-appropriate).
    ``net`` (a :class:`~gpuschedule_tpu.net.model.NetModel`) powers the
    TPU ``contention`` scheme's residual-bandwidth scoring; other schemes
    ignore it."""
    if isinstance(cluster, GpuCluster):
        if scheme not in GPU_SCHEMES:
            raise ValueError(f"unknown GPU scheme {scheme!r}; known: {GPU_SCHEMES}")
        cluster.scheme = scheme
        # the caller's seed must govern the scheme's randomness, or seed
        # sweeps through this entry point collapse to one replicate
        cluster._rng = random.Random(seed)
        return cluster
    if isinstance(cluster, TpuCluster):
        if scheme == "consolidated":
            return cluster  # the allocator default; no wrapper needed
        return PlacedTpuCluster(cluster, scheme, seed=seed, net=net)
    raise TypeError(f"no placement schemes for cluster type {type(cluster).__name__}")
