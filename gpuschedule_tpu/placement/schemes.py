"""Scheme wiring for both cluster flavors."""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from gpuschedule_tpu.cluster.gpu import SCHEMES as GPU_SCHEMES
from gpuschedule_tpu.cluster.gpu import GpuCluster
from gpuschedule_tpu.cluster.tpu import TpuCluster

TPU_SCHEMES = ("consolidated", "random", "spread", "contention", "health")

Origin = Tuple[int, ...]


class PlacedTpuCluster:
    """TpuCluster wrapper that injects an origin-order hint per scheme.

    Delegates everything else to the wrapped cluster, so it satisfies the
    ClusterBase surface (and OverlayMixin's) by forwarding.  Policy-supplied
    hints (overlay, shape, pod) always win over the scheme's origin order.

    The ``contention`` scheme is network-aware: it searches pods in order
    of residual DCN uplink bandwidth (highest first; see
    :meth:`~gpuschedule_tpu.net.model.NetModel.residual_gbps`) before the
    allocator's lexicographic origin scan, steering new gangs away from
    uplinks already loaded with multislice allreduce or ingest traffic.
    Without a :class:`~gpuschedule_tpu.net.model.NetModel` attached, every
    pod scores equally and the scheme degrades to consolidated's pod-index
    order — deterministic either way (no RNG involved).  When a hazard
    model is bound to the cluster (faults/hazard.py), the residual score
    is additionally discounted by ``1 + hazard`` per pod, so equal
    bandwidth goes to the healthier pod (hazard 0 everywhere divides by
    1.0 exactly — bit-identical orderings).

    The ``health`` scheme (ISSUE 8) is failure-aware for *every* policy,
    not just Gandiva's post-hoc evacuation: pods are searched in
    ascending ``cluster.hazard_score(("pod", p))`` order (degraded-chip
    penalty plus the bound hazard model's age/wear term; pod index
    breaks ties) and every allocation carries a soft ``avoid_degraded``
    hint, so a gang never lands on a known-slow chip while a clean box
    exists anywhere.
    """

    def __init__(
        self,
        cluster: TpuCluster,
        scheme: str = "consolidated",
        seed: int = 0,
        net=None,
    ):
        if scheme not in TPU_SCHEMES:
            raise ValueError(f"unknown TPU scheme {scheme!r}; known: {TPU_SCHEMES}")
        self.inner = cluster
        self.scheme = scheme
        self.net = net
        self._rng = random.Random(seed)

    def _origin_order(self, origins: List[Origin]) -> List[Origin]:
        if self.scheme == "random":
            picked = list(origins)
            self._rng.shuffle(picked)
            return picked
        if self.scheme == "spread":
            return sorted(origins, reverse=True)  # far corner first
        return origins  # consolidated/contention: lexicographic first-fit

    def _pod_order(self, pods: List[int]) -> List[int]:
        """Contention scoring: most residual uplink bandwidth first, pod
        index as the deterministic tie-break (ties are the rule when no
        net model is attached or nothing is running).  A bound hazard
        model (faults/hazard.py — i.e. a hazard knob was armed)
        additionally discounts each pod's residual by ``1 + hazard``.
        The discount is gated on the BOUND MODEL, not on the score being
        nonzero: a pre-hazard config with stragglers (whose degrade
        penalty alone would make the score nonzero) must keep its PR-7
        pod orderings byte for byte."""
        if self.net is None:
            return sorted(pods)
        if getattr(self.inner, "_hazard_model", None) is None:
            return sorted(pods, key=lambda p: (-self.net.residual_gbps(p), p))
        return sorted(
            pods,
            key=lambda p: (
                -self.net.residual_gbps(p)
                / (1.0 + self.inner.hazard_score(("pod", p))),
                p,
            ),
        )

    def _health_pod_order(self, pods: List[int]) -> List[int]:
        """Health scoring (ISSUE 8): lowest hazard first — degraded-chip
        penalty plus the bound model's age/wear term — pod index as the
        deterministic tie-break (every pod ties at 0.0 on a healthy,
        hazard-free fleet, degrading to consolidated's order)."""
        return sorted(
            pods, key=lambda p: (self.inner.hazard_score(("pod", p)), p)
        )

    def allocate(self, num_chips: int, *, job=None, hint: Optional[dict] = None):
        if self.scheme == "consolidated":
            merged: dict = {}
        elif self.scheme == "contention":
            merged = {"pod_order": self._pod_order}
        elif self.scheme == "health":
            merged = {
                "pod_order": self._health_pod_order,
                "avoid_degraded": True,
            }
        else:
            merged = {"origin_order": self._origin_order}
        if hint:
            merged.update(hint)  # policy hints (overlay etc.) take precedence
        return self.inner.allocate(num_chips, job=job, hint=merged or None)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def __repr__(self) -> str:
        return f"PlacedTpuCluster({self.scheme}, {self.inner!r})"


def with_placement(cluster, scheme: str, *, seed: int = 0, net=None):
    """Attach a placement scheme to a cluster (flavor-appropriate).
    ``net`` (a :class:`~gpuschedule_tpu.net.model.NetModel`) powers the
    TPU ``contention`` scheme's residual-bandwidth scoring; other schemes
    ignore it."""
    if isinstance(cluster, GpuCluster):
        if scheme not in GPU_SCHEMES:
            raise ValueError(f"unknown GPU scheme {scheme!r}; known: {GPU_SCHEMES}")
        cluster.scheme = scheme
        # the caller's seed must govern the scheme's randomness, or seed
        # sweeps through this entry point collapse to one replicate
        cluster._rng = random.Random(seed)
        return cluster
    if isinstance(cluster, TpuCluster):
        if scheme == "consolidated":
            return cluster  # the allocator default; no wrapper needed
        return PlacedTpuCluster(cluster, scheme, seed=seed, net=net)
    raise TypeError(f"no placement schemes for cluster type {type(cluster).__name__}")
