"""Optimus: goodput-driven elastic allocation by marginal gain.

The Optimus scheduler (EuroSys'18; SURVEY.md §3.2) re-plans the whole
cluster each round from per-model goodput curves:

1. every active job's remaining time at k chips is estimated from its
   fitted step-time curve (remaining work scaled by the curve ratio);
2. chips are assigned greedily — every job seeds at ``min_chips``, then
   the upgrade with the best **marginal gain** (remaining-time reduction
   per added chip) wins the next doubling, until the pod is exhausted or
   no upgrade helps (the curve's latency term makes oversized slices
   genuinely unprofitable, so the greedy loop self-terminates);
3. the plan is enacted through the engine: shrink/preempt first to free
   chips, then grow, then start — growth is a slice-size doubling because
   TPU allocations are power-of-two sub-meshes, where the reference grew
   GPU counts one at a time.

Curves come from a :class:`~gpuschedule_tpu.profiler.CurveCache` (device-
free replay, SURVEY.md §4 "pre-fitted curve files") or, with
``online=True``, from the live JAX harness the first time each model is
seen — the reference's "launch a profiling run when a new job type
arrives" loop with jitted step timing instead of NCCL microbenchmarks
(BASELINE.json config #4).
"""

from __future__ import annotations

import heapq
import math
from typing import Dict, Optional

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.profiler.goodput import CurveCache, GoodputCurve
from gpuschedule_tpu.sim.job import Job, JobState

# Fallback when no curve exists and online profiling is off: near-ideal DP
# scaling with a whisper of latency so oversizing still has a cost.
DEFAULT_CURVE = GoodputCurve((1.0, 0.0, 1e-4))


class OptimusPolicy(Policy):
    name = "optimus"

    # stable cause-code tokens (attribution layer, ISSUE 5): the four
    # moves the marginal-gain planner can make on a job each round
    rule_codes = {
        "plan-evicted": "evict",
        "plan-shrink": "shrink",
        "plan-grow": "grow",
        "plan-start": "start",
    }

    def __init__(
        self,
        *,
        curve_cache: Optional[CurveCache] = None,
        online: bool = False,
        round_interval: float = 60.0,
        resize_overhead: float = 10.0,
        min_chips: int = 1,
        profile_ks=(1, 2, 4),
        profile_batch: int = 2,
        profile_seq: int = 32,
        profile_iters: int = 10,
        profile_warmup: int = 2,
        profile_compile_s: float = 30.0,
        profile_time_cost: Optional[float] = None,
    ):
        self.cache = curve_cache
        self.online = online
        self.round_interval = round_interval
        self.resize_overhead = resize_overhead
        self.min_chips = min_chips
        self.profile_ks = tuple(profile_ks)
        self.profile_batch = profile_batch
        self.profile_seq = profile_seq
        self.profile_iters = int(profile_iters)
        self.profile_warmup = int(profile_warmup)
        self.profile_compile_s = float(profile_compile_s)
        # Profiling is NOT free in simulated time (round-3 verdict #5; the
        # reference's profiling runs consume real cluster resources,
        # SURVEY.md §3.2 ★): the first job of each online-profiled model
        # pays a start overhead — its slice is held but makes no training
        # progress, the engine's overhead mechanism — before real work
        # begins.  Cache-hit models pay nothing, so a warm CurveCache is
        # measurably better than a cold one.  By default the charge is
        # DERIVED from the profiling workload itself (round-4 verdict #7:
        # a flat constant ignores that the harness cost scales with
        # profile_ks and iters): per profiled k, one compile plus
        # (warmup + iters) steps at that k's fitted step time.  A float
        # here overrides with the old flat charge.
        self.profile_time_cost = (
            None if profile_time_cost is None else float(profile_time_cost)
        )
        self._curves: Dict[str, GoodputCurve] = {}
        self._profile_charge_pending: Dict[str, float] = {}
        # the scheduled cluster's pod boundary, captured each schedule()
        # call: DCN-cliff planning must use the fleet's real pod size, not
        # the nominal generation pod the curve was profiled against
        self._cluster_pod: Optional[int] = None

    # ------------------------------------------------------------------ #
    # curves

    @staticmethod
    def _curve_key(job: Job) -> str:
        """Cache key for a job's curve: the @sp{s}tp{t}[pp{p}] variant
        when the job declares a parallelism spec, else the bare model
        name — the consumer side of profile_model's variant keys
        (harness.py)."""
        sp = getattr(job, "sp", 1)
        tp = getattr(job, "tp", 1)
        pp = getattr(job, "pp", 1)
        if sp == 1 and tp == 1 and pp == 1:
            return job.model_name
        if pp == 1:
            return f"{job.model_name}@sp{sp}tp{tp}"
        return f"{job.model_name}@sp{sp}tp{tp}pp{pp}"

    def _profile_charge(self, curve: GoodputCurve, ks=None) -> float:
        """Simulated seconds one online-profiling run occupies its slice:
        per profiled k, a compile plus (warmup + iters) steps at the
        fitted step time — so more ks, more iters, or a slower model all
        raise the charge the way they raise the real harness cost."""
        if self.profile_time_cost is not None:
            return self.profile_time_cost
        steps = self.profile_warmup + self.profile_iters
        return sum(
            self.profile_compile_s + steps * curve.step_time(k)
            for k in (self.profile_ks if ks is None else ks)
        )

    def _job_curve(self, job: Job) -> GoodputCurve:
        key = self._curve_key(job)
        curve = self._curves.get(key)
        if curve is not None:
            return curve
        if self.cache is not None and key in self.cache:
            curve = self.cache.get(key)
        elif self.online:
            # the reference's online-profiling boundary (SURVEY.md §3.2 ★):
            # a real measured run, here a jitted train step on live devices
            from gpuschedule_tpu.profiler.harness import profile_model

            sp = getattr(job, "sp", 1)
            tp = getattr(job, "tp", 1)
            pp = getattr(job, "pp", 1)
            unit = sp * tp * pp
            # profile_model requires ks divisible by the replica unit:
            # profile at replica multiples for parallelism-spec jobs
            ks = tuple(k * unit for k in self.profile_ks) if unit > 1 else self.profile_ks
            try:
                curve = profile_model(
                    job.model_name,
                    ks=ks,
                    batch_size=self.profile_batch,
                    seq_len=self.profile_seq,
                    sp=sp,
                    tp=tp,
                    pp=pp,
                    cache=self.cache,
                )
            except ValueError:
                # unmeasurable here (e.g. one replica spans more devices
                # than this host exposes): a degraded curve must not
                # abort the whole simulation — fall back like the
                # offline path, with no profiling charge (nothing ran)
                curve = (
                    self.cache.get(job.model_name)
                    if self.cache is not None and job.model_name in self.cache
                    else DEFAULT_CURVE
                )
            else:
                charge = self._profile_charge(curve, ks=ks)
                if charge > 0.0:
                    self._profile_charge_pending[key] = charge
        elif self.cache is not None and job.model_name in self.cache:
            # offline, no measured variant: the bare-model curve beats the
            # featureless default.  (Online runs never take this branch —
            # the variant deserves its own profile; a bare-model cache hit
            # must not shadow it.)
            curve = self.cache.get(job.model_name)
        else:
            curve = DEFAULT_CURVE
        self._curves[key] = curve
        return curve

    # ------------------------------------------------------------------ #

    def schedule(self, sim) -> Optional[float]:
        self._cluster_pod = getattr(sim.cluster, "pod_chips", None)
        active = [j for j in sim.pending + sim.running if not j.finished]
        if not active:
            return None
        plan = self._plan(sim, active)
        self._enact(sim, plan)
        # Anchor the next tick to the global round grid, NOT now + interval:
        # per-event offsets never coincide, so free-running chains seeded by
        # every arrival/completion would multiply into O(events x horizon)
        # tick storms; grid-aligned ticks land on equal timestamps and the
        # engine batches them into one policy invocation.
        return (math.floor(sim.now / self.round_interval) + 1) * self.round_interval

    # ------------------------------------------------------------------ #
    # planning

    def _remaining_at(self, job: Job, k: int) -> float:
        """Wall seconds to finish job on k chips per its curve (the curve
        ratio rescales the trace-declared reference-speed work).

        Planning uses ``step_time_dcn``: beyond one pod the analytic DCN
        allreduce phase degrades the estimate, so marginal gain sees the
        ICI->DCN cliff — comm-heavy models decline whale growth that
        compute-heavy models accept (round-4 verdict #3)."""
        curve = self._job_curve(job)
        pod = self._cluster_pod
        return (
            job.remaining_work
            * curve.step_time_dcn(k, pod_chips=pod)
            / curve.step_time_dcn(job.num_chips, pod_chips=pod)
        )

    def _gain(self, job: Job, k: int) -> float:
        """Marginal remaining-time reduction per chip for doubling k."""
        return (self._remaining_at(job, k) - self._remaining_at(job, 2 * k)) / k

    def _max_chips(self, sim, job: Job) -> int:
        """Growth ceiling: one pod for curves that carry no DCN model (a
        smooth extrapolation across the pod boundary would overestimate
        multislice gain), the whole fleet for multislice-aware curves —
        the cliff in step_time_dcn is then what self-terminates growth."""
        pod = getattr(sim.cluster, "pod_chips", sim.cluster.total_chips)
        # the payload is what makes the DCN phase computable; the boundary
        # itself comes from the scheduled cluster (_remaining_at)
        if self._job_curve(job).dcn_grad_bytes is not None:
            return sim.cluster.total_chips
        return pod

    def _plan(self, sim, active) -> Dict[str, int]:
        """Greedy marginal-gain chip assignment; returns job_id -> chips."""
        budget = sim.cluster.total_chips
        ordered = sorted(active, key=lambda j: j.arrival_seq)
        plan: Dict[str, int] = {}
        by_id: Dict[str, Job] = {}
        for job in ordered:
            by_id[job.job_id] = job
            # one model replica spans sp*tp*pp chips: a parallelism-spec
            # job cannot seed below its replica size
            k0 = max(
                self.min_chips,
                getattr(job, "sp", 1)
                * getattr(job, "tp", 1)
                * getattr(job, "pp", 1),
            )
            if budget >= k0 and sim.cluster.is_satisfiable(k0):
                plan[job.job_id] = k0
                budget -= k0
            else:
                plan[job.job_id] = 0

        h: list = []
        for job in ordered:
            k = plan[job.job_id]
            if k > 0:
                g = self._gain(job, k)
                if g > 0:
                    heapq.heappush(h, (-g, job.arrival_seq, job.job_id))
        while h and budget > 0:
            _, seq, jid = heapq.heappop(h)
            job = by_id[jid]
            k = plan[jid]
            nk = 2 * k
            cost = nk - k
            if (
                cost > budget
                or nk > self._max_chips(sim, job)
                or not sim.cluster.is_satisfiable(nk)
            ):
                continue
            plan[jid] = nk
            budget -= cost
            g = self._gain(job, nk)
            if g > 0:
                heapq.heappush(h, (-g, seq, jid))
        return plan

    # ------------------------------------------------------------------ #
    # enactment

    def _speed(self, job: Job, k: int) -> float:
        """Enacted progress rate: the PLAIN (DCN-free) curve ratio.  The
        engine multiplies in the DCN toll itself via job.locality_factor
        (cluster `_multislice_speed_factor`); using step_time_dcn here
        would charge a multislice job the toll twice."""
        return self._job_curve(job).speed_factor(k, job.num_chips)

    def _enact(self, sim, plan: Dict[str, int]) -> None:
        ex = self.explaining(sim)

        def why(job: Job, rule: str, k: int):
            if not ex:
                return None
            # the marginal gain that justified (or failed to justify) the
            # planned size: remaining-time reduction per chip of the next
            # doubling, the quantity the greedy planner ranked on
            d = {"planned_chips": k}
            if k > 0:
                d["marginal_gain_s_per_chip"] = round(self._gain(job, k), 6)
            return self.explain(rule, **d)

        # shrink & evict first: frees chips (and boxes) for the growers
        for job in list(sim.running):
            k = plan.get(job.job_id, 0)
            if k == 0:
                sim.preempt(job, suspend=False, why=why(job, "plan-evicted", k))
            elif k < job.allocated_chips:
                sim.resize(
                    job, chips=k, speed=self._speed(job, k),
                    overhead=self.resize_overhead,
                    why=why(job, "plan-shrink", k),
                )
        for job in list(sim.running):
            k = plan.get(job.job_id, 0)
            if k > job.allocated_chips:
                sim.resize(
                    job, chips=k, speed=self._speed(job, k),
                    overhead=self.resize_overhead,
                    why=why(job, "plan-grow", k),
                )
        for job in sorted(sim.pending, key=lambda j: j.arrival_seq):
            k = plan.get(job.job_id, 0)
            if k > 0:
                overhead = self.resize_overhead if job.executed_work > 0.0 else 0.0
                # The first job of a freshly online-profiled model carries
                # the profiling run: its slice is occupied for the derived
                # charge (see _profile_charge) before training progresses.
                key = self._curve_key(job)
                charge = self._profile_charge_pending.get(key, 0.0)
                if (
                    sim.try_start(
                        job, chips=k, speed=self._speed(job, k),
                        overhead=overhead + charge,
                        why=why(job, "plan-start", k),
                    )
                    and charge > 0.0
                ):
                    self._profile_charge_pending.pop(key, None)
                    sim.metrics.count("profiling_runs")
