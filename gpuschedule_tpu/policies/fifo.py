"""FIFO: non-preemptive gang scheduling in arrival order.

Head-of-line blocking is intentional and part of the policy's definition
(SURVEY.md §2 "Policy: FIFO": "Non-preemptive gang scheduling in arrival
order; head-of-line blocks"): if the oldest pending job's gang cannot be
placed, nothing behind it starts, which is what makes FIFO the baseline the
preemptive policies beat.  A ``backfill=True`` variant relaxes that for
comparison runs.
"""

from __future__ import annotations

from typing import Optional

from gpuschedule_tpu.policies.base import Policy


class FifoPolicy(Policy):
    name = "fifo"

    # FIFO (both variants) orders by submit_time alone and never inspects
    # a running job's integrated progress — the v2 accounting engine may
    # skip the per-batch sweep (ISSUE 11; sim/ledger.py)
    reads_progress = False

    # stable cause-code tokens for the attribution layer (ISSUE 5)
    rule_codes = {
        "arrival-order-head": "head",
        "backfill": "backfill",
    }

    def __init__(self, *, backfill: bool = False):
        self.backfill = backfill

    def schedule(self, sim) -> Optional[float]:
        # ``sim.pending`` iterates in arrival order by construction (jobset.py
        # invariant; FIFO never preempts, so no job is ever re-appended out of
        # order) — no per-event sort.
        ex = self.explaining(sim)
        if not self.backfill:
            # Head-of-line: peek the oldest pending job; each successful start
            # removes it from the set, so this is O(1) per start and O(1) per
            # blocked event — no snapshot of a possibly-huge backlog.
            while sim.pending:
                job = sim.pending[0]
                why = (
                    self.explain(
                        "arrival-order-head",
                        waited_s=round(sim.now - job.submit_time, 3),
                    )
                    if ex else None
                )
                if not sim.try_start(job, why=why):
                    break  # head-of-line blocks
            return None
        for job in list(sim.pending):  # backfill scans past blocked heads
            why = (
                self.explain(
                    "backfill",
                    waited_s=round(sim.now - job.submit_time, 3),
                )
                if ex else None
            )
            sim.try_start(job, why=why)
        return None
