"""FIFO: non-preemptive gang scheduling in arrival order.

Head-of-line blocking is intentional and part of the policy's definition
(SURVEY.md §2 "Policy: FIFO": "Non-preemptive gang scheduling in arrival
order; head-of-line blocks"): if the oldest pending job's gang cannot be
placed, nothing behind it starts, which is what makes FIFO the baseline the
preemptive policies beat.  A ``backfill=True`` variant relaxes that for
comparison runs.
"""

from __future__ import annotations

from typing import Optional

from gpuschedule_tpu.policies.base import Policy


class FifoPolicy(Policy):
    name = "fifo"

    def __init__(self, *, backfill: bool = False):
        self.backfill = backfill

    def schedule(self, sim) -> Optional[float]:
        queue = sorted(sim.pending, key=lambda j: (j.submit_time, j.arrival_seq))
        for job in queue:
            if sim.try_start(job):
                continue
            if not self.backfill:
                break  # head-of-line blocks
        return None
