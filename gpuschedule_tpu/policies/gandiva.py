"""Gandiva: time-slicing, packing, and migration-for-defrag.

The three Gandiva mechanisms (OSDI'18; SURVEY.md §3.3) re-targeted to
slice-shaped TPU allocations:

- **Time-slicing**: when demand exceeds capacity, running and waiting jobs
  rotate in rounds.  A job that has held its slice for a full round is
  suspended (preempt with resume intent) in favor of the longest-waiting
  job of a size that can use the freed chips; resuming burns
  ``suspend_overhead`` seconds of modeled checkpoint/restore cost through
  the engine's ``overhead_remaining`` mechanism (SURVEY.md §5
  "Checkpoint / resume": costs are modeled, not real).
- **Packing**: a waiting job may be *overlaid* onto a running job's slice
  (cluster overlay allocation) when both gangs are the same size and the
  sum of their profiled utilizations stays under ``pack_util_threshold``.
  If the pair fits under 1.0 they both run at full speed — the ideal
  Gandiva case; above 1.0 both are slowed proportionally.
- **Migration**: when a waiting gang is blocked purely by fragmentation
  (enough free chips, no contiguous box), running jobs are migrated —
  paying ``migration_overhead`` — toward the origin-packed first-fit
  layout until the box exists.  This exercises the engine's migrate path
  on real slice geometry (the round-1 verdict's dead-code item #5/#6).
- **Grow-shrink**: when nothing is waiting, running data-parallel jobs
  opportunistically *grow* into idle chips (slice-doubling, speed from the
  growth goodput curve); the moment demand returns they *shrink* back to
  their requested size so waiters see the chips (SURVEY.md §3.3
  "grow-shrink idle-GPU opportunistic expansion").

Round ticks are policy-requested wakeups; between ticks the policy is
purely event-driven.
"""

from __future__ import annotations

from typing import List, Optional

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.profiler.goodput import GoodputCurve
from gpuschedule_tpu.sim.job import Job, JobState
from gpuschedule_tpu.sim.overhead import resolve_overhead

# Growth model for opportunistic expansion: near-linear DP scaling with a
# whisper of per-chip latency, same family Optimus fits (profiler/goodput).
DEFAULT_GROWTH_CURVE = GoodputCurve((1.0, 0.0, 1e-4))


class GandivaPolicy(Policy):
    name = "gandiva"

    # stable cause-code tokens (attribution layer, ISSUE 5): one per
    # rationale rule this policy emits, grouped by mechanism — time-slice
    # rotation, overlay packing, and migration
    rule_codes = {
        "quantum-expired": "quantum",
        "longest-waiting": "resume",
        "pack-low-utilization": "pack",
        "pack-contention": "pack-net",
        "pack-dissolved": "unpack",
        "evacuate-degraded-pod": "evacuate",
        "evacuate-straggler": "evacuate-slow",
        "defrag-for-blocked-waiter": "defrag",
        "shrink-for-demand": "shrink",
        "grow-into-idle": "grow",
    }

    def __init__(
        self,
        *,
        round_length: float = 300.0,
        suspend_overhead: float | str = 30.0,
        migration_overhead: float | str = 45.0,
        packing: bool = True,
        pack_util_threshold: float = 1.25,
        max_migrations_per_event: int = 2,
        grow_shrink: bool = True,
        grow_overhead: float = 1.0,
        growth_curve: Optional[GoodputCurve] = None,
    ):
        if round_length <= 0:
            raise ValueError("round_length must be positive")
        # Overhead knobs take a constant (seconds) or "auto": derive the
        # cost from the job's model size and slice shape (sim/overhead.py —
        # checkpoint costs parameterized per slice size).
        for knob in (suspend_overhead, migration_overhead):
            if knob != "auto":
                float(knob)
        self.round_length = round_length
        self.suspend_overhead = suspend_overhead
        self.migration_overhead = migration_overhead
        self.packing = packing
        self.pack_util_threshold = pack_util_threshold
        self.max_migrations_per_event = max_migrations_per_event
        self.grow_shrink = grow_shrink
        self.grow_overhead = grow_overhead
        self.growth_curve = growth_curve or DEFAULT_GROWTH_CURVE

    # ------------------------------------------------------------------ #
    # fault reaction (faults/): evacuate degraded pods

    def on_fault(self, sim, fault, victims) -> None:
        """Migrate running jobs off a degraded pod.

        A chip failure inside a pod both fragments it and signals elevated
        risk (maintenance windows and spot revocations take whole pods at
        once), so Gandiva — the one policy with a migration mechanism —
        proactively moves unpacked survivors on the faulted pod to the
        healthiest other pod that can hold their slice, paying the usual
        migration overhead.  Victims re-enter the wait queue stamped with
        the fault time, same as rotation victims (longest-waiting order
        stays meaningful under churn).  Single-pod fleets and non-TPU
        scopes have nowhere to evacuate to; the default requeue stands.
        """
        for v in victims:
            v.sched["g_wait_since"] = sim.now
        if getattr(fault, "kind", "") == "straggler":
            # nothing was revoked: gangs on the degraded chip are merely
            # slowed, and the one policy with a migration mechanism can
            # move them somewhere fast
            self._evacuate_stragglers(sim)
            return
        if fault.scope[0] not in ("chip", "box", "pod"):
            return
        cluster = sim.cluster
        if getattr(cluster, "num_pods", 1) <= 1 or not hasattr(
            cluster, "pod_free_chips"
        ):
            return
        pod = fault.scope[1]
        budget = self.max_migrations_per_event
        groups = self._overlay_groups(sim)
        ex = self.explaining(sim)
        for job in list(sim.running):
            if budget == 0:
                break
            geom = job.allocation.detail if job.allocation is not None else None
            if getattr(geom, "pod", None) != pod:
                continue  # multislice gangs (no .pod) stay put too
            if self._is_packed(sim, job, groups):
                continue
            targets = sorted(
                (p for p in range(cluster.num_pods) if p != pod),
                key=lambda p: -cluster.pod_free_chips(p),
            )
            for target in targets:
                if cluster.pod_free_chips(target) < job.allocated_chips:
                    break  # healthiest pod first: smaller ones won't fit either
                overhead = resolve_overhead(
                    self.migration_overhead, job, cluster, migration=True
                )
                why = (
                    self.explain(
                        "evacuate-degraded-pod",
                        pod=pod, target=target, fault=fault.kind,
                    )
                    if ex else None
                )
                if sim.migrate(
                    job, overhead=overhead, placement_hint={"pod": target},
                    why=why,
                ):
                    sim.metrics.count("fault_evacuations")
                    budget -= 1
                    break

    def _evacuate_stragglers(self, sim) -> None:
        """Migrate slowed gangs off straggler chips.

        A gang whose ``slow_factor`` dropped below 1.0 is paced by a
        degraded chip somewhere in its slice; moving it to another pod
        (healthiest first, the evacuate-degraded-pod target order)
        restores full rate for the usual migration overhead.  Packed
        groups and multislice gangs stay put, and single-pod fleets have
        nowhere to go — the slowdown stands (the engine's slow-factor
        re-derivation heals them on straggler recovery)."""
        cluster = sim.cluster
        if getattr(cluster, "num_pods", 1) <= 1 or not hasattr(
            cluster, "pod_free_chips"
        ):
            return
        budget = self.max_migrations_per_event
        groups = self._overlay_groups(sim)
        ex = self.explaining(sim)
        for job in list(sim.running):
            if budget == 0:
                break
            if job.slow_factor >= 1.0 or self._is_packed(sim, job, groups):
                continue
            geom = job.allocation.detail if job.allocation is not None else None
            pod = getattr(geom, "pod", None)
            if pod is None:
                continue  # multislice gangs stay put (whole-pod claims)
            targets = sorted(
                (p for p in range(cluster.num_pods) if p != pod),
                key=lambda p: -cluster.pod_free_chips(p),
            )
            for target in targets:
                if cluster.pod_free_chips(target) < job.allocated_chips:
                    break  # healthiest pod first: smaller ones won't fit either
                overhead = resolve_overhead(
                    self.migration_overhead, job, cluster, migration=True
                )
                why = (
                    self.explain(
                        "evacuate-straggler",
                        pod=pod, target=target,
                        slow=round(job.slow_factor, 4),
                    )
                    if ex else None
                )
                if sim.migrate(
                    job, overhead=overhead, placement_hint={"pod": target},
                    why=why,
                ):
                    sim.metrics.count("straggler_evacuations")
                    budget -= 1
                    break

    # ------------------------------------------------------------------ #

    def schedule(self, sim) -> Optional[float]:
        now = sim.now
        groups = self._overlay_groups(sim)
        if self.grow_shrink:
            self._shrink_for_demand(sim, now, groups)  # waiters reclaim idle growth
        self._rotate(sim, now, groups)
        self._start_waiters(sim, now)
        if self.packing:
            groups = self._overlay_groups(sim)
            self._pack_waiters(sim, now, groups)
            self._update_pack_speeds(sim)
        self._defrag(sim, now)
        self._start_waiters(sim, now)  # migration may have opened a box
        if self.grow_shrink and not sim.pending:
            self._grow_into_idle(sim)

        if sim.pending:
            # Anchor the next tick to the earliest *future* round end among
            # running jobs: a waiter arriving mid-round must trigger rotation
            # when the incumbent's round ends, not a full round_length after
            # the arrival.  Rounds already expired (victim not suspendable —
            # packed, or no waiter fits) must NOT anchor, or the tick would
            # land in the past and degenerate into an eps-spaced tick storm.
            groups = self._overlay_groups(sim)
            future_ends = [
                end
                for j in sim.running
                if not self._is_packed(sim, j, groups)
                for end in [j.sched.get("g_round_start", now) + self.round_length]
                if end > now + sim.eps
            ]
            return min(future_ends) if future_ends else now + self.round_length
        return None

    @staticmethod
    def _overlay_groups(sim) -> dict:
        getter = getattr(sim.cluster, "overlay_groups", None)
        return getter() if getter is not None else {}

    # ------------------------------------------------------------------ #
    # time-slicing

    def _waiters(self, sim) -> List[Job]:
        """Pending jobs, longest-waiting first (by when they last ran or
        arrived)."""
        return sorted(
            sim.pending, key=lambda j: (j.sched.get("g_wait_since", j.submit_time), j.arrival_seq)
        )

    def _rotate(self, sim, now: float, groups: dict) -> None:
        """Suspend jobs whose round expired while same-size work waits."""
        if not sim.pending:
            return
        min_waiting = min(j.num_chips for j in sim.pending)
        expired = [
            j
            for j in sim.running
            if now - j.sched.get("g_round_start", j.submit_time) >= self.round_length - sim.eps
            and not self._is_packed(sim, j, groups)
            # a victim is only useful if some waiter fits in what it frees
            and min_waiting <= j.allocated_chips
        ]
        # oldest rounds first; suspend at most one victim per distinct waiter
        expired.sort(key=lambda j: j.sched.get("g_round_start", 0.0))
        n_waiters = len(sim.pending)
        ex = self.explaining(sim)
        for job in expired[:n_waiters]:
            why = (
                self.explain(
                    "quantum-expired",
                    round_age_s=round(
                        now - job.sched.get("g_round_start", job.submit_time), 3
                    ),
                    round_length_s=self.round_length,
                    waiters=n_waiters,
                )
                if ex else None
            )
            sim.preempt(job, suspend=True, why=why)
            job.sched["g_wait_since"] = now

    def _resume_overhead(self, sim, job: Job) -> float:
        if job.executed_work <= 0.0:
            return 0.0  # first start: nothing to restore
        return resolve_overhead(self.suspend_overhead, job, sim.cluster)

    def _start_waiters(self, sim, now: float) -> None:
        ex = self.explaining(sim)
        for job in self._waiters(sim):
            why = (
                self.explain(
                    "longest-waiting",
                    waited_s=round(
                        now - job.sched.get("g_wait_since", job.submit_time), 3
                    ),
                )
                if ex else None
            )
            if sim.try_start(job, overhead=self._resume_overhead(sim, job), why=why):
                job.sched["g_round_start"] = now

    # ------------------------------------------------------------------ #
    # packing

    @staticmethod
    def _is_packed(sim, job: Job, groups: dict) -> bool:
        if not groups or job.allocation is None:
            return False
        aid = job.allocation.alloc_id
        return aid in groups or any(aid in os for os in groups.values())

    def _pack_waiters(self, sim, now: float, groups: dict) -> None:
        if not hasattr(sim.cluster, "overlay_groups"):
            return
        for job in self._waiters(sim):
            if job.utilization >= 1.0:
                continue
            host = self._find_pack_host(sim, job, groups)
            if host is None:
                continue
            hint = {"overlay": host.allocation}
            # started at nominal speed; _update_pack_speeds (invoked right
            # after in the same schedule pass, zero sim time elapsing) is the
            # single owner of the contention model for packed groups
            overhead = self._resume_overhead(sim, job)
            why = (
                self.explain(
                    "pack-low-utilization",
                    host=host.job_id,
                    combined_util=round(host.utilization + job.utilization, 3),
                    threshold=self.pack_util_threshold,
                )
                if self.explaining(sim) else None
            )
            if sim.try_start(job, overhead=overhead, speed=1.0,
                             placement_hint=hint, why=why):
                job.sched["g_round_start"] = now
                sim.metrics.count("packings")
                groups = self._overlay_groups(sim)  # refresh: host now packed

    def _find_pack_host(self, sim, job: Job, groups: dict) -> Optional[Job]:
        """A running, unpacked job whose slice can host the waiter — same
        size or larger (sub-box overlay) — with combined utilization under
        the threshold (best = lowest combined).

        Gandiva's packing co-locates ANY low-util pair whose demand fits,
        not just equal sizes (round-3 verdict weak #6); the slice-geometry
        form is: a guest no bigger than the host's granted box shares its
        chips.  The contention model stays the utilization sum — slightly
        conservative for a smaller guest, which only occupies a sub-box of
        the host's slice."""
        best, best_u = None, self.pack_util_threshold
        for host in sim.running:
            # A grown host is never a pack target: packed jobs are exempt
            # from shrink/rotate, so packing one would lock its grown
            # excess away from waiters for the pack's whole lifetime.
            if (
                host.allocated_chips < job.num_chips
                or host.allocated_chips > host.num_chips
                or self._is_packed(sim, host, groups)
            ):
                continue
            combined = host.utilization + job.utilization
            if combined <= best_u:
                best, best_u = host, combined
        return best

    def _update_pack_speeds(self, sim) -> None:
        """Re-derive packed-group speeds (a partner may have finished)."""
        groups = self._overlay_groups(sim)  # {} on clusters without overlays
        by_alloc = {
            j.allocation.alloc_id: j for j in sim.running if j.allocation is not None
        }
        ex = self.explaining(sim)
        grouped_ids = set()
        for base, overlays in groups.items():
            members = [by_alloc[a] for a in [base, *overlays] if a in by_alloc]
            grouped_ids.update(j.allocation.alloc_id for j in members)
            combined = sum(j.utilization for j in members)
            factor = 1.0 if combined <= 1.0 else 1.0 / combined
            for j in members:
                # scale each member's entitled rate (growth speedup for a
                # grown host) — packing no longer erases a host's growth
                speed = self._nominal_speed(j) * factor
                if abs(j.speed - speed) > 1e-12:
                    why = (
                        self.explain(
                            "pack-contention",
                            combined_util=round(combined, 3),
                            group_size=len(members),
                        )
                        if ex else None
                    )
                    sim.set_speed(j, speed, why=why)
        # jobs no longer sharing: restore nominal speed (which is the growth
        # speedup for an opportunistically grown job, not necessarily 1.0)
        for j in sim.running:
            if j.allocation is not None and j.allocation.alloc_id not in grouped_ids:
                target = self._nominal_speed(j)
                if j.speed != target:
                    why = self.explain("pack-dissolved") if ex else None
                    sim.set_speed(j, target, why=why)

    # ------------------------------------------------------------------ #
    # migration / defrag

    def _defrag(self, sim, now: float) -> None:
        """If the head waiter is blocked purely by fragmentation, migrate
        running jobs toward the packed first-fit layout to open a box."""
        cluster = sim.cluster
        if not hasattr(cluster, "fragmentation"):
            return
        waiters = self._waiters(sim)
        if not waiters:
            return
        head = waiters[0]
        k = head.num_chips
        if k > cluster.free_chips or cluster.can_allocate(k):
            return  # not fragmentation-blocked
        budget = self.max_migrations_per_event
        # migrate smallest unpacked jobs first: cheapest moves, and small
        # slices are what shatters the free space.  A job already at its
        # first-fit position re-grants the same slice and migrate() returns
        # False with no cost charged (engine contract), so the loop walks on
        # to a job whose move actually compacts the layout.
        groups = self._overlay_groups(sim)
        movable = sorted(
            (j for j in sim.running if not self._is_packed(sim, j, groups)),
            key=lambda j: (j.allocated_chips, j.arrival_seq),
        )
        ex = self.explaining(sim)
        for job in movable:
            if budget == 0 or cluster.can_allocate(k):
                break
            overhead = resolve_overhead(
                self.migration_overhead, job, cluster, migration=True
            )
            why = (
                self.explain(
                    "defrag-for-blocked-waiter",
                    waiter=head.job_id,
                    waiter_chips=k,
                    free_chips=cluster.free_chips,
                )
                if ex else None
            )
            if sim.migrate(job, overhead=overhead, why=why):
                budget -= 1

    # ------------------------------------------------------------------ #
    # grow-shrink

    def _nominal_speed(self, job: Job) -> float:
        """Progress rate a job is entitled to at its current slice size:
        1.0 at the requested size, the growth curve's speedup when grown."""
        if job.allocated_chips and job.allocated_chips != job.num_chips:
            return self.growth_curve.speed_factor(job.allocated_chips, job.num_chips)
        return 1.0

    def _shrink_for_demand(self, sim, now: float, groups: dict) -> None:
        """Waiters the free pool cannot place reclaim grown jobs' excess.

        Growth survives arrivals that currently-free chips already satisfy
        (round-2 advisor #3: the old unconditional collapse shrank every
        grown job whenever *anything* was pending, then ``_grow_into_idle``
        re-grew it later — charging ``grow_overhead`` twice for a no-op
        round trip).  Placement is interleaved with reclaim — place what
        fits, shrink ONE job, place again — so each freed chip is consumed
        by a waiter before the next probe (a shared-pool ``can_allocate``
        over multiple waiters would double-count the same free chips).
        Reclaim is skipped outright when free + total excess cannot cover
        even the smallest waiter: shrinking would charge overhead and
        forfeit growth speedup without placing anyone (fragmentation-
        blocked waiters are ``_defrag``'s job, later in the same pass)."""
        if not sim.pending:
            return
        grown = [
            j
            for j in sim.running
            if j.allocated_chips > j.num_chips
            and not self._is_packed(sim, j, groups)
        ]
        if not grown:
            return
        # largest excess first: most chips reclaimed per overhead charge
        grown.sort(key=lambda j: j.allocated_chips - j.num_chips, reverse=True)
        self._start_waiters(sim, now)
        remaining_excess = sum(j.allocated_chips - j.num_chips for j in grown)
        for job in grown:
            if not sim.pending:
                break
            # re-checked per shrink, against the CURRENT pending set: once
            # the placeable waiters are gone, the survivors may all be too
            # big for free + what's still reclaimable — shrinking then
            # would charge overhead and forfeit speedup for nobody
            if sim.cluster.free_chips + remaining_excess < min(
                j.num_chips for j in sim.pending
            ):
                break
            remaining_excess -= job.allocated_chips - job.num_chips
            sim.resize(
                job,
                chips=job.num_chips,
                speed=1.0,
                overhead=self.grow_overhead,
                why=(
                    self.explain(
                        "shrink-for-demand",
                        reclaimed_chips=job.allocated_chips - job.num_chips,
                        pending=len(sim.pending),
                    )
                    if self.explaining(sim) else None
                ),
            )
            self._start_waiters(sim, now)

    def _grow_into_idle(self, sim) -> None:
        """Nothing waits and chips sit idle: double willing jobs' slices
        (slice sizes are powers of two), cheapest-to-please first."""
        cluster = sim.cluster
        groups = self._overlay_groups(sim)
        candidates = sorted(
            (
                j
                for j in sim.running
                if not self._is_packed(sim, j, groups)
            ),
            key=lambda j: (j.allocated_chips, j.arrival_seq),
        )
        for job in candidates:
            # pick the largest power-of-two size that fits AND still improves
            # the curve speed, then resize ONCE — one overhead charge and one
            # free/alloc cycle instead of a doubling ladder
            budget = job.allocated_chips + cluster.free_chips
            # growth never crosses the DCN boundary: the growth curve
            # models ICI scaling only, so cap at one pod on slice clusters
            cap = min(
                cluster.total_chips,
                getattr(cluster, "pod_chips", cluster.total_chips),
            )
            best_k, best_speed = job.allocated_chips, job.speed
            k = job.allocated_chips * 2
            while k <= cap and k <= budget:
                speed = self.growth_curve.speed_factor(k, job.num_chips)
                if speed <= best_speed:
                    break  # latency term took over; bigger only gets worse
                best_k, best_speed = k, speed
                k *= 2
            # geometry may refuse the chosen box (fragmentation): halve until
            # a contiguous slice exists or growth stops being worthwhile
            while best_k > job.allocated_chips:
                why = (
                    self.explain(
                        "grow-into-idle",
                        speedup=round(best_speed, 4),
                        idle_chips=cluster.free_chips,
                    )
                    if self.explaining(sim) else None
                )
                if sim.resize(
                    job, chips=best_k, speed=best_speed,
                    overhead=self.grow_overhead, why=why,
                ):
                    sim.metrics.count("grows")
                    break
                best_k //= 2
                best_speed = self.growth_curve.speed_factor(best_k, job.num_chips)
                if best_speed <= job.speed:
                    break
