"""Policy interface.

A policy is invoked by the engine after every event batch (arrival,
completion, or a wakeup the policy itself requested) and manipulates jobs
exclusively through the engine API — ``sim.try_start`` / ``sim.preempt`` /
``sim.set_speed`` / ``sim.migrate`` / ``sim.resize`` — which is the same
contract as the reference's per-policy ``*_sim_jobs`` loops acting on the
global JOBS/CLUSTER singletons (SURVEY.md §3.1), minus the globals.
"""

from __future__ import annotations

from typing import Optional


class Policy:
    """Base class for scheduling policies."""

    name: str = "base"

    def attach(self, sim) -> None:
        """Called once before the run starts; override for setup that needs
        the cluster/trace (e.g. Tiresias queue thresholds)."""

    def schedule(self, sim) -> Optional[float]:
        """Make scheduling decisions at ``sim.now``.

        Returns an optional absolute sim time at which the policy wants to be
        woken even if no arrival/completion occurs (time-slice quanta,
        periodic rounds).  Return ``None`` for purely event-driven policies.
        """
        raise NotImplementedError
