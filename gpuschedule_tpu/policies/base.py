"""Policy interface.

A policy is invoked by the engine after every event batch (arrival,
completion, or a wakeup the policy itself requested) and manipulates jobs
exclusively through the engine API — ``sim.try_start`` / ``sim.preempt`` /
``sim.set_speed`` / ``sim.migrate`` / ``sim.resize`` — which is the same
contract as the reference's per-policy ``*_sim_jobs`` loops acting on the
global JOBS/CLUSTER singletons (SURVEY.md §3.1), minus the globals.
"""

from __future__ import annotations

from typing import Dict, Optional


class Policy:
    """Base class for scheduling policies."""

    name: str = "base"

    #: Progress-read declaration (ISSUE 11, v2 accounting).  True (the
    #: safe default) means ``schedule()`` may read running jobs'
    #: *integrated* progress state — ``executed_work`` /
    #: ``attained_service`` / ``remaining_work`` / ``remaining_runtime``
    #: — so the v2 engine must sync the accounting ledger before every
    #: policy pass.  A policy that only inspects pending jobs and
    #: cluster state (FIFO) sets False and the v2 engine skips the
    #: per-batch sweep entirely: jobs then integrate lazily at their
    #: next mutation.  Irrelevant under v1 (the default accounting),
    #: which always advances every running job every batch.
    reads_progress: bool = True

    #: Machine-parseable cause codes (ISSUE 5): maps each human-readable
    #: ``explain()`` rule string to a short stable token.  When a run is
    #: captured with attribution armed (``MetricsLog(attribution=True)``),
    #: every rationale record additionally carries
    #: ``code = "<policy>/<token>"`` — the key the analyzer's blame
    #: tables group preemptions by.  The tokens are a compatibility
    #: surface: renaming a rule string must keep its token.
    rule_codes: Dict[str, str] = {}

    def attach(self, sim) -> None:
        """Called once before the run starts; override for setup that needs
        the cluster/trace (e.g. Tiresias queue thresholds)."""

    # ------------------------------------------------------------------ #
    # scheduling-rationale channel (obs layer)

    def explaining(self, sim) -> bool:
        """True when rationale records should be built for this run — i.e.
        the structured event stream is on.  Policies hoist this check once
        per ``schedule()`` call so the disabled path never constructs a
        rationale dict (the tools/check_overhead.py zero-overhead
        contract).  Also latches whether this run wants machine-parseable
        cause codes stamped on rationale records (attribution armed) —
        off-path streams must stay byte-identical, so ``explain()`` adds
        the ``code`` field only then."""
        self._stamp_codes = bool(getattr(sim.metrics, "attribution", False))
        return sim.metrics.record_events

    def cause_code(self, rule: str) -> str:
        """The stable machine-parseable token for a rule:
        ``<policy>/<rule_codes[rule]>`` (falling back to the rule string
        itself for rules without a table entry)."""
        return f"{self.name}/{self.rule_codes.get(rule, rule)}"

    def explain(self, rule: str, **detail) -> dict:
        """One scheduling-rationale record: which rule fired and the numbers
        behind it (queue rank, quantum age, goodput delta, ...).  Passed as
        the ``why=`` argument of the engine's mutation API, which persists
        it on the corresponding event in the run's event stream.  Under
        attribution the record leads with its ``code`` so blame tables
        never have to parse the human-readable rule text."""
        d = {"policy": self.name, "rule": rule}
        if getattr(self, "_stamp_codes", False):
            d["code"] = self.cause_code(rule)
        d.update(detail)
        return d

    def on_fault(self, sim, fault, victims) -> None:
        """React to a hardware fault (faults/) at ``sim.now``.

        The engine has already done the mechanical recovery before this is
        called: ``fault.scope`` is marked unhealthy on the cluster and every
        running gang overlapping it has been revoked — progress rolled back
        to its last checkpoint, restore cost charged, job requeued as
        PENDING (``victims`` lists them).  The default is exactly that
        requeue: victims wait in the queue like any other pending job and
        the next ``schedule()`` pass (the engine runs one after every fault
        batch) places them when capacity allows.

        Override to react beyond requeueing — e.g. Gandiva migrates running
        jobs away from a degraded pod.  Implementations may use the full
        engine mutation API; ``sim.cluster`` already reflects the outage.

        Straggler onsets (``fault.kind == "straggler"``) also arrive
        here: nothing is revoked (``victims`` is empty) but gangs on the
        degraded unit are already slowed — Gandiva migrates them off.
        """

    def on_hazard(self, sim, job, exposure: float) -> None:
        """React to a running gang whose failure exposure crossed the
        fault plan's ``migrate_threshold`` (faults/hazard.py, ISSUE 8).

        ``exposure`` combines the gang's lost straggler rate
        (``1 - job.slow_factor``) with its relative hazard heat (how much
        hotter than the fleet mean its pods run).  The engine offers a
        priced **checkpoint-then-migrate**: the default accepts —
        :meth:`Simulator.proactive_migrate` takes a checkpoint (raising
        the rollback floor to the current watermark), pays the write +
        restore cost as overhead, and moves the gang to a strictly
        clean allocation (``avoid_degraded="strict"``; no clean box →
        no move, no cost).  Override to decline (``pass``) or to react
        differently; the ``proactive-migrate`` rationale rides the
        migrate event either way so avoided-loss is measurable against
        lost-work in the fault panel.
        """
        why = (
            self.explain("proactive-migrate", exposure=round(exposure, 6))
            if self.explaining(sim) else None
        )
        sim.proactive_migrate(job, exposure=exposure, why=why)

    def on_warning(self, sim, fault, victims) -> None:
        """React to a spot pre-revoke notice (faults/) at ``sim.now``.

        ``fault`` is the upcoming revocation record (``fault.time`` is
        when it lands) and ``victims`` the running jobs that would be
        revoked right now.  The engine has already taken the emergency
        checkpoints the recovery model allows; the default is to do
        nothing more.  Override to act on the notice — e.g. migrate the
        gang off the spot unit before the revocation lands.
        """

    def schedule(self, sim) -> Optional[float]:
        """Make scheduling decisions at ``sim.now``.

        Returns an optional absolute sim time at which the policy wants to be
        woken even if no arrival/completion occurs (time-slice quanta,
        periodic rounds).  Return ``None`` for purely event-driven policies.
        """
        raise NotImplementedError
