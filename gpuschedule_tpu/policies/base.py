"""Policy interface.

A policy is invoked by the engine after every event batch (arrival,
completion, or a wakeup the policy itself requested) and manipulates jobs
exclusively through the engine API — ``sim.try_start`` / ``sim.preempt`` /
``sim.set_speed`` / ``sim.migrate`` / ``sim.resize`` — which is the same
contract as the reference's per-policy ``*_sim_jobs`` loops acting on the
global JOBS/CLUSTER singletons (SURVEY.md §3.1), minus the globals.
"""

from __future__ import annotations

from typing import Optional


class Policy:
    """Base class for scheduling policies."""

    name: str = "base"

    def attach(self, sim) -> None:
        """Called once before the run starts; override for setup that needs
        the cluster/trace (e.g. Tiresias queue thresholds)."""

    # ------------------------------------------------------------------ #
    # scheduling-rationale channel (obs layer)

    def explaining(self, sim) -> bool:
        """True when rationale records should be built for this run — i.e.
        the structured event stream is on.  Policies hoist this check once
        per ``schedule()`` call so the disabled path never constructs a
        rationale dict (the tools/check_overhead.py zero-overhead
        contract)."""
        return sim.metrics.record_events

    def explain(self, rule: str, **detail) -> dict:
        """One scheduling-rationale record: which rule fired and the numbers
        behind it (queue rank, quantum age, goodput delta, ...).  Passed as
        the ``why=`` argument of the engine's mutation API, which persists
        it on the corresponding event in the run's event stream."""
        d = {"policy": self.name, "rule": rule}
        d.update(detail)
        return d

    def on_fault(self, sim, fault, victims) -> None:
        """React to a hardware fault (faults/) at ``sim.now``.

        The engine has already done the mechanical recovery before this is
        called: ``fault.scope`` is marked unhealthy on the cluster and every
        running gang overlapping it has been revoked — progress rolled back
        to its last checkpoint, restore cost charged, job requeued as
        PENDING (``victims`` lists them).  The default is exactly that
        requeue: victims wait in the queue like any other pending job and
        the next ``schedule()`` pass (the engine runs one after every fault
        batch) places them when capacity allows.

        Override to react beyond requeueing — e.g. Gandiva migrates running
        jobs away from a degraded pod.  Implementations may use the full
        engine mutation API; ``sim.cluster`` already reflects the outage.
        """

    def schedule(self, sim) -> Optional[float]:
        """Make scheduling decisions at ``sim.now``.

        Returns an optional absolute sim time at which the policy wants to be
        woken even if no arrival/completion occurs (time-slice quanta,
        periodic rounds).  Return ``None`` for purely event-driven policies.
        """
        raise NotImplementedError
