"""Scheduling policies (SURVEY.md §2, layer 6).

Registry maps CLI names to policy factories; policies plug into the engine via
the :class:`gpuschedule_tpu.policies.base.Policy` interface.
"""

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.policies.dlas import DlasPolicy
from gpuschedule_tpu.policies.fifo import FifoPolicy
from gpuschedule_tpu.policies.gandiva import GandivaPolicy
from gpuschedule_tpu.policies.optimus import OptimusPolicy
from gpuschedule_tpu.policies.srtf import SrtfPolicy
from gpuschedule_tpu.policies.themis import ThemisPolicy

_REGISTRY = {  # lint: allow[GS601] populated by register() at import time only; every process re-imports identically
    "fifo": FifoPolicy,
    "srtf": SrtfPolicy,
    "dlas": DlasPolicy,
    "gandiva": GandivaPolicy,
    "optimus": OptimusPolicy,
    "themis": ThemisPolicy,
}


def register(name: str, factory) -> None:
    _REGISTRY[name] = factory


def make_policy(name: str, **kwargs) -> Policy:
    """Instantiate a registered policy by CLI name (e.g. 'fifo', 'dlas')."""
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown policy {name!r}; known: {sorted(_REGISTRY)}") from None


def available() -> list[str]:
    return sorted(_REGISTRY)


__all__ = [
    "Policy",
    "FifoPolicy",
    "SrtfPolicy",
    "DlasPolicy",
    "GandivaPolicy",
    "OptimusPolicy",
    "ThemisPolicy",
    "make_policy",
    "available",
    "register",
]
