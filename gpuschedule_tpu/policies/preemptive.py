"""Shared machinery for priority-driven preemptive policies.

SRTF and Tiresias-DLAS both reduce to the same step each time the engine
wakes them (SURVEY.md §3.1: "preempt lower-queue jobs if needed,
gang-aware"): order the active jobs by policy priority, make the running set
equal the longest prefix that fits the cluster, preempting losers and
gang-starting winners.  The helper here implements that step once.

Capacity planning is chip-count based (strict priority: a high-priority gang
reserves its chips even while geometry search for it fails), while actual
grants go through ``cluster.allocate`` so slice-shape constraints always
hold.  A winner whose box cannot be carved this round simply stays pending —
its reservation still throttles lower-priority jobs, which is what keeps
large gangs from starving on a fragmented pod.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from gpuschedule_tpu.sim.job import Job, JobState
from gpuschedule_tpu.sim.overhead import resolve_overhead

# Machine-parseable cause codes (ISSUE 5) for the two rationale rules this
# shared prefix-preemption step emits; every policy built on it (SRTF /
# DLAS / Themis) adopts the table so blame analysis sees the same stable
# tokens whichever priority currency ranked the prefix.
PRIORITY_RULE_CODES = {
    "displaced-by-priority-prefix": "displace",
    "priority-prefix": "start",
}


def apply_priority_schedule(
    sim,
    ordered: Sequence[Job],
    *,
    restart_overhead: float | str = 0.0,
    policy=None,
    detail_fn: Optional[Callable[[Job], dict]] = None,
) -> None:
    """Make the running set match the highest-priority prefix that fits.

    ``ordered`` lists schedulable jobs (PENDING/SUSPENDED/RUNNING), highest
    priority first.  ``restart_overhead`` seconds are charged to a job that
    resumes after having run before (modeled checkpoint/restore, SURVEY.md
    §5 "Checkpoint / resume"); pass ``"auto"`` to derive the cost from the
    job's model size and slice shape (sim/overhead.py).

    When ``policy`` is given and the run records events, every start /
    preempt carries a rationale record (``Policy.explain``): the job's rank
    in ``ordered`` plus whatever ``detail_fn(job)`` adds (the policy's
    priority currency — remaining time, queue index, rho).  Rationale
    construction is skipped entirely otherwise.
    """
    budget = sim.cluster.total_chips
    keep: List[Job] = []
    for job in ordered:
        if job.num_chips <= budget:
            keep.append(job)
            budget -= job.num_chips
    keep_ids = {id(j) for j in keep}

    expl = None
    if policy is not None and policy.explaining(sim):
        ranks = {id(j): r for r, j in enumerate(ordered)}

        def expl(job: Job, rule: str) -> dict:
            detail = detail_fn(job) if detail_fn is not None else {}
            return policy.explain(rule, rank=ranks.get(id(job)), **detail)

    # Preempt running losers first so their chips are free for winners.
    for job in list(sim.running):
        if id(job) not in keep_ids:
            sim.preempt(
                job, suspend=False,
                why=expl(job, "displaced-by-priority-prefix") if expl else None,
            )

    # Gang-start winners in priority order; geometry failures skip (the
    # budget reservation above already throttled lower priorities).
    for job in keep:
        if job.state is JobState.RUNNING:
            continue
        overhead = (
            resolve_overhead(restart_overhead, job, sim.cluster)
            if job.executed_work > 0.0
            else 0.0
        )
        sim.try_start(
            job, overhead=overhead,
            why=expl(job, "priority-prefix") if expl else None,
        )


def active_jobs(sim) -> List[Job]:
    """All jobs currently competing for the cluster."""
    return [j for j in sim.pending + sim.running if not j.finished]
