"""Tiresias-DLAS: discretized least-attained-service MLFQ.

The Tiresias scheduler (NSDI'19, the algorithm the reference implements per
SURVEY.md §2 "Policy: Tiresias LAS/DLAS") prioritizes jobs by how little
**attained service** (chip-seconds = gang size x run time) they have
consumed, discretized into a small number of queues so that long jobs are
not perpetually reshuffled:

- a job enters the highest-priority queue (Q0) and is demoted to the next
  queue each time its attained service crosses a configured threshold
  (quantum expiry);
- scheduling is strict priority across queues, FIFO within a queue,
  gang-aware and preemptive;
- a starving job — one that has waited longer than ``promote_ratio`` times
  its executed time since it last ran — is promoted back to Q0, with its
  service counter offset so it re-earns its demotions (the anti-starvation
  knob).

Demotions and promotions are event-exact: the policy computes the next
threshold-crossing / promote-eligibility instant and asks the engine for a
wakeup then, instead of polling on a fixed delta (the engine's event-driven
departure from the reference's stepped loops, engine.py module docstring).
"""

from __future__ import annotations

import bisect
from typing import Optional, Sequence

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.policies.preemptive import (
    PRIORITY_RULE_CODES,
    active_jobs,
    apply_priority_schedule,
)
from gpuschedule_tpu.sim.job import Job, JobState

# Default queue thresholds in chip-seconds: Q0 -> Q1 after one chip-hour,
# Q1 -> Q2 after ten chip-hours (Tiresias uses coarse exponential bands).
DEFAULT_THRESHOLDS = (3600.0, 36000.0)


class DlasPolicy(Policy):
    name = "dlas"

    # shared prefix-preemption cause codes (attribution layer, ISSUE 5)
    rule_codes = PRIORITY_RULE_CODES

    def __init__(
        self,
        *,
        thresholds: Sequence[float] = DEFAULT_THRESHOLDS,
        promote_ratio: float = 2.0,
        restart_overhead: float = 0.0,
    ):
        self.thresholds = sorted(float(t) for t in thresholds)
        if any(t <= 0 for t in self.thresholds):
            raise ValueError(f"thresholds must be positive: {self.thresholds}")
        self.promote_ratio = promote_ratio
        self.restart_overhead = restart_overhead

    # ------------------------------------------------------------------ #

    def _effective_service(self, job: Job) -> float:
        """Attained service since the last promotion (offset resets demotions)."""
        return job.attained_service - job.sched.get("dlas_offset", 0.0)

    def _queue(self, job: Job) -> int:
        return bisect.bisect_right(self.thresholds, self._effective_service(job))

    def _maybe_promote(self, job: Job, now: float) -> None:
        if job.state is not JobState.PENDING or job.executed_work <= 0.0:
            return
        waited = now - job.sched.get("dlas_last_run", job.submit_time)
        if waited >= self.promote_ratio * job.executed_work and self._queue(job) > 0:
            job.sched["dlas_offset"] = job.attained_service
            job.sched["dlas_promotions"] = job.sched.get("dlas_promotions", 0) + 1

    # ------------------------------------------------------------------ #

    def schedule(self, sim) -> Optional[float]:
        now = sim.now
        # Jobs running as of this event have been served up to now; stamp
        # before any preemption so a victim's waiting clock starts at now.
        for job in sim.running:
            job.sched["dlas_last_run"] = now

        jobs = active_jobs(sim)
        for job in jobs:
            self._maybe_promote(job, now)

        ordered = sorted(jobs, key=lambda j: (self._queue(j), j.arrival_seq))
        apply_priority_schedule(
            sim, ordered, restart_overhead=self.restart_overhead,
            policy=self,
            # which MLFQ band put the job here, and the service that earned
            # it (quantum expiry = a higher queue index than last round)
            detail_fn=lambda j: {
                "queue": self._queue(j),
                "service_chip_s": round(self._effective_service(j), 1),
                "promotions": j.sched.get("dlas_promotions", 0),
            },
        )

        # Jobs (re)started this round are also "last seen running now".
        for job in sim.running:
            job.sched["dlas_last_run"] = now

        return self._next_wakeup(sim, now)

    def _next_wakeup(self, sim, now: float) -> Optional[float]:
        """Earliest future demotion or promotion instant.

        Wakeups overshoot the analytic crossing time by 2x the engine's eps:
        attained service is integrated across multiple advance() segments,
        so at the exact instant the accumulated value can sit a few ulps
        below the threshold — the queue would not change and the re-armed
        wakeup (now + tiny) would be silently dropped by request_wakeup's
        eps guard, losing the demotion tick entirely.
        """
        slack = 2.0 * sim.eps
        candidates = []
        for job in sim.running:
            eff = self._effective_service(job)
            i = bisect.bisect_right(self.thresholds, eff)
            if i < len(self.thresholds) and job.allocated_chips > 0:
                dt = (self.thresholds[i] - eff) / job.allocated_chips
                candidates.append(now + job.overhead_remaining + dt + slack)
        for job in sim.pending:
            if job.executed_work > 0.0 and self._queue(job) > 0:
                t = (
                    job.sched.get("dlas_last_run", job.submit_time)
                    + self.promote_ratio * job.executed_work
                )
                if t > now:
                    candidates.append(t + slack)
        return min(candidates) if candidates else None
