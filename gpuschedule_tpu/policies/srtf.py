"""SRTF: preemptive shortest-remaining-time-first.

The reference's SRTF/SJF uses known (trace-declared) remaining time to order
jobs and preempts running work when a shorter job arrives (SURVEY.md §2
"Policy: SRTF/SJF").  Remaining time here is ``job.remaining_work`` — the
trace duration minus executed work — which is exactly what a simulator knows
and what the optimality argument (exchange argument on any two jobs sharing
a resource) is stated over.

Ties break on arrival order so equal-length jobs never thrash.
"""

from __future__ import annotations

from typing import Optional

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.policies.preemptive import (
    PRIORITY_RULE_CODES,
    active_jobs,
    apply_priority_schedule,
)


class SrtfPolicy(Policy):
    name = "srtf"

    # shared prefix-preemption cause codes (attribution layer, ISSUE 5)
    rule_codes = PRIORITY_RULE_CODES

    def __init__(self, *, restart_overhead: float = 0.0):
        self.restart_overhead = restart_overhead

    def schedule(self, sim) -> Optional[float]:
        ordered = sorted(
            active_jobs(sim),
            key=lambda j: (j.remaining_work, j.arrival_seq),
        )
        apply_priority_schedule(
            sim, ordered, restart_overhead=self.restart_overhead,
            policy=self,
            detail_fn=lambda j: {"remaining_s": round(j.remaining_work, 3)},
        )
        return None
