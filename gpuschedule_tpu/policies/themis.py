"""Themis-style finish-time fairness (beyond reference parity).

The reference's policy set stops at Optimus (SURVEY.md §2 lists five
policies); this sixth policy is a round-based finish-time-fairness
scheduler in the spirit of Themis (Mahajan et al., NSDI'20), adapted to
gang trace-replay.  Each round every active job is scored by its
projected *slowdown*

    rho(job, t) = projected_finish / ideal_jct
                = ((t - submit) + overhead_remaining + remaining/rate)
                  / duration

— the completion time the job would see if granted its full gang right
now, relative to a dedicated-cluster run (its trace duration at the
requested chip count).  The cluster then runs the highest-rho prefix
that fits, via the same gang-aware prefix-preemption step SRTF and
Tiresias use (policies/preemptive.py).

Fairness intuition: rho >= 1 always.  A freshly submitted job starts at
rho = 1 and a waiting job's rho grows at rate 1/duration — so a short
job's urgency climbs fast (it has the most to lose, proportionally,
from every second of queueing) but it can never starve a long job
indefinitely: the long job's accumulated wait eventually dominates any
newcomer's.  That min-max-slowdown behavior is the deliberate contrast
to SRTF (min *mean* JCT, starvation-prone under a stream of short
arrivals — tests/test_themis.py pins the contrast) and is what the
p95_slowdown / max_slowdown summary metrics (sim/metrics.py) measure.

Round-based (default 300 s, the paper's auction-round scale): rho
drifts continuously even when no event fires, so a purely event-driven
policy would never revisit its ordering between arrivals; the round
wakeup bounds how stale the ordering can get.  Preemption uses
``suspend=False`` (the Tiresias/SRTF demotion path) and charges
``restart_overhead`` on resume like the other preemptive policies —
pass ``"auto"`` to derive it from model size and slice shape.
"""

from __future__ import annotations

from typing import Optional

from gpuschedule_tpu.policies.base import Policy
from gpuschedule_tpu.policies.preemptive import (
    PRIORITY_RULE_CODES,
    active_jobs,
    apply_priority_schedule,
)
from gpuschedule_tpu.sim.job import Job, JobState

_EPS = 1e-9


def finish_time_rho(job: Job, now: float) -> float:
    """Projected slowdown if ``job`` ran its full gang from ``now`` on.

    Running jobs project at their current effective speed (packing or
    locality degradation makes their finish later, raising rho — a
    degraded job becomes *more* urgent, not less); pending/suspended
    jobs project at full reference speed, which is what ``try_start``
    grants (engine.try_start defaults speed=1.0).
    """
    ideal = max(job.duration, _EPS)
    if job.state is JobState.RUNNING and job.effective_speed > 0.0:
        rate = job.effective_speed
    else:
        rate = 1.0
    projected = (
        (now - job.submit_time)
        + job.overhead_remaining
        + job.remaining_work / rate
    )
    return projected / ideal


class ThemisPolicy(Policy):
    name = "themis"

    # shared prefix-preemption cause codes (attribution layer, ISSUE 5)
    rule_codes = PRIORITY_RULE_CODES

    def __init__(
        self,
        *,
        round_s: float = 300.0,
        hysteresis: float = 0.05,
        restart_overhead: float | str = 0.0,
    ):
        if not round_s > 0.0:
            raise ValueError(f"round_s must be > 0, got {round_s}")
        if hysteresis < 0.0:
            raise ValueError(f"hysteresis must be >= 0, got {hysteresis}")
        self.round_s = float(round_s)
        self.hysteresis = float(hysteresis)
        self.restart_overhead = restart_overhead
        self._next_tick: Optional[float] = None

    def attach(self, sim) -> None:
        self._next_tick = None

    def schedule(self, sim) -> Optional[float]:
        jobs = active_jobs(sim)
        if not jobs:
            self._next_tick = None
            return None
        now = sim.now
        # A waiting job's rho always outgrows a running one's (the runner's
        # projected finish is fixed while the waiter's recedes), so a bare
        # rho ordering churns allocations at every event — the thrash the
        # paper's leases exist to stop.  The incumbent-retention boost is
        # the lease in rho terms: a challenger must beat a runner by
        # ``hysteresis`` (relative), not merely tie past it.
        h = 1.0 + self.hysteresis
        ordered = sorted(
            jobs,
            key=lambda j: (
                -finish_time_rho(j, now)
                * (h if j.state is JobState.RUNNING else 1.0),
                j.arrival_seq,
            ),
        )
        apply_priority_schedule(
            sim, ordered, restart_overhead=self.restart_overhead,
            policy=self,
            detail_fn=lambda j: {"rho": round(finish_time_rho(j, now), 4)},
        )
        # One outstanding tick, ever: the engine arms a _TICK for every
        # non-None return with no dedup (engine.run), and each tick
        # re-invokes schedule() — returning now + round_s unconditionally
        # would let every arrival/completion event spawn its own
        # self-perpetuating tick chain, O(events x horizon / round_s)
        # sorts on a Philly-scale replay.  Re-arm only once the armed
        # tick has fired (or was never armed).
        if self._next_tick is not None and self._next_tick > now + sim.eps:
            return None
        self._next_tick = now + self.round_s
        return self._next_tick
