"""Command-line entry points.

The reference is driven by a flags-parsing ``run_sim`` script (SURVEY.md §2
"Sim entry / main loop", §3.1: ``run_sim --schedule=dlas --trace_file=...
--cluster_spec=...``).  This is the equivalent surface:

    python -m gpuschedule_tpu.cli run --policy dlas --cluster tpu-v5e \\
        --philly data/philly_sample.csv --out results/

    python -m gpuschedule_tpu.cli run --policy fifo --cluster simple \\
        --chips 64 --synthetic 200 --seed 42 --out results/   # config #1

    python -m gpuschedule_tpu.cli gen-trace --num-jobs 500 --philly-like \\
        --out trace.csv

    python -m gpuschedule_tpu.cli compare-topology --philly data/... \\
        --out results/topo/                                   # config #5

    python -m gpuschedule_tpu.cli profile --model transformer-tiny \\
        --curves curves.json                                  # fit goodput

Each ``run`` prints the summary as one JSON line on stdout and writes the
per-job/utilization CSVs (MetricsLog.write) when ``--out`` is given.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from gpuschedule_tpu.cluster import GpuCluster, SimpleCluster, TpuCluster
from gpuschedule_tpu.placement import with_placement
from gpuschedule_tpu.policies import available, make_policy
from gpuschedule_tpu.sim import Simulator
from gpuschedule_tpu.sim.philly import generate_philly_like_trace, load_philly_csv, save_philly_csv
from gpuschedule_tpu.sim.trace import generate_poisson_trace, load_trace_csv, save_trace_csv


def _parse_dims(raw: str) -> tuple:
    return tuple(int(x) for x in raw.lower().split("x"))


def build_cluster(args, net=None) -> object:
    if args.cluster == "simple":
        cluster = SimpleCluster(args.chips)
    elif args.cluster in ("tpu-v5e", "tpu-v5p"):
        gen = args.cluster.split("-")[1]
        dims = _parse_dims(args.dims) if args.dims else None
        cluster = TpuCluster(gen, dims=dims, num_pods=args.pods)
    elif args.cluster == "gpu":
        sw, npsw, gpn = _parse_dims(args.gpu_shape)
        cluster = GpuCluster(
            num_switches=sw, nodes_per_switch=npsw, gpus_per_node=gpn,
            seed=args.placement_seed,
        )
    else:
        raise SystemExit(f"unknown cluster {args.cluster!r}")
    if args.placement != "consolidated" and not isinstance(cluster, SimpleCluster):
        # with_placement validates per flavor — an unknown/mismatched scheme
        # must error, not silently run a different experiment than requested
        try:
            cluster = with_placement(
                cluster, args.placement, seed=args.placement_seed, net=net
            )
        except ValueError as e:
            raise SystemExit(str(e)) from None
    return cluster


def build_net(args):
    """The shared-fabric contention model for ``run --net`` (None when the
    flag is absent — the static-factor path, bit-identical to before the
    net layer existed)."""
    if not getattr(args, "net", None):
        return None
    if args.cluster not in ("tpu-v5e", "tpu-v5p"):
        raise SystemExit(
            "--net models the TPU DCN fabric; use --cluster tpu-v5e/tpu-v5p"
        )
    from gpuschedule_tpu.net import NetConfig, NetModel, parse_net_spec

    try:
        config = (
            parse_net_spec(args.net) if isinstance(args.net, str) else NetConfig()
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    return NetModel(config)


def load_jobs(args) -> List:
    if args.philly:
        return load_philly_csv(args.philly, max_chips=args.max_job_chips)
    if args.trace:
        return load_trace_csv(args.trace)
    if args.synthetic:
        return generate_poisson_trace(
            args.synthetic,
            seed=args.seed,
            arrival_rate=args.arrival_rate,
            mean_duration=args.mean_duration,
            failure_rate=args.failure_rate,
            util_range=(args.util_min, 1.0),
        )
    raise SystemExit("provide one of --philly / --trace / --synthetic N")


def _parse_policy_kwargs(pairs) -> dict:
    kwargs = {}
    for kv in pairs or []:
        k, _, v = kv.partition("=")
        try:
            parsed = json.loads(v)
        except json.JSONDecodeError:
            parsed = v
        kwargs[k.replace("-", "_")] = parsed
    return kwargs


def build_policy(args):
    kwargs = _parse_policy_kwargs(args.policy_arg)
    if args.policy == "optimus" and args.curves:
        from gpuschedule_tpu.profiler import CurveCache

        kwargs.setdefault("curve_cache", CurveCache(args.curves))
        if args.online:
            kwargs.setdefault("online", True)
    return make_policy(args.policy, **kwargs)


def build_fault_plan(args, cluster, jobs):
    """Fault injection (faults/): one ``--seed`` governs every stochastic
    stream in the run — trace synthesis keeps the bare seed (unchanged
    from before faults existed), while each fault process derives its own
    independent ``random.Random(f"{seed}:faults:<process>")`` stream, so
    the same seed reproduces byte-identical trace AND fault schedules,
    and changing the fault config never perturbs the trace (the
    seed-split rule, documented in faults/schedule.py).  Shared by
    ``run`` and ``whatif`` so the mirrored world is built identically."""
    if not args.faults:
        return None
    from gpuschedule_tpu.faults import (
        fault_horizon,
        make_fault_plan,
        parse_fault_spec,
    )

    try:
        fconfig, frecovery = parse_fault_spec(args.faults)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    horizon = args.max_time if args.max_time else fault_horizon(jobs)
    try:
        return make_fault_plan(
            cluster, fconfig, frecovery, horizon=horizon, seed=args.seed
        )
    except ValueError as e:
        # config-vs-cluster mismatches (e.g. a domain weight naming a
        # level this topology has no domains for) are user errors,
        # not tracebacks
        raise SystemExit(str(e)) from None


def _run_config_hash(args) -> str:
    """Digest of the *experiment* config — cluster + trace + fault spec,
    deliberately not the policy — so `compare` accepts policy-A-vs-B runs
    of the same seeded world and refuses cross-world diffs.  The flag ->
    hash-key mapping lives in ONE table (``worldspec.py``, ISSUE 13) that
    this function and the contract linter's coverage rule both consume,
    so a flag added without a hash/allowlist decision is a lint failure
    instead of silent drift."""
    from gpuschedule_tpu import worldspec
    from gpuschedule_tpu.obs import config_hash

    return config_hash(worldspec.hash_config(args))


def _append_run_history(store_path, run_meta, summary, *, policy, seed,
                        fallback_hash) -> None:
    """One history row for a finished replay, keyed by its run identity
    (ISSUE 10).  Shared by fresh and resumed runs so the row shape
    cannot drift between the two paths."""
    from gpuschedule_tpu.obs import HistoryStore

    chash = run_meta["config_hash"] if run_meta else fallback_hash
    with HistoryStore(store_path) as store:
        store.append(
            "run",
            run_id=(run_meta["run_id"] if run_meta
                    else f"{policy}-s{seed}-{chash}"),
            config_hash=chash,
            policy=policy,
            seed=seed,
            metrics=summary,
        )


def _cmd_resume(args) -> int:
    """``run --resume SNAPSHOT``: reconstruct a mid-replay engine from a
    ``--snapshot`` file and finish it.  World-building flags (--philly /
    --synthetic / --cluster / --faults / --net ...) are ignored — the
    snapshot IS the world; output flags (--out / --events / --prefix),
    --history / --cache-stats, and the snapshot/self-profile knobs still
    apply.  --perfetto / --prom / --spans are refused (their collectors
    are process-bound and cannot cover the pre-snapshot head).  Under v1
    accounting the finished outputs are byte-identical to the
    uninterrupted run (the obs registry / metrics.prom is process-bound
    and counts only the tail — the one documented exception)."""
    import math
    from pathlib import Path

    from gpuschedule_tpu.sim import Simulator
    from gpuschedule_tpu.sim.snapshot import SnapshotError

    if args.events is True and not args.out:
        raise SystemExit("--events without a PATH requires --out")
    if bool(args.snapshot) != bool(args.snapshot_every):
        raise SystemExit("--snapshot PATH and --snapshot-every SECONDS arm together")
    if args.snapshot_every is not None and not (
            math.isfinite(float(args.snapshot_every))
            and float(args.snapshot_every) > 0.0):
        # the fresh-run path gets this from the Simulator constructor;
        # the resume re-arm pokes the fields directly, so check here
        raise SystemExit(
            f"--snapshot-every must be > 0 seconds, got {args.snapshot_every}"
        )
    for armed, name in ((args.perfetto, "--perfetto"), (args.prom, "--prom"),
                        (args.spans, "--spans")):
        if armed:
            raise SystemExit(f"{name} is not supported with --resume")
    events_sink = None
    if isinstance(args.events, str):
        events_sink = Path(args.events)
    elif args.events:
        events_sink = Path(args.out) / f"{args.prefix}events.jsonl"
    profiler = None
    if args.self_profile:
        from gpuschedule_tpu.obs import PhaseProfiler

        profiler = PhaseProfiler()
    try:
        sim = Simulator.restore(
            args.resume, events_sink=events_sink, profiler=profiler
        )
    except SnapshotError as e:
        raise SystemExit(str(e)) from None
    if args.snapshot and args.snapshot_every:
        # re-arm (or move) periodic snapshotting for the resumed leg:
        # next strict multiple of the cadence past the restored clock
        every = float(args.snapshot_every)
        sim._snap_path = Path(args.snapshot)
        sim._snap_every = every
        nxt = every * (math.floor(sim.now / every) + 1.0)
        while nxt <= sim.now:  # float-rounding guard
            nxt += every
        sim._snap_next = nxt
    if args.cache_stats:
        # arm (or re-arm) cache telemetry for the resumed leg: restored
        # caches start empty, so the counters cover exactly the tail —
        # the same process-bound scope as the obs registry exception
        sim.metrics.cache_telemetry = True
        sim._cache_telemetry = True
    if args.flush_events is not None:
        # re-arm the tailable-sink flush cadence (ISSUE 15): the cadence
        # is process-bound output plumbing (like the sink handle itself,
        # deliberately not in the snapshot), so the resumed leg must
        # re-request it — next strict multiple past the restored clock
        if args.flush_events <= 0.0:
            raise SystemExit(
                f"--flush-events must be > 0 seconds, got {args.flush_events}"
            )
        fe = float(args.flush_events)
        sim.metrics._flush_every = fe
        nxt = fe * (math.floor(sim.now / fe) + 1.0)
        while nxt <= sim.now:  # float-rounding guard
            nxt += fe
        sim.metrics._flush_next = nxt
    with sim.metrics:
        res = sim.run()
    print(json.dumps(res.summary(), sort_keys=True))
    if profiler is not None:
        profiler.write(args.self_profile)
    if args.history:
        # cross-run memory (ISSUE 10): the resumed leg appends its
        # summary under the pickled run identity, same as the
        # uninterrupted run would have
        rm = sim.metrics.run_meta
        _append_run_history(
            args.history, rm, res.summary(),
            policy=(rm or {}).get("policy", args.policy),
            seed=(rm or {}).get("seed", args.seed),
            fallback_hash="resumed",
        )
    if args.out:
        sim.metrics.write(args.out, prefix=args.prefix)
    else:
        sim.metrics.close_events()
    return 0


def cmd_run(args) -> int:
    from pathlib import Path

    from gpuschedule_tpu.sim.metrics import MetricsLog

    if args.resume:
        return _cmd_resume(args)
    # --events PATH captures anywhere; bare --events keeps the historical
    # behavior (events.jsonl under --out)
    if args.events is True and not args.out:
        raise SystemExit("--events without a PATH requires --out")
    from gpuschedule_tpu.obs import get_tracer

    # --spans enables the tracer; GSTPU_TRACE=1 enables it at import.  Either
    # way an enabled tracer gets its spans reported below — a run must never
    # collect spans it then silently drops.
    if args.spans:
        get_tracer().enable().reset()
    tracer = get_tracer() if get_tracer().enabled else None
    registry = None
    if args.out or args.prom:
        from gpuschedule_tpu.obs import MetricsRegistry

        registry = MetricsRegistry()
    net_model = build_net(args)
    if args.placement == "contention" and net_model is None:
        # without the net model every pod scores equally and the scheme
        # silently becomes consolidated — a different experiment than the
        # one requested, so refuse (same rule as unknown schemes)
        raise SystemExit(
            "--placement contention scores pods by residual DCN bandwidth "
            "and needs the fabric model: add --net"
        )
    cluster = build_cluster(args, net=net_model)
    jobs = load_jobs(args)
    fault_plan = build_fault_plan(args, cluster, jobs)
    # With --events the stream goes straight to its JSONL sink (constant
    # memory at Philly scale): to the given PATH, or events.jsonl under
    # --out for the bare flag; --perfetto alone buffers events in RAM just
    # long enough to convert them.
    if isinstance(args.events, str):
        events_sink = Path(args.events)
    elif args.events:
        events_sink = Path(args.out) / f"{args.prefix}events.jsonl"
    else:
        events_sink = None
    # Stream identity header (obs/analyze.py): stamped whenever events are
    # recorded so `report`/`compare` can verify what they are reading.
    run_meta = None
    if events_sink is not None or args.perfetto:
        chash = _run_config_hash(args)
        run_meta = {
            "run_id": f"{args.policy}-s{args.seed}-{chash}",
            "seed": args.seed, "policy": args.policy, "config_hash": chash,
        }
    if args.sample_interval is not None and args.sample_interval <= 0.0:
        raise SystemExit(
            f"--sample-interval must be > 0 seconds, got {args.sample_interval}"
        )
    if args.flush_events is not None and args.flush_events <= 0.0:
        raise SystemExit(
            f"--flush-events must be > 0 seconds, got {args.flush_events}"
        )
    # Attribution/sampling (ISSUE 5) are observability, not experiment
    # config: they are deliberately NOT in the config hash, so an
    # attribution-armed capture stays `compare`-compatible with (and,
    # flags off, byte-identical to) the plain run of the same world.
    metrics = MetricsLog(
        record_events=bool(args.events) or bool(args.perfetto),
        events_sink=events_sink,
        registry=registry,
        run_meta=run_meta,
        attribution=bool(args.attrib),
        cache_telemetry=bool(args.cache_stats),
        flush_interval_s=args.flush_events,
    )
    # Wall-clock self-profiling (ISSUE 10): --self-profile attaches the
    # phase profiler and selects the engine's profiled loop body; the
    # flag off, no clock is ever read (the ≤2% overhead contract).
    profiler = None
    if args.self_profile:
        from gpuschedule_tpu.obs import PhaseProfiler

        profiler = PhaseProfiler()
    if bool(args.snapshot) != bool(args.snapshot_every):
        raise SystemExit("--snapshot PATH and --snapshot-every SECONDS arm together")
    try:
        sim = Simulator(
            cluster, build_policy(args), jobs,
            metrics=metrics,
            max_time=args.max_time or float("inf"),
            faults=fault_plan,
            net=net_model,
            sample_interval=args.sample_interval,
            sample_on_change=bool(args.sample_on_change),
            profiler=profiler,
            accounting=args.accounting,
            snapshot_every=args.snapshot_every,
            snapshot_path=Path(args.snapshot) if args.snapshot else None,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    # context-manager path: an engine exception still flushes/closes the
    # JSONL sink, leaving an analyzable stream behind (ISSUE 3 satellite)
    with metrics:
        res = sim.run()
    print(json.dumps(res.summary(), sort_keys=True))
    if profiler is not None:
        profiler.meta.update({
            "seed": args.seed,
            **({"run_id": run_meta["run_id"],
                "config_hash": run_meta["config_hash"]}
               if run_meta is not None else {}),
        })
        profiler.write(args.self_profile)
        print(json.dumps(
            {"selfprof": str(args.self_profile),
             "total_wall_s": profiler.total_wall_s,
             "batches": profiler.batches},
            sort_keys=True), file=sys.stderr)
    if args.history:
        # cross-run memory (ISSUE 10): append this invocation's summary
        # keyed by run identity, so `history trend` can render the
        # trajectory across invocations
        _append_run_history(
            args.history, run_meta, res.summary(),
            policy=args.policy, seed=args.seed,
            fallback_hash=(None if run_meta else _run_config_hash(args)),
        )
    if args.out:
        sim.metrics.write(args.out, prefix=args.prefix)
    else:
        metrics.close_events()
    if args.perfetto:
        from gpuschedule_tpu.obs import export_chrome_trace, load_events_jsonl

        events = (
            load_events_jsonl(events_sink) if events_sink is not None
            else metrics.events
        )
        export_chrome_trace(events, args.perfetto)
    if registry is not None:
        if args.prom:
            registry.write(prom_path=args.prom)
        if args.out:
            registry.write(
                prom_path=Path(args.out) / f"{args.prefix}metrics.prom",
                json_path=Path(args.out) / f"{args.prefix}metrics.json",
            )
    if tracer is not None:
        if args.out:
            tracer.write_chrome(Path(args.out) / f"{args.prefix}spans.trace.json")
        print(json.dumps({"spans": tracer.summary()}, sort_keys=True),
              file=sys.stderr)
    return 0


def cmd_obs_export(args) -> int:
    """Convert a persisted events.jsonl into a ui.perfetto.dev-loadable
    Chrome trace-event file (the offline half of `run --perfetto`)."""
    from gpuschedule_tpu.obs import export_chrome_trace, load_events_jsonl

    doc = export_chrome_trace(load_events_jsonl(args.events), args.out)
    print(json.dumps({
        "trace": str(args.out),
        "trace_events": len(doc["traceEvents"]),
    }, sort_keys=True))
    return 0


def cmd_report(args) -> int:
    """Render one run's events.jsonl as a self-contained HTML report
    (inline CSS/SVG, zero network fetches) — the human half of the
    analytics layer; `compare` is the CI half."""
    from gpuschedule_tpu.obs import SchemaError, StreamError, analyze_file, write_report

    selfprof = None
    if args.selfprof:
        from gpuschedule_tpu.obs import load_profile

        try:
            selfprof = load_profile(args.selfprof)
        except (OSError, ValueError) as e:
            raise SystemExit(str(e)) from None
    alerts = None
    if args.alerts:
        # the watchtower's side stream (ISSUE 15): skip its header, keep
        # the alert records — the report's Alerts panel input
        from gpuschedule_tpu.obs import iter_jsonl_records

        try:
            alerts = [
                rec for rec in iter_jsonl_records(args.alerts)
                if rec.get("event") == "alert"
            ]
        except StreamError as e:
            raise SystemExit(str(e)) from None
    try:
        analysis = analyze_file(args.events, require_header=not args.no_header,
                                low_memory=args.low_mem)
    except (SchemaError, StreamError) as e:
        raise SystemExit(str(e)) from None
    out = write_report(analysis, args.out, title=args.title, selfprof=selfprof,
                       alerts=alerts)
    if args.json:
        from pathlib import Path

        if args.low_mem:
            # spill-backed JSON dump (ISSUE 10 satellite): stream the
            # jobs array straight from the sqlite store — byte-identical
            # to the in-memory serialization, resident memory O(active)
            analysis.write_json(args.json)
        else:
            Path(args.json).write_text(
                json.dumps(analysis.to_json(), indent=2, sort_keys=True)
            )
    print(json.dumps({
        "report": str(out),
        "events": analysis.num_events,
        "jobs": len(analysis.jobs),
        "max_progress_drift": analysis.max_progress_drift,
    }, sort_keys=True))
    return 0


def cmd_compare(args) -> int:
    """Regression-diff event streams for CI gating.

    Two streams: the gate — exit 0 when B stays within threshold of A on
    every gated metric, 1 past any threshold, 2 when the runs are not
    comparable (missing or mismatched headers).  Three or more: the
    n-way policy x metric matrix with per-metric best/worst highlighting
    (exit 0, or 2 when any pair is not comparable; thresholds apply only
    to the two-run gate)."""
    from gpuschedule_tpu.obs import (
        SchemaError,
        StreamError,
        analyze_file,
        compare_matrix,
        compare_runs,
        parse_thresholds,
        write_compare_json,
        write_matrix_json,
    )

    try:
        default, per_metric = parse_thresholds(args.threshold)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    if len(args.streams) < 2:
        # usage error, not a regression: exit 2 (the not-comparable
        # bucket) so a CI glob matching one file doesn't read as exit-1
        # "metric regressed"
        print("compare needs at least two event streams", file=sys.stderr)
        return 2
    if len(args.streams) > 2 and args.threshold:
        print(
            "--threshold gates the two-run compare; the n-way matrix "
            "ranks, it does not gate",
            file=sys.stderr,
        )
        return 2
    try:
        analyses = [
            analyze_file(path, low_memory=args.low_mem)
            for path in args.streams
        ]
        if len(analyses) == 2:
            result = compare_runs(
                analyses[0], analyses[1],
                threshold=default, per_metric=per_metric,
                allow_mismatch=args.allow_mismatch,
            )
        else:
            result = compare_matrix(
                analyses, allow_mismatch=args.allow_mismatch
            )
    except (SchemaError, StreamError) as e:
        print(f"refusing to compare: {e}", file=sys.stderr)
        return 2
    print(result.format_table())
    if args.json:
        if len(analyses) == 2:
            write_compare_json(result, args.json)
        else:
            write_matrix_json(result, args.json)
    if args.history:
        # cross-invocation trend substrate (ISSUE 10, retiring the PR-3
        # trend-over-history omission): every compared stream's summary
        # lands in the store under its own header identity, so repeated
        # compare invocations accumulate per-config trajectories that
        # `history trend` renders — the TopoOpt search loop's ledger
        from gpuschedule_tpu.obs import HistoryStore

        with HistoryStore(args.history) as store:
            for a in analyses:
                h = a.header
                store.append(
                    "compare",
                    run_id=h.run_id if h else "",
                    config_hash=h.config_hash if h else "",
                    policy=h.policy if h else "",
                    seed=h.seed if h else None,
                    metrics=a.summary(),
                )
    return result.exit_code if len(analyses) == 2 else 0


def cmd_faults(args) -> int:
    """Fault-injection demo: one seeded chaos replay (Philly-like trace,
    finite MTBF) per policy config, reporting the goodput decomposition —
    which policies degrade gracefully as hardware gets flakier.

    ``tools/fault_sweep.py`` is the full MTBF x policy grid; this
    subcommand is its single-MTBF slice, small enough to eyeball.
    """
    from gpuschedule_tpu.faults.sweep import POLICY_CONFIGS, jsonable, run_cell

    keys = args.policies.split(",") if args.policies else list(POLICY_CONFIGS)
    unknown = [k for k in keys if k not in POLICY_CONFIGS]
    if unknown:
        raise SystemExit(
            f"unknown policy configs {unknown}; known: {sorted(POLICY_CONFIGS)}"
        )
    if args.restore == "auto":
        restore: object = "auto"
    else:
        try:
            restore = float(args.restore)
        except ValueError:
            raise SystemExit(
                f"--restore wants seconds or 'auto', got {args.restore!r}"
            ) from None
    events_dir = None
    if args.events:
        from pathlib import Path

        events_dir = Path(args.events)
        events_dir.mkdir(parents=True, exist_ok=True)
    cells = [
        run_cell(
            k,
            mtbf=args.mtbf,
            repair=args.repair,
            ckpt=args.ckpt,
            restore=restore,
            num_jobs=args.num_jobs,
            seed=args.seed,
            dims=_parse_dims(args.dims),
            num_pods=args.pods,
            max_time=args.max_time,
            events_path=(
                events_dir / f"{k}.events.jsonl" if events_dir else None
            ),
        )
        for k in keys
    ]
    doc = jsonable({  # --mtbf inf must stay strict JSON ("inf", not Infinity)
        "mtbf_s": args.mtbf,
        "repair_s": args.repair,
        "ckpt_s": args.ckpt,
        "seed": args.seed,
        "num_jobs": args.num_jobs,
        "cells": cells,
    })
    print(json.dumps(doc, sort_keys=True))
    if args.out:
        from pathlib import Path

        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def cmd_history(args) -> int:
    """Cross-run history (ISSUE 10): render the store's accumulated
    run/compare/bench results.  ``trend`` prints a deterministic
    per-metric trajectory table (same store -> same bytes, however many
    times it is invoked); ``list`` prints the matching rows."""
    from pathlib import Path

    from gpuschedule_tpu.obs import HistoryStore, render_trend

    if not Path(args.store).exists():
        raise SystemExit(f"history store {args.store} does not exist")
    with HistoryStore(args.store) as store:
        rows = store.rows(
            kind=args.kind, config_hash=args.config, label=args.label,
            last=args.last,
        )
    if args.action == "list":
        for r in rows:
            print(json.dumps({
                "seq": r.seq, "kind": r.kind, "run_id": r.run_id,
                "config_hash": r.config_hash, "policy": r.policy,
                "seed": r.seed, "label": r.label,
            }, sort_keys=True))
        print(f"{len(rows)} rows", file=sys.stderr)
    else:
        metrics = args.metric or ["avg_jct"]
        print(render_trend(rows, metrics))
    if args.json:
        Path(args.json).write_text(json.dumps(
            [{
                "seq": r.seq, "ts": r.ts, "kind": r.kind,
                "run_id": r.run_id, "config_hash": r.config_hash,
                "policy": r.policy, "seed": r.seed, "label": r.label,
                "metrics": r.metrics,
            } for r in rows],
            indent=2, sort_keys=True,
        ))
    return 0


def cmd_watch(args) -> int:
    """Live-tail watchtower (ISSUE 15): tail an events.jsonl stream —
    one-shot batch (default), ``--replay`` (paced as-if-live by sim
    time), or ``--follow`` (polling a growing file) — through the
    rolling-window detector set, printing each alert as one JSON line
    the moment its window closes, and a final ``{"watch": ...}`` summary
    line.  The alert sequence is byte-identical across all three modes
    (the determinism contract, tests/test_watch.py)."""
    from gpuschedule_tpu.obs import MetricsRegistry, StreamError
    from gpuschedule_tpu.obs.watch import (
        AlertStream,
        Watcher,
        follow_stream,
        iter_stream,
        load_rules,
        replay_stream,
        run_watch,
    )

    if args.follow and args.replay:
        raise SystemExit("--follow and --replay are mutually exclusive")
    if args.poll <= 0.0:
        raise SystemExit(f"--poll must be > 0 seconds, got {args.poll}")
    if args.speed < 0.0:
        raise SystemExit(f"--speed must be >= 0, got {args.speed}")
    try:
        rules = load_rules(args.rules)
        if args.window is not None:
            if args.window <= 0.0:
                raise ValueError(f"--window must be > 0, got {args.window}")
            rules["window_s"] = float(args.window)
        if args.ring is not None:
            if args.ring < 1:
                raise ValueError(f"--ring must be >= 1, got {args.ring}")
            rules["ring"] = int(args.ring)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    registry = MetricsRegistry()
    history = None
    if args.history:
        from gpuschedule_tpu.obs import HistoryStore

        history = HistoryStore(args.history)
    watcher = Watcher(
        rules,
        alerts=AlertStream(args.alerts),
        flight_dir=args.flight_dir,
        snapshot=args.snapshot,
        registry=registry,
        history=history,
        source=str(args.events),
    )
    if args.follow:
        stream = follow_stream(
            args.events, poll_s=args.poll,
            idle_timeout_s=args.idle_timeout, max_wall_s=args.max_wall,
        )
    elif args.replay:
        stream = replay_stream(args.events, speed=args.speed)
    else:
        stream = iter_stream(args.events)
    try:
        summary = run_watch(
            stream, watcher,
            on_alert=lambda a: print(json.dumps(a, sort_keys=True)),
        )
    except StreamError as e:
        raise SystemExit(str(e)) from None
    finally:
        if history is not None:
            history.close()
    print(json.dumps({"watch": summary}, sort_keys=True))
    if args.prom:
        registry.write(prom_path=args.prom)
    return 0


def cmd_whatif(args) -> int:
    """Interactive what-if queries against a mirrored replay (ISSUE 12):
    build the world exactly like ``run``, advance the engine to ``--at``
    and pause it there — a live mirror of cluster state — then answer
    admit / drain / policy-swap queries by speculative forks (optionally
    across a persistent worker pool), each returning the attributed
    delta against a mutation-free baseline fork of the same bounded
    horizon."""
    from pathlib import Path

    from gpuschedule_tpu.obs import MetricsRegistry
    from gpuschedule_tpu.sim.metrics import MetricsLog
    from gpuschedule_tpu.sim.whatif import (
        WhatIfService,
        append_history,
        parse_admit_spec,
        parse_drain_spec,
        result_document,
    )

    queries = []
    try:
        for spec in args.admit or []:
            queries.extend(parse_admit_spec(spec))
        for spec in args.drain or []:
            queries.append(parse_drain_spec(spec))
    except ValueError as e:
        raise SystemExit(str(e)) from None
    for name in args.swap_policy or []:
        queries.append({"kind": "policy-swap", "policy": name})
    if not queries:
        raise SystemExit(
            "whatif needs at least one --admit / --drain / --swap-policy "
            "query"
        )
    if args.at < 0.0:
        raise SystemExit(f"--at must be >= 0, got {args.at}")
    if args.resume:
        # flight-recorder handshake (ISSUE 15): mirror from a pinned
        # engine snapshot (`watch --flight-dir` + `run --snapshot`)
        # instead of rebuilding the world — world-building flags are
        # ignored, the snapshot IS the world.  The mirror must never
        # write into (or truncate!) the watched run's event stream, so
        # the sink is detached and recording disarmed.
        from gpuschedule_tpu.sim.snapshot import SnapshotError

        try:
            sim = Simulator.restore(args.resume, events_sink=False)
        except SnapshotError as e:
            raise SystemExit(str(e)) from None
        sim.metrics.record_events = False
        sim.metrics.events = []
        sim._snap_path = None
        sim._snap_every = None
        sim._snap_next = float("inf")
        # the snapshotted run's --max-time was an output-capture cutoff,
        # not a property of the world: speculating past the incident is
        # the whole point, so the mirror's bound is --horizon (and an
        # explicit --max-time on THIS invocation, when given)
        sim.max_time = args.max_time or float("inf")
        if args.at < sim.now:
            raise SystemExit(
                f"--at {args.at} is before the snapshot instant "
                f"(t={sim.now}); pin an earlier snapshot"
            )
    else:
        net_model = build_net(args)
        if args.placement == "contention" and net_model is None:
            raise SystemExit(
                "--placement contention scores pods by residual DCN "
                "bandwidth and needs the fabric model: add --net"
            )
        cluster = build_cluster(args, net=net_model)
        jobs = load_jobs(args)
        fault_plan = build_fault_plan(args, cluster, jobs)
        # the mirror runs with attribution armed so every speculative
        # delta decomposes by cause (the PR-5 machinery); whatif has no
        # byte-compat surface of its own to preserve
        metrics = MetricsLog(attribution=True)
        try:
            sim = Simulator(
                cluster, build_policy(args), jobs,
                metrics=metrics,
                max_time=args.max_time or float("inf"),
                faults=fault_plan,
                net=net_model,
                accounting=args.accounting,
            )
        except ValueError as e:
            raise SystemExit(str(e)) from None
    sim.run_until(args.at)
    # deterministic user errors must exit cleanly BEFORE evaluation — a
    # pooled worker would otherwise retry them with backoff and surface
    # a raw traceback
    for q in queries:
        at = q.get("at")
        if at is None:
            continue
        if at < sim.now:
            raise SystemExit(
                f"query at={at} is before the mirror instant "
                f"(the engine paused at t={sim.now}); speculative "
                "mutations cannot land in the replayed past"
            )
        if at > min(sim.now + args.horizon, sim.max_time):
            raise SystemExit(
                f"query at={at} is beyond the bounded replay window "
                f"(mirror t={sim.now} + horizon {args.horizon}, capped "
                f"by --max-time {sim.max_time}); it would never be "
                "applied — raise --horizon or move it earlier"
            )
    if args.resume:
        # the mirror's identity is the snapshotted run's, not the
        # (ignored) world flags'
        rm = sim.metrics.run_meta or {}
        chash = str(rm.get("config_hash") or "resumed")
        run_meta = {
            "run_id": str(rm.get("run_id") or f"resumed-{sim.policy.name}"),
            "seed": rm.get("seed"), "policy": sim.policy.name,
            "config_hash": chash,
        }
    else:
        chash = _run_config_hash(args)
        run_meta = {
            "run_id": f"{args.policy}-s{args.seed}-{chash}",
            "seed": args.seed, "policy": args.policy, "config_hash": chash,
        }
    registry = MetricsRegistry()
    fleet = None
    if args.trace_out:
        # ISSUE 16: arm cross-process tracing — the run_id is the trace
        # id every worker span links back to
        from gpuschedule_tpu.obs import FleetCollector

        fleet = FleetCollector(run_meta["run_id"], parent="whatif")
    try:
        service = WhatIfService(
            sim, horizon=args.horizon, workers=args.pool, registry=registry,
            fleet=fleet,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    pool_stats = None
    try:
        results = service.evaluate(queries)
        pool_stats = service.pool_stats()
    except ValueError as e:
        # belt and braces: any remaining deterministic query error (the
        # evaluator re-validates against the fork's actual bound) is a
        # user error, not a traceback
        raise SystemExit(str(e)) from None
    finally:
        service.close()
    doc = result_document(
        sim, results, requested_at=args.at, horizon=args.horizon,
        pool=args.pool, run_meta=run_meta,
    )
    print(json.dumps(doc, sort_keys=True))
    if args.history:
        # pool_stats() now answers in serial mode too (ISSUE 18, for
        # /status) — the extra history "pool" row stays pool-only
        n = append_history(args.history, results, run_meta=run_meta,
                           pool_stats=pool_stats if args.pool else None)
        print(f"{n} whatif history rows -> {args.history}", file=sys.stderr)
    if args.out:
        out = Path(args.out)
        if out.parent and not out.parent.exists():
            out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(doc, indent=2, sort_keys=True))
    if fleet is not None:
        # parent-side families (query latency, pool lifecycle) join the
        # merged document FIRST; the federated worker families then fold
        # into the --prom registry (this order, or worker counters would
        # double-count in the document)
        fleet.registry.merge(registry)
        tdoc = fleet.write(args.trace_out)
        print(
            f"fleet trace ({tdoc['federation']['tasks']} tasks, "
            f"{len(tdoc['federation']['workers'])} workers) -> "
            f"{args.trace_out}",
            file=sys.stderr,
        )
        fleet.merge_into(registry)
    if args.prom:
        registry.write(prom_path=args.prom)
    return 0


def cmd_serve(args) -> int:
    """Serve the twin (ISSUE 18): build the world exactly like
    ``whatif``, pause it at ``--at``, warm a :class:`WhatIfService`
    pool, and run the long-lived control plane — ``GET /metrics``,
    ``GET /alerts`` (SSE), ``POST /whatif`` (admission-controlled),
    ``GET /status`` / ``/healthz`` / ``/readyz``, and the ``GET /``
    dashboard — until SIGTERM/SIGINT (or ``--max-wall``), then drain
    gracefully.  One ``{"serve": ...}`` line announces the bound port
    the moment the daemon is ready; one ``{"serve_summary": ...}`` line
    closes the session."""
    from gpuschedule_tpu.obs import MetricsRegistry
    from gpuschedule_tpu.obs.server import (
        TwinServer,
        install_signal_handlers,
    )
    from gpuschedule_tpu.obs.watch import load_rules
    from gpuschedule_tpu.sim.metrics import MetricsLog
    from gpuschedule_tpu.sim.whatif import WhatIfService

    if args.follow and args.replay:
        raise SystemExit("--follow and --replay are mutually exclusive")
    if args.at < 0.0:
        raise SystemExit(f"--at must be >= 0, got {args.at}")
    if args.poll <= 0.0:
        raise SystemExit(f"--poll must be > 0 seconds, got {args.poll}")
    if args.speed < 0.0:
        raise SystemExit(f"--speed must be >= 0, got {args.speed}")
    mode = "follow" if args.follow else ("replay" if args.replay else "batch")
    rules = None
    slo_cfg = None
    try:
        if args.events is not None:
            rules = load_rules(args.rules)
            if args.window is not None:
                if args.window <= 0.0:
                    raise ValueError(
                        f"--window must be > 0, got {args.window}"
                    )
                rules["window_s"] = float(args.window)
        if args.self_slo is not None:
            slo_cfg = json.loads(args.self_slo)
            if not isinstance(slo_cfg, dict):
                raise ValueError(
                    "--self-slo wants a JSON object of SELF_SLO_DEFAULTS "
                    "overrides"
                )
    except (ValueError, json.JSONDecodeError) as e:
        raise SystemExit(str(e)) from None
    net_model = build_net(args)
    if args.placement == "contention" and net_model is None:
        raise SystemExit(
            "--placement contention scores pods by residual DCN "
            "bandwidth and needs the fabric model: add --net"
        )
    cluster = build_cluster(args, net=net_model)
    jobs = load_jobs(args)
    fault_plan = build_fault_plan(args, cluster, jobs)
    # the mirror runs with attribution armed, exactly like `whatif` —
    # same builders, same config hash, byte-identical served documents
    metrics = MetricsLog(attribution=True)
    try:
        sim = Simulator(
            cluster, build_policy(args), jobs,
            metrics=metrics,
            max_time=args.max_time or float("inf"),
            faults=fault_plan,
            net=net_model,
            accounting=args.accounting,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    sim.run_until(args.at)
    chash = _run_config_hash(args)
    run_meta = {
        "run_id": f"{args.policy}-s{args.seed}-{chash}",
        "seed": args.seed, "policy": args.policy, "config_hash": chash,
    }
    registry = MetricsRegistry()
    try:
        service = WhatIfService(
            sim, horizon=args.horizon, workers=args.pool,
            registry=registry, max_inflight=args.max_inflight,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None
    try:
        service.warm()
        server = TwinServer(
            service,
            registry=registry,
            requested_at=args.at,
            run_meta=run_meta,
            events=args.events,
            mode=mode,
            rules=rules,
            self_slo=slo_cfg,
            alerts_path=args.alerts,
            history=args.history,
            host=args.host,
            port=args.port,
            speed=args.speed,
            poll_s=args.poll,
            idle_timeout_s=args.idle_timeout,
            max_wall_s=args.max_wall,
            drain_s=args.drain_s,
        )
    except ValueError as e:
        service.close()
        raise SystemExit(str(e)) from None
    try:
        stop = install_signal_handlers(server)
    except ValueError:
        # signal handlers need the main thread; tests drive main() from
        # a worker thread and stop via --max-wall instead
        import threading

        stop = threading.Event()
    server.start()
    print(json.dumps({"serve": {
        "host": server.host, "port": server.port, "mode": mode,
        "pool": args.pool, "run_id": run_meta["run_id"],
        "config_hash": chash,
    }}, sort_keys=True), flush=True)
    try:
        stop.wait(timeout=args.max_wall)
    except KeyboardInterrupt:
        pass
    summary = server.shutdown()
    print(json.dumps({"serve_summary": summary}, sort_keys=True))
    if args.prom:
        registry.write(prom_path=args.prom)
    return 0


def cmd_gen_trace(args) -> int:
    if args.philly_like:
        from gpuschedule_tpu.sim.philly import PHILLY_MEAN_INTERARRIVAL_S

        rate = (args.arrival_rate if args.arrival_rate is not None
                else 1.0 / PHILLY_MEAN_INTERARRIVAL_S)
        jobs = generate_philly_like_trace(args.num_jobs, seed=args.seed,
                                          arrival_rate=rate)
        save_philly_csv(jobs, args.out)
    else:
        rate = args.arrival_rate if args.arrival_rate is not None else 1.0 / 60.0
        jobs = generate_poisson_trace(
            args.num_jobs,
            seed=args.seed,
            arrival_rate=rate,
            mean_duration=args.mean_duration,
            failure_rate=args.failure_rate,
            util_range=(args.util_min, 1.0),
        )
        save_trace_csv(jobs, args.out)
    print(f"wrote {len(jobs)} jobs to {args.out}")
    return 0


def cmd_compare_topology(args) -> int:
    """BASELINE config #5: NVLink GPU nodes vs contiguous TPU slices.

    Computes the BASELINE.json:5 acceptance band — the TPU-v5p replay's
    avg-JCT/makespan delta vs the GPU-backed baseline (the consolidated
    scheme, the reference lineage's YARN-ish default) on the same trace —
    and averages the random-placement scheme over ``--seeds`` draws so the
    GPU-vs-TPU contrast is not a single sample.
    """
    from statistics import mean

    from gpuschedule_tpu.analysis import acceptance_band, write_report

    def jobs(num_pods: int = 1):
        if args.philly:
            # multi-pod configs keep the trace's whales as multislice
            # gangs instead of clamping them to one pod
            return load_philly_csv(args.philly, num_pods=num_pods)
        return generate_poisson_trace(args.synthetic or 200, seed=args.seed)

    gpu_shape = _parse_dims(args.gpu_shape)

    def gpu(scheme: str, seed: int = 0) -> GpuCluster:
        return GpuCluster(
            num_switches=gpu_shape[0], nodes_per_switch=gpu_shape[1],
            gpus_per_node=gpu_shape[2], scheme=scheme, seed=seed)

    configs = {"gpu-consolidated": gpu("consolidated")}
    for s in range(max(1, args.seeds)):
        configs[f"gpu-random-s{s}"] = gpu("random", seed=s)
    configs.update({
        "gpu-topology": gpu("topology"),
        "tpu-v5p": TpuCluster("v5p"),
        "tpu-v5e": TpuCluster("v5e"),
        # the ICI-vs-DCN boundary made visible: same generation, two pods
        # joined by DCN — whales run as multislice gangs at a speed_factor
        # < 1 instead of being clamped into one pod
        "tpu-v5p-2pod": TpuCluster("v5p", num_pods=2),
    })
    pods_of = {"tpu-v5p-2pod": 2}
    pol_kwargs = _parse_policy_kwargs(args.policy_arg)
    results = {}
    for name, cluster in configs.items():
        results[name] = Simulator(
            cluster, make_policy(args.policy, **pol_kwargs),
            jobs(pods_of.get(name, 1)),
        ).run()

    # contention column: the 2-pod fleet again, this time with the shared-
    # fabric model on — whales pay a max-min fair share of the DCN instead
    # of each assuming an isolated fabric.  The ratio vs the static 2-pod
    # replay is the shared-fabric penalty under the default 4:1 core
    # oversubscription: >= 1.0 even for a lone gang (the static model
    # assumed an isolated, non-blocking fabric), larger when gangs
    # actually contend; mean link utilization says how loaded it was.
    from gpuschedule_tpu.net import NetModel

    net_model = NetModel()
    results["tpu-v5p-2pod-net"] = Simulator(
        TpuCluster("v5p", num_pods=2), make_policy(args.policy, **pol_kwargs),
        jobs(2), net=net_model,
    ).run()

    rand = [results[k] for k in results if k.startswith("gpu-random-s")]
    # how many gangs actually spanned pods in the 2-pod replay: on the
    # synthetic path (or a whale-free Philly trace) the answer is zero and
    # the 2-pod/1-pod JCT ratio says nothing about DCN — it only measures
    # doubled capacity, and the two fleets replay different gang sizes
    # anyway (whales clamped vs multislice), so the ratio is reported with
    # its multislice count and nulled when no gang crossed a pod
    pod_chips = configs["tpu-v5p-2pod"].pod_chips
    n_multislice = sum(1 for j in jobs(2) if j.num_chips > pod_chips)
    extra = {
        "acceptance": acceptance_band(results["gpu-consolidated"], results["tpu-v5p"]),
        "gpu-random-mean": {
            "avg_jct": mean(r.avg_jct for r in rand),
            "makespan": mean(r.makespan for r in rand),
            "seeds": len(rand),
        },
        "dcn_vs_ici": {
            "multislice_jobs": n_multislice,
            "jct_ratio_2pod_over_1pod": (
                results["tpu-v5p-2pod"].avg_jct / results["tpu-v5p"].avg_jct
                if n_multislice else None
            ),
        },
        "contention": {
            "multislice_jobs": n_multislice,
            "oversubscription": net_model.config.oversubscription,
            "jct_ratio_net_over_static": (
                results["tpu-v5p-2pod-net"].avg_jct
                / results["tpu-v5p-2pod"].avg_jct
                if n_multislice and results["tpu-v5p-2pod"].avg_jct > 0
                else None
            ),
            "net_reprices": int(
                results["tpu-v5p-2pod-net"].counters.get("net_reprices", 0)
            ),
            "mean_link_utilization": net_model.mean_utilization(),
        },
    }
    if args.load_sweep:
        # the acceptance band vs offered load (plain FIFO's entry point
        # into the 5% band lives here; see the golden sweep table).  The
        # base-load point reuses the replays already computed above.
        from gpuschedule_tpu.analysis import acceptance_load_sweep

        extra["load_sweep"] = acceptance_load_sweep(
            jobs,
            lambda: gpu("consolidated"),
            lambda: TpuCluster("v5p"),
            lambda: make_policy(args.policy, **pol_kwargs),
            base_results=(results["gpu-consolidated"], results["tpu-v5p"]),
        )
    out = {k: v.summary() for k, v in results.items()}
    out.update(extra)
    print(json.dumps(out, sort_keys=True))
    if args.out:
        write_report(results, args.out, extra=extra)
    return 0


def _datastream_identity(args) -> dict:
    """What makes the training data stream what it is: the count-based
    resume offset is only valid when every one of these matches the
    saved run."""
    import hashlib
    import os

    ident = {
        "seed": args.seed,
        "batch_size": args.batch_size,
        "seq_len": args.seq_len,
        "host_shard": getattr(args, "host_shard", None),
        "data": None,
    }
    if args.data and os.path.exists(args.data):
        h = hashlib.sha256()
        with open(args.data, "rb") as f:
            h.update(f.read(1 << 20))  # first MiB + size: cheap fingerprint
        ident["data"] = {
            "bytes": os.path.getsize(args.data),
            "sha256_head": h.hexdigest(),
        }
    return ident


def _meta_path(ckpt: str):
    from pathlib import Path

    return Path(str(ckpt) + ".datastream.json")


def _write_datastream_meta(args) -> None:
    _meta_path(args.ckpt).write_text(json.dumps(_datastream_identity(args)))


def _warn_on_datastream_drift(args) -> None:
    """Compare this invocation's stream identity with the checkpoint's;
    a mismatch means count-based resume would re-train on seen data or
    skip unseen data — warn loudly, don't block (the operator may be
    switching datasets deliberately)."""
    path = _meta_path(args.restore)
    if not path.exists():
        return  # pre-0.5 checkpoint: nothing to compare
    saved = json.loads(path.read_text())
    current = _datastream_identity(args)
    drift = {
        k: (saved.get(k), current.get(k))
        for k in current
        if saved.get(k) != current.get(k)
    }
    if drift:
        print(
            "WARNING: data stream differs from the checkpointed run "
            f"({', '.join(f'{k}: {a!r} -> {b!r}' for k, (a, b) in drift.items())}); "
            "count-based resume may replay seen data or skip unseen data",
            file=sys.stderr,
        )


def cmd_train(args) -> int:
    """Actually train a model — the framework's user-facing training entry
    (mesh + trainer + input pipeline + checkpoint in one command).

    Drives the same ShardedTrainer the profiler measures: build a
    (dp, sp, tp) mesh over the visible devices, feed it from a token file
    (``--data``) or the synthetic generator, optionally restore from /
    save to an orbax checkpoint, and print one JSON summary line."""
    import jax

    from gpuschedule_tpu.data import (
        TokenFileDataset,
        prefetch_to_device,
        synthetic_lm_batches,
    )
    from gpuschedule_tpu.parallel import (
        ShardedTrainer,
        make_mesh,
        restore_state,
        save_state,
    )

    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    devs = jax.devices()[: args.devices] if args.devices else jax.devices()
    pp = getattr(args, "pp", 1)
    if pp > 1:
        # the staged trainer: blocks split over pp, microbatches flow
        # through pipeline_apply (round-4 verdict #4: pp reachable from
        # the user surfaces, not only from tests/the dryrun)
        if args.sp > 1 or args.tp > 1 or args.ring_attn:
            raise SystemExit(
                "--pp composes with dp only; drop --sp/--tp/--ring-attn"
            )
        from gpuschedule_tpu.parallel import PipelinedLM

        try:
            mesh = make_mesh(pp=pp, devices=devs)
            trainer = PipelinedLM(
                args.model,
                mesh,
                batch_size=args.batch_size,
                seq_len=args.seq_len,
                num_microbatches=args.microbatches,
                learning_rate=args.lr,
                flash_attn=args.flash_attn,
                warmup_steps=args.warmup_steps,
                decay_steps=args.decay_steps,
                grad_clip=args.grad_clip,
                schedule=args.pp_schedule,
            )
        except ValueError as e:
            # divisibility constraints (layers % pp, batch % microbatches,
            # devices % pp) are flag mistakes, not tracebacks
            raise SystemExit(str(e))
    else:
        mesh = make_mesh(sp=args.sp, tp=args.tp, devices=devs)
        trainer = ShardedTrainer(
            args.model,
            mesh,
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            learning_rate=args.lr,
            seq_shard=args.ring_attn,
            ring_attn=args.ring_attn,
            flash_attn=args.flash_attn,
            warmup_steps=args.warmup_steps,
            decay_steps=args.decay_steps,
            grad_clip=args.grad_clip,
        )
    if trainer.is_image:
        raise SystemExit(
            f"{args.model!r} is a CNN config; `train` feeds LM token "
            "batches (image models are profile-only for now)"
        )
    state = (
        restore_state(trainer, args.restore) if args.restore
        else trainer.init(seed=args.seed)
    )
    # resume the data stream where the saved run left it: the optimizer's
    # adamw step count IS the number of batches consumed (deterministic
    # seeded stream + count -> the restored run never re-trains on data
    # the checkpointed run already saw).  That arithmetic silently breaks
    # if the resuming invocation changes the stream (different seed,
    # shape, or data file), so the save writes the stream identity next
    # to the checkpoint and the restore warns on any drift.
    if args.restore:
        _warn_on_datastream_drift(args)
    resumed_at = 0
    if args.restore:
        import optax

        # every transform's count advances once per update, but an LR
        # schedule adds a SECOND "count" leaf (scale_by_schedule) and
        # tree_get raises on multiple matches — collect them all; they
        # agree, and max() is safe if a transform ever lacked one
        counts = optax.tree_utils.tree_get_all_with_path(state[1], "count")
        resumed_at = max((int(v) for _, v in counts), default=0)

    # The optimizer count (resumed_at) counts batches THIS HOST consumed;
    # stream `start` offsets are in GLOBAL positions.  Unsharded the two
    # coincide; under --host-shard i,n the host consumed global positions
    # i, i+n, ..., so its next global position is resumed_at * n.
    host_shard = None
    shard_n = 1
    if args.host_shard:
        try:
            i, n = (int(x) for x in args.host_shard.split(","))
        except ValueError:
            raise SystemExit(
                f"--host-shard wants INDEX,COUNT; got {args.host_shard!r}"
            )
        if n < 1 or not (0 <= i < n):
            raise SystemExit(
                f"--host-shard needs 0 <= INDEX < COUNT; got {i},{n}"
            )
        host_shard = (i, n)
        shard_n = n
    if args.data:
        ds = TokenFileDataset(
            args.data, batch_size=trainer.batch_size, seq_len=args.seq_len,
            dtype=args.data_dtype, seed=args.seed,
        )
        if ds.num_batches % shard_n:
            # unequal per-host epoch lengths would desync the count-based
            # resume arithmetic across epoch boundaries
            raise SystemExit(
                f"--host-shard COUNT={shard_n} must divide the dataset's "
                f"{ds.num_batches} batches for resumable streams"
            )
        per_host_epoch = ds.num_batches // shard_n

        def batches():
            # O(1) jump to the resume position: whole epochs are encoded
            # in the per-host count, the remainder maps back to a global
            # position in the epoch's permutation
            epoch, k = divmod(resumed_at, per_host_epoch)
            start = k * shard_n
            while True:
                yield from ds.batches(
                    epoch=epoch, start=start, host_shard=host_shard
                )
                epoch += 1
                start = 0
    else:
        def batches():
            yield from synthetic_lm_batches(
                batch_size=trainer.batch_size, seq_len=args.seq_len,
                vocab=trainer.cfg.vocab,
                num_batches=(resumed_at + args.steps) * shard_n,
                seed=args.seed,
                start=resumed_at * shard_n,  # per-index keying: O(1)
                host_shard=host_shard,
            )

    import itertools
    import time as _time

    first_loss = None
    t0 = None
    feed = prefetch_to_device(
        itertools.islice(batches(), args.steps), size=2,
        sharding=trainer.batch_sharding,
    )
    n = 0
    for batch in feed:
        state, loss = trainer.step(state, batch)
        n += 1
        if first_loss is None:
            # the float() readback fences compile+step 1; the timed
            # window starts here so tokens_per_s reports warm throughput
            first_loss = float(loss)
            t0 = _time.perf_counter()
    last_loss = float(loss)
    elapsed = _time.perf_counter() - t0
    tokens_per_s = (
        round((n - 1) * trainer.batch_size * args.seq_len / elapsed, 1)
        if n > 1 and elapsed > 0
        else None  # one step is all compile; no honest rate to report
    )
    if args.ckpt:
        save_state(state, args.ckpt)
        _write_datastream_meta(args)
    print(
        json.dumps(
            {
                "model": args.model,
                "steps": n,
                "mesh": dict(mesh.shape),
                "first_loss": first_loss,
                "last_loss": last_loss,
                "tokens_per_s": tokens_per_s,
                "checkpoint": args.ckpt or None,
                "resumed_at_step": resumed_at if args.restore else None,
            },
            sort_keys=True,
        )
    )
    from gpuschedule_tpu.obs import get_tracer

    if get_tracer().enabled:
        # per-step spans (parallel/train.py) aggregated: fenced step times
        # and tokens/s for the whole command, on stderr so the stdout JSON
        # contract above stays one line
        print(json.dumps({"spans": get_tracer().summary()}, sort_keys=True),
              file=sys.stderr)
    return 0


def cmd_profile(args) -> int:
    from gpuschedule_tpu.profiler import CurveCache
    from gpuschedule_tpu.profiler.harness import capture_trace, profile_model

    cache = CurveCache(args.curves)
    for model in args.model:
        curve = profile_model(
            model,
            ks=tuple(int(k) for k in args.ks.split(",")),
            generation=args.generation,
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            sp=args.sp,
            tp=args.tp,
            pp=args.pp,
            cache=cache,
        )
        print(json.dumps({"model": model, "theta": list(curve.theta)}))
        if args.trace_dir:
            path = capture_trace(
                model,
                f"{args.trace_dir}/{model}",
                batch_size=args.batch_size,
                seq_len=args.seq_len,
                sp=args.sp,
                tp=args.tp,
            )
            print(json.dumps({"model": model, "xprof_trace": path}))
    return 0


def _apply_platform_override() -> None:
    """Make the JAX_PLATFORMS env var mean what it says.

    This image's sitecustomize registers the axon TPU plugin at
    interpreter boot, which overrides the env var; only a programmatic
    config update before first backend access restores it (same hook as
    bench.py / tests/conftest.py).  No-op when the var is unset or jax is
    not installed — the sim core stays jax-free."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax
    except ImportError:
        return
    jax.config.update("jax_platforms", plat)


def _fixture_covered_codes(root) -> set:
    """GS codes exercised by the fixture trees under
    ``tests/lint_fixtures/`` — the non-vacuity floor ``--update-baseline``
    refuses to cross: a finding whose code no fixture can produce must
    not be baselined (add the fixture pair first)."""
    from gpuschedule_tpu.lint import run_lint

    fixtures = root / "tests" / "lint_fixtures"
    covered = {"GS001"}  # stale-baseline findings are never baselined
    if not fixtures.is_dir():
        return covered
    for tree in sorted(fixtures.iterdir()):
        if (tree / "gpuschedule_tpu").is_dir():
            covered.update(
                f.code for f in run_lint(tree).findings
            )
    return covered


def _update_baseline(root, baseline_path, old_entries) -> int:
    """``lint --update-baseline``: rewrite the baseline deterministically
    from the tree's current findings (sorted fingerprints, justifications
    carried over; new entries get an explicit edit-me placeholder)."""
    import json as _json

    from gpuschedule_tpu.lint import run_lint

    report = run_lint(root)  # pragma suppression applies, baseline doesn't
    if report.files_scanned == 0:
        raise SystemExit(f"no package files found under {root} — wrong root?")
    covered = _fixture_covered_codes(root)
    uncovered = sorted(
        {f.code for f in report.findings} - covered
    )
    if uncovered:
        raise SystemExit(
            "refusing to baseline findings for rule codes with zero "
            f"fixtures: {', '.join(uncovered)} — add a good/bad fixture "
            "pair under tests/lint_fixtures/ first "
            "(docs/static-analysis.md)"
        )
    old = {
        (e["code"], e["path"], e["detail"]): e["justification"]
        for e in old_entries
    }
    entries = []
    for key in sorted({(f.code, f.path, f.detail) for f in report.findings}):
        code, path, detail = key
        entries.append({
            "code": code, "path": path, "detail": detail,
            "justification": old.get(
                key, "UNJUSTIFIED — written by lint --update-baseline; "
                     "replace with a real reason before shipping"
            ),
        })
    doc = {
        "_comment": "Contract-linter findings baseline "
                    "(docs/static-analysis.md). Entries match findings on "
                    "(code, path, detail) — deliberately not line numbers. "
                    "Rewrite deterministically with `python -m "
                    "gpuschedule_tpu lint --update-baseline`.",
        "entries": entries,
    }
    baseline_path.parent.mkdir(parents=True, exist_ok=True)
    baseline_path.write_text(
        _json.dumps(doc, indent=2, sort_keys=False) + "\n"
    )
    dropped = len(old) - sum(
        1 for e in entries
        if (e["code"], e["path"], e["detail"]) in old
    )
    print(
        f"baseline rewritten: {len(entries)} entr"
        f"{'y' if len(entries) == 1 else 'ies'} "
        f"({dropped} stale dropped) -> {baseline_path}"
    )
    return 0


def cmd_lint(args) -> int:
    """``lint``: the contract linter (ISSUE 13/14) — AST-enforced
    determinism / seed-stream / event-schema / config-hash / cache /
    fork-safety / state-machine invariants over this checkout.  Exit 0
    when every finding is fixed, pragma-allowed, or baselined; 1
    otherwise.  Output is deterministic: the same tree and baseline
    produce byte-identical JSON, so ``--json`` artifacts diff cleanly
    and ``--history`` rows trend meaningfully."""
    from pathlib import Path

    from gpuschedule_tpu.lint import load_baseline, run_lint

    if args.root:
        root = Path(args.root)
        if not root.is_dir():
            raise SystemExit(f"lint root is not a directory: {args.root}")
    else:
        root = Path(__file__).resolve().parent.parent
    baseline = None
    baseline_path = (
        Path(args.baseline) if args.baseline
        else root / "tools" / "lint_baseline.json"
    )
    if baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, KeyError) as e:
            raise SystemExit(f"bad baseline {baseline_path}: {e}") from None
    elif args.baseline and not getattr(args, "update_baseline", False):
        # --update-baseline is allowed to CREATE the file it points at;
        # every other mode refuses a missing explicit baseline
        raise SystemExit(f"baseline not found: {args.baseline}")

    if getattr(args, "update_baseline", False):
        return _update_baseline(root, baseline_path, baseline or [])

    report = run_lint(root, baseline=baseline)
    if report.files_scanned == 0:
        # an empty scan exiting 0 would greenwash a mistyped --root
        raise SystemExit(f"no package files found under {root} — wrong root?")

    doc = report.render_json()
    if args.json is True:
        sys.stdout.write(doc)
    else:
        if args.json:
            Path(args.json).write_text(doc)
        for f in report.findings:
            print(f.render())
        print(
            f"contract-lint: {len(report.findings)} finding(s), "
            f"{report.baselined} baselined, {report.allowed} allowed by "
            f"pragma, {report.files_scanned} files, "
            f"{report.rules_run} rules / {report.rules} codes — "
            f"{'ok' if report.ok else 'FAIL'}"
        )
    if args.history:
        from gpuschedule_tpu.obs import HistoryStore

        with HistoryStore(args.history) as store:
            store.append("lint", metrics=report.summary_metrics(),
                         label="contract-lint")
    return 0 if report.ok else 1


def _add_world_args(p) -> None:
    """The world-building flags, defined ONCE and shared by every
    subcommand that builds a seeded world (``run``, ``whatif``): the
    builder helpers (build_cluster / load_jobs / build_policy /
    build_fault_plan / build_net / _run_config_hash) read them by
    attribute, so semantics — and the config hash — cannot drift
    between subcommands."""
    p.add_argument("--policy", choices=available(), default="fifo")
    p.add_argument("--policy-arg", action="append", metavar="K=V",
                   help="policy constructor kwarg (JSON values)")
    p.add_argument("--cluster", default="tpu-v5e",
                   choices=("simple", "tpu-v5e", "tpu-v5p", "gpu"))
    p.add_argument("--chips", type=int, default=64,
                   help="simple cluster size")
    p.add_argument("--dims", help="TPU pod dims, e.g. 16x16 / 8x8x4")
    p.add_argument("--pods", type=int, default=1)
    p.add_argument("--gpu-shape", default="2x4x8",
                   help="switches x nodes x gpus for --cluster gpu")
    p.add_argument("--placement", default="consolidated",
                   help="consolidated|random|greedy|topology (gpu) / "
                        "consolidated|random|spread|contention|health "
                        "(tpu; contention needs --net, health steers "
                        "away from degraded/high-hazard chips)")
    p.add_argument("--placement-seed", type=int, default=0)
    p.add_argument("--philly", help="Philly-schema trace CSV")
    p.add_argument("--trace", help="native-schema trace CSV")
    p.add_argument("--synthetic", type=int, metavar="N",
                   help="generate N-job Poisson trace")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--arrival-rate", type=float, default=1.0 / 60.0)
    p.add_argument("--mean-duration", type=float, default=3600.0)
    p.add_argument("--failure-rate", type=float, default=0.0)
    p.add_argument("--util-min", type=float, default=1.0)
    p.add_argument("--max-job-chips", type=int, default=256)
    p.add_argument("--max-time", type=float)
    p.add_argument("--curves", help="goodput curve cache (optimus)")
    p.add_argument("--online", action="store_true",
                   help="profile unseen models live (optimus)")
    p.add_argument("--faults", metavar="SPEC",
                   help="inject hardware faults: k=v pairs, e.g. "
                        "mtbf=86400,repair=3600,ckpt=1800 (keys: mtbf, "
                        "repair, maintenance, maintenance_duration, spot, "
                        "spot_mtbf, spot_outage, spot_warning (pre-revoke "
                        "notice window: emergency checkpoints when it "
                        "covers the write cost), domain_mtbf / "
                        "domain_repair (correlated host/rack/pod "
                        "outages), domain_host / domain_rack / "
                        "domain_pod (per-level outage-rate multipliers), "
                        "hazard_shape (Weibull shape; 1 = memoryless), "
                        "hazard_util (wear-driven aging weight), "
                        "migrate_threshold (proactive checkpoint-and-"
                        "migrate trigger), straggler_mtbf / "
                        "straggler_repair / "
                        "straggler_degrade (slow chips pacing their "
                        "gangs), link_mtbf / link_repair / link_degrade, "
                        "ckpt, restore, ckpt_write (priced periodic "
                        "checkpoint writes; 'auto' sizes from model "
                        "state); seconds, inf ok, restore=auto derives "
                        "cost from the model size).  The fault schedule "
                        "derives from --seed via independent RNG "
                        "streams, so trace and faults reproduce together")
    p.add_argument("--net", nargs="?", const=True, default=None,
                   metavar="SPEC",
                   help="model the shared DCN fabric (net/): multislice "
                        "jobs get max-min fair bandwidth shares instead "
                        "of the static isolated-fabric speed factor, "
                        "re-priced on every running-set change.  SPEC is "
                        "k=v pairs: os (core oversubscription ratio, "
                        "default 4), ingest (Gbps per occupied chip, "
                        "default 0.05), uplinks (redundant sibling "
                        "uplinks per pod, default 1; >1 arms adaptive "
                        "routing around degraded links), partial "
                        "(bottleneck-group partial re-solve with the "
                        "hierarchical core tier, default 0).  TPU "
                        "clusters only; enables the "
                        "'contention' placement scheme's residual-"
                        "bandwidth scoring and ('link', pod) fault "
                        "degradation")
    p.add_argument("--accounting", choices=("v1", "v2"), default="v1",
                   help="progress-accounting version (ISSUE 11): v1 "
                        "(default) keeps the historical chunk-per-batch "
                        "integration and its byte-identity contract; v2 "
                        "integrates lazily / vectorized under an "
                        "exact-sum closure contract instead — ~2x "
                        "jobs/sec on policies that don't read running "
                        "progress per batch.  v2 rides the config hash")


def main(argv: Optional[List[str]] = None) -> int:
    _apply_platform_override()
    p = argparse.ArgumentParser(prog="gpuschedule_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="replay a trace under a policy")
    _add_world_args(run)
    run.add_argument("--out", help="directory for jobs/utilization CSVs")
    run.add_argument("--prefix", default="")
    run.add_argument("--events", nargs="?", const=True, default=None,
                     metavar="PATH",
                     help="record a structured events.jsonl stream (opt-in: "
                          "~1 record per state transition; streamed "
                          "incrementally, constant memory).  With PATH the "
                          "stream goes there directly; the bare flag writes "
                          "events.jsonl under --out.  The stream opens with "
                          "a schema header (run_id/seed/policy/config_hash) "
                          "for `report` and `compare`")
    run.add_argument("--perfetto", metavar="PATH",
                     help="export the replay as a Chrome/Perfetto trace "
                          "(one track per pod/slice, one slice per job "
                          "occupancy interval); implies event recording")
    run.add_argument("--spans", action="store_true",
                     help="enable the obs span tracer (engine batches + "
                          "policy invocations); writes spans.trace.json "
                          "under --out and prints a span summary to stderr")
    run.add_argument("--attrib", action="store_true",
                     help="causal slowdown attribution: blame every queued "
                          "interval with its cause (capacity / policy-"
                          "preempt / fault-outage / admission), split "
                          "running time into work / policy-share / net-"
                          "degraded / overhead legs, and stamp the exact "
                          "cumulative legs onto the event stream — the "
                          "analyzer's wait/JCT decompositions close bit-"
                          "exactly against the engine's own arithmetic.  "
                          "Adds delay_<cause>_s keys to the summary line; "
                          "off, the run is byte-identical to before this "
                          "flag existed")
    run.add_argument("--snapshot", metavar="PATH",
                     help="with --snapshot-every: serialize the full "
                          "engine state here periodically, making the "
                          "replay crash-resumable (run --resume PATH)")
    run.add_argument("--snapshot-every", type=float, metavar="SECONDS",
                     help="sim-seconds between engine snapshots (arms "
                          "together with --snapshot)")
    run.add_argument("--resume", metavar="SNAPSHOT",
                     help="restore a mid-replay engine from a --snapshot "
                          "file and finish it; under v1 accounting the "
                          "finished outputs are byte-identical to the "
                          "uninterrupted run.  World-building flags are "
                          "ignored — the snapshot is the world")
    run.add_argument("--sample-interval", type=float, metavar="SECONDS",
                     help="emit periodic cluster-side 'sample' events "
                          "(physical occupancy, health-masked chips, per-"
                          "pod fragmentation, queue depth) every SECONDS "
                          "of sim time; with --events the analyzer/report "
                          "overlay physical on demand occupancy and "
                          "Perfetto gains counter tracks.  Sampling never "
                          "perturbs the replay")
    run.add_argument("--prom", metavar="PATH",
                     help="write run counters/gauges/histograms in the "
                          "Prometheus text exposition format (with --out, "
                          "metrics.prom/metrics.json are written there too)")
    run.add_argument("--self-profile", metavar="PATH",
                     help="profile the replay loop itself: bucket each "
                          "batch's WALL time into phases (event-apply / "
                          "policy / net-resolve / fault-dispatch / advance "
                          "/ metrics / analytics) and write PATH as a "
                          "ui.perfetto.dev-loadable document with the "
                          "machine-readable 'selfprof' summary block; "
                          "phase times sum to total replay wall time "
                          "exactly.  Replay output is byte-identical with "
                          "or without the flag")
    run.add_argument("--cache-stats", action="store_true",
                     help="unified engine cache telemetry: harvest every "
                          "PR-7/9 cache's hit/miss/invalidate counts "
                          "(fabric pricing, flow list, bottleneck groups, "
                          "TPU allocate caches, bitmask rows, engine "
                          "memos) into cache_<name>_<outcome> summary "
                          "keys, the engine_cache_events{cache,outcome} "
                          "registry family, and a trailing 'cache' stream "
                          "record the report's Engine-health panel renders")
    run.add_argument("--sample-on-change", action="store_true",
                     help="with --sample-interval or alone: additionally "
                          "emit a cluster 'sample' event whenever a batch "
                          "changes the health/degrade masks (fault, "
                          "repair, straggler onset/recovery, domain "
                          "outage) — state-driven snapshots at exactly "
                          "the transitions, not just the timer.  Never "
                          "perturbs the replay")
    run.add_argument("--history", metavar="STORE",
                     help="append this run's summary to the sqlite "
                          "history store at STORE (created if missing), "
                          "keyed by run_id/config_hash — `history trend` "
                          "renders trajectories across invocations")
    run.add_argument("--flush-events", type=float, default=None,
                     metavar="SECONDS",
                     help="tailable-sink flush cadence (ISSUE 15): flush "
                          "the --events stream to disk at least every "
                          "SECONDS of sim time, so `watch --follow` is "
                          "never more than one interval behind the "
                          "replay.  Default: 512-record batching only "
                          "(byte-identical to the historical writer)")
    run.set_defaults(fn=cmd_run)

    wi = sub.add_parser(
        "whatif",
        help="interactive what-if queries against a mirrored replay: "
             "pause the world at --at, then answer admit / drain / "
             "policy-swap questions by speculative forks with "
             "attributed deltas (ISSUE 12)",
    )
    _add_world_args(wi)
    wi.add_argument("--at", type=float, required=True, metavar="SECONDS",
                    help="sim time to mirror the world at: the engine "
                         "replays to the last batch at or before this "
                         "instant and pauses there")
    wi.add_argument("--resume", metavar="SNAPSHOT",
                    help="mirror from an engine snapshot (e.g. a flight-"
                         "recorder pin from `watch --flight-dir`) instead "
                         "of rebuilding the world: restore, replay "
                         "forward to --at, and serve queries there.  "
                         "World-building flags are ignored — the "
                         "snapshot is the world (ISSUE 15)")
    wi.add_argument("--horizon", type=float, default=86_400.0,
                    metavar="SECONDS",
                    help="bounded speculative-replay horizon per query "
                         "(default: one day of sim time); deltas compare "
                         "variant vs baseline forks at at+horizon")
    wi.add_argument("--pool", type=int, default=0, metavar="N",
                    help="persistent worker processes serving queries "
                         "concurrently (each restores the mirror once, "
                         "then forks per query); 0 (default) evaluates "
                         "in-process")
    wi.add_argument("--admit", action="append", metavar="SPEC",
                    help="admit query: chips=8,duration=3600"
                         "[,model=M][,at=T][,pods=0:2:5] — one candidate "
                         "evaluation per pod in pods (omitted: the "
                         "policy places it); repeatable")
    wi.add_argument("--drain", action="append", metavar="SPEC",
                    help="drain query: pod=7[,at=T][,duration=S] "
                         "(duration defaults to permanent); repeatable")
    wi.add_argument("--swap-policy", action="append", metavar="NAME",
                    choices=available(),
                    help="policy-swap query: rerun the future under "
                         "NAME; repeatable")
    wi.add_argument("--out", metavar="PATH",
                    help="also write the full result document here")
    wi.add_argument("--history", metavar="STORE",
                    help="append one history row per query (kind "
                         "'whatif') to the sqlite store at STORE")
    wi.add_argument("--prom", metavar="PATH",
                    help="write the query-latency histogram "
                         "(whatif_query_latency_ms{kind}) in Prometheus "
                         "text format")
    wi.add_argument("--trace-out", metavar="PATH", dest="trace_out",
                    help="write ONE merged Perfetto/Chrome trace of the "
                         "whole fleet: parent enqueue/dispatch/reassemble "
                         "spans plus a named track per worker, every "
                         "worker span carrying the propagated trace id; "
                         "also federates worker counters into --prom "
                         "(ISSUE 16).  Off by default — disarmed runs "
                         "are byte-identical")
    wi.set_defaults(fn=cmd_whatif)

    sv = sub.add_parser(
        "serve",
        help="serve the twin (ISSUE 18): a long-lived observability "
             "control plane over the mirrored world — /metrics scrape, "
             "SSE alert feed (/alerts), admission-controlled POST "
             "/whatif, /status + /healthz + /readyz, a live dashboard "
             "at /, and a self-SLO watchdog that pages about the "
             "daemon itself",
    )
    _add_world_args(sv)
    sv.add_argument("--at", type=float, required=True, metavar="SECONDS",
                    help="sim time to mirror the world at (exactly like "
                         "`whatif --at`): the daemon serves queries "
                         "against the engine paused there")
    sv.add_argument("--horizon", type=float, default=86_400.0,
                    metavar="SECONDS",
                    help="bounded speculative-replay horizon per served "
                         "query (default: one day of sim time)")
    sv.add_argument("--pool", type=int, default=0, metavar="N",
                    help="persistent worker processes serving queries "
                         "(0 = in-process; served documents are pinned "
                         "identical either way)")
    sv.add_argument("--host", default="127.0.0.1",
                    help="listen address (default 127.0.0.1)")
    sv.add_argument("--port", type=int, default=0, metavar="PORT",
                    help="listen port; 0 (default) binds an ephemeral "
                         "port, announced on the {\"serve\": ...} line")
    sv.add_argument("--max-inflight", type=int, default=None, metavar="N",
                    dest="max_inflight",
                    help="admission bound on concurrently admitted "
                         "queries (default: 2 x max(1, --pool)); a full "
                         "queue answers 429 + whatif_rejected_total")
    sv.add_argument("--events", metavar="EVENTS_JSONL",
                    help="also watch this event stream through the "
                         "PR-15 detector set; alerts fan out to the SSE "
                         "feed, --alerts, --history, and "
                         "watch_alerts_total")
    sv.add_argument("--follow", action="store_true",
                    help="tail --events as a GROWING file (live run)")
    sv.add_argument("--replay", action="store_true",
                    help="pace --events as-if-live by sim time")
    sv.add_argument("--speed", type=float, default=0.0, metavar="X",
                    help="--replay pacing: X sim seconds per wall "
                         "second (0 = no pacing)")
    sv.add_argument("--poll", type=float, default=0.5, metavar="SECONDS",
                    help="--follow poll interval (wall)")
    sv.add_argument("--idle-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="--follow: stop watching after this long "
                         "without stream growth")
    sv.add_argument("--max-wall", type=float, default=None,
                    metavar="SECONDS",
                    help="hard wall-clock serving budget: shut down "
                         "gracefully after SECONDS (default: serve "
                         "until SIGTERM/SIGINT)")
    sv.add_argument("--rules", metavar="RULES_JSON",
                    help="detector config overlaying DEFAULT_RULES "
                         "(like `watch --rules`)")
    sv.add_argument("--window", type=float, metavar="SECONDS",
                    help="detector window length (overrides rules)")
    sv.add_argument("--self-slo", metavar="JSON", dest="self_slo",
                    help="self-SLO watchdog overrides as a JSON object "
                         "(SELF_SLO_DEFAULTS keys: latency_slo_ms, "
                         "target, fast_burn, slow_burn, window_queries, "
                         "slow_windows)")
    sv.add_argument("--alerts", metavar="PATH",
                    help="write the alert side stream (cluster AND "
                         "self-SLO alerts) here")
    sv.add_argument("--history", metavar="STORE",
                    help="append alert rows (kind 'watch') live and one "
                         "kind 'serve' session row at shutdown")
    sv.add_argument("--prom", metavar="PATH",
                    help="also write the final registry in Prometheus "
                         "text format at shutdown (the live surface is "
                         "GET /metrics)")
    sv.add_argument("--drain-s", type=float, default=10.0, dest="drain_s",
                    metavar="SECONDS",
                    help="graceful-shutdown budget for draining "
                         "in-flight queries (default 10)")
    sv.set_defaults(fn=cmd_serve)

    lint = sub.add_parser(
        "lint",
        help="contract linter (ISSUE 13): statically enforce the "
             "determinism / seed-stream / event-schema / config-hash / "
             "cache-discipline / fork-safety invariants; exit 1 on any "
             "unbaselined finding (rule catalog: docs/static-analysis.md)",
    )
    lint.add_argument("--root", metavar="DIR",
                      help="repo checkout to lint (default: the checkout "
                           "containing this package)")
    lint.add_argument("--baseline", metavar="JSON",
                      help="findings baseline (default: "
                           "ROOT/tools/lint_baseline.json when present)")
    lint.add_argument("--json", nargs="?", const=True, default=None,
                      metavar="PATH",
                      help="emit the deterministic JSON report (bare flag: "
                           "stdout instead of the human rendering; with "
                           "PATH: write there, keep the human output)")
    lint.add_argument("--update-baseline", action="store_true",
                      help="rewrite ROOT/tools/lint_baseline.json (or "
                           "--baseline) deterministically from the "
                           "tree's current findings: sorted "
                           "fingerprints, stale entries dropped, "
                           "existing justifications kept; refuses "
                           "findings for rule codes no fixture tree "
                           "exercises")
    lint.add_argument("--history", metavar="STORE",
                      help="append the summary metrics to the sqlite "
                           "history store at STORE (kind 'lint') — "
                           "finding-count trends ride `history trend`")
    lint.set_defaults(fn=cmd_lint)

    gen = sub.add_parser("gen-trace", help="write a synthetic trace CSV")
    gen.add_argument("--num-jobs", type=int, required=True)
    gen.add_argument("--seed", type=int, default=0)
    gen.add_argument("--philly-like", action="store_true")
    gen.add_argument("--arrival-rate", type=float, default=None,
                     help="jobs/sec; defaults to 1/60 (poisson) or the "
                          "published Philly rate 1/67.3 (--philly-like)")
    gen.add_argument("--mean-duration", type=float, default=3600.0)
    gen.add_argument("--failure-rate", type=float, default=0.0)
    gen.add_argument("--util-min", type=float, default=1.0)
    gen.add_argument("--out", required=True)
    gen.set_defaults(fn=cmd_gen_trace)

    fl = sub.add_parser(
        "faults",
        help="fault-injection demo: seeded chaos replay across the policy "
             "suite with goodput decomposition",
    )
    fl.add_argument("--policies",
                    help="comma list of policy configs (default: the whole "
                         "eight-policy suite; see tools/fault_sweep.py)")
    fl.add_argument("--mtbf", type=float, default=6 * 3600.0,
                    help="per-chip mean time between failures, seconds "
                         "(inf = fault-free control arm)")
    fl.add_argument("--repair", type=float, default=3600.0,
                    help="mean repair duration, seconds")
    fl.add_argument("--ckpt", type=float, default=1800.0,
                    help="checkpoint interval in work-seconds (progress "
                         "rolls back to the last multiple on a fault)")
    fl.add_argument("--restore", default="auto",
                    help="restart cost per revocation: seconds, or 'auto' "
                         "to derive from model size and slice shape")
    fl.add_argument("--num-jobs", type=int, default=200,
                    help="Philly-like trace length")
    fl.add_argument("--seed", type=int, default=0,
                    help="governs trace AND fault streams (seed-split rule)")
    fl.add_argument("--dims", default="8x8", help="TPU pod dims")
    fl.add_argument("--pods", type=int, default=1)
    fl.add_argument("--max-time", type=float,
                    help="horizon cutoff (also bounds schedule generation)")
    fl.add_argument("--out", help="also write the JSON document here")
    fl.add_argument("--events", metavar="DIR",
                    help="capture one <policy>.events.jsonl per cell into "
                         "DIR (each with its own schema header), ready for "
                         "`report` / `compare`")
    fl.set_defaults(fn=cmd_faults)

    rep = sub.add_parser(
        "report",
        help="render an events.jsonl stream as one self-contained HTML "
             "report (inline CSS/SVG, zero network fetches)",
    )
    rep.add_argument("--events", required=True, metavar="EVENTS_JSONL",
                     help="stream captured by `run --events` / `faults "
                          "--events`")
    rep.add_argument("--out", required=True, metavar="REPORT_HTML")
    rep.add_argument("--title", help="report heading (default: from header)")
    rep.add_argument("--json", metavar="PATH",
                     help="also dump the full analysis document as JSON")
    rep.add_argument("--no-header", action="store_true",
                     help="admit bare streams captured without run identity "
                          "(Python API without run_meta)")
    rep.add_argument("--low-mem", action="store_true",
                     help="bounded-memory analysis: spill finished job "
                          "records to a sqlite temp store so multi-GB "
                          "streams render at O(active jobs) resident "
                          "memory; output (HTML and --json document, now "
                          "streamed from the store) is byte-identical")
    rep.add_argument("--selfprof", metavar="PROFILE_JSON",
                     help="fold a `run --self-profile` document into the "
                          "report's Engine-health panel (wall-clock "
                          "phase stacked bar)")
    rep.add_argument("--alerts", metavar="ALERTS_JSONL",
                     help="fold a `watch --alerts` side stream into the "
                          "report: timeline ticks on the occupancy chart "
                          "plus a per-detector Alerts panel")
    rep.set_defaults(fn=cmd_report)

    wt = sub.add_parser(
        "watch",
        help="live-tail watchtower (ISSUE 15): stream an events.jsonl "
             "through rolling-window detectors (queue-depth surge, "
             "goodput collapse, fragmentation creep, hazard spike, "
             "multi-window SLO burn rate), emitting schema-additive "
             "alert records, history rows, and watch_alerts_total "
             "counters, with a flight recorder for whatif replay",
    )
    wt.add_argument("--events", required=True, metavar="EVENTS_JSONL",
                    help="the stream to watch (written by `run --events`; "
                         ".gz accepted in batch/--replay modes)")
    wt.add_argument("--follow", action="store_true",
                    help="tail a GROWING file: poll for appends, retain "
                         "mid-record truncated tails until the writer "
                         "completes them")
    wt.add_argument("--replay", action="store_true",
                    help="pace a finished stream as-if-live by sim time "
                         "(deterministic: any --speed yields the batch "
                         "mode's exact alert sequence)")
    wt.add_argument("--rules", metavar="RULES_JSON",
                    help="declarative detector config overlaying the "
                         "defaults (obs/watch.py DEFAULT_RULES); unknown "
                         "detectors/keys are rejected")
    wt.add_argument("--window", type=float, metavar="SECONDS",
                    help="detector window length (overrides rules)")
    wt.add_argument("--ring", type=int, metavar="N",
                    help="flight-recorder ring size in raw events "
                         "(overrides rules)")
    wt.add_argument("--alerts", metavar="PATH",
                    help="write the alert side stream here (JSONL behind "
                         "its own versioned header; see docs/events.md)")
    wt.add_argument("--flight-dir", metavar="DIR",
                    help="flight recorder: on each alert, dump the last "
                         "--ring raw events (and pin the watched run's "
                         "newest --snapshot engine state) into DIR")
    wt.add_argument("--snapshot", metavar="PATH",
                    help="the watched run's `--snapshot` file: each alert "
                         "pins a copy (plus its .meta.json sim-time "
                         "sidecar) so `whatif --resume` replays the "
                         "minutes before the incident")
    wt.add_argument("--history", metavar="STORE",
                    help="append one history row per alert (kind 'watch', "
                         "label = detector) to the sqlite store")
    wt.add_argument("--prom", metavar="PATH",
                    help="write watch_alerts_total{detector} in the "
                         "Prometheus text exposition format")
    wt.add_argument("--speed", type=float, default=0.0, metavar="X",
                    help="--replay pacing: X sim seconds per wall second "
                         "(0 = no pacing, the default)")
    wt.add_argument("--poll", type=float, default=0.5, metavar="SECONDS",
                    help="--follow poll interval (wall)")
    wt.add_argument("--idle-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="--follow: stop after this long without growth "
                         "(default: tail forever)")
    wt.add_argument("--max-wall", type=float, default=None,
                    metavar="SECONDS",
                    help="--follow: hard wall-clock stop")
    wt.set_defaults(fn=cmd_watch)

    cmpr = sub.add_parser(
        "compare",
        help="regression-diff two event streams for CI gating (exit 0 "
             "within thresholds, 1 regressed, 2 not comparable); three "
             "or more render an n-way policy x metric matrix with "
             "best/worst highlighting",
    )
    cmpr.add_argument("streams", nargs="+", metavar="EVENTS_JSONL",
                      help="two streams: baseline + candidate (the CI "
                           "gate); three or more: n-way matrix columns")
    cmpr.add_argument("--threshold", action="append",
                      metavar="FLOAT|METRIC=FLOAT",
                      help="relative worsening gate: a bare float sets the "
                           "default (0.05), METRIC=FLOAT overrides one "
                           "metric; repeatable.  Negative values demand "
                           "improvement")
    cmpr.add_argument("--allow-mismatch", action="store_true",
                      help="compare runs of different seeds/configs anyway "
                           "(the deltas then measure the worlds, not the "
                           "scheduler)")
    cmpr.add_argument("--json", metavar="PATH",
                      help="write the machine-readable diff here")
    cmpr.add_argument("--low-mem", action="store_true",
                      help="bounded-memory analysis of each stream (see "
                           "report --low-mem); verdicts byte-identical")
    cmpr.add_argument("--history", metavar="STORE",
                      help="append every compared stream's summary to the "
                           "sqlite history store (keyed by its header "
                           "identity) so repeated compares accumulate "
                           "`history trend` trajectories")
    cmpr.set_defaults(fn=cmd_compare)

    hist = sub.add_parser(
        "history",
        help="cross-run history store: list appended results and render "
             "per-metric trajectories across invocations",
    )
    hist.add_argument("action", choices=("list", "trend"),
                      help="list: matching rows; trend: per-metric "
                           "trajectory table with step deltas")
    hist.add_argument("--store", required=True, metavar="STORE",
                      help="sqlite store written by run/compare/"
                           "engine_bench --history")
    hist.add_argument("--metric", action="append", metavar="NAME",
                      help="summary metric(s) to render (trend; "
                           "repeatable; default avg_jct)")
    hist.add_argument("--kind", help="filter: run / compare / bench")
    hist.add_argument("--config", metavar="HASH",
                      help="filter by config_hash (compare-compatible "
                           "worlds only)")
    hist.add_argument("--label", help="filter by bench label, e.g. "
                                      "plain/1000")
    hist.add_argument("--last", type=int, metavar="N",
                      help="only the newest N matching rows")
    hist.add_argument("--json", metavar="PATH",
                      help="also write the matching rows as JSON")
    hist.set_defaults(fn=cmd_history)

    cmp_ = sub.add_parser("compare-topology",
                          help="config #5: GPU placement schemes vs TPU slices")
    cmp_.add_argument("--policy", choices=available(), default="fifo")
    cmp_.add_argument("--policy-arg", action="append", metavar="K=V",
                      help="policy constructor kwarg (JSON values), e.g. "
                           "backfill=true")
    cmp_.add_argument("--philly")
    cmp_.add_argument("--synthetic", type=int)
    cmp_.add_argument("--seed", type=int, default=0)
    cmp_.add_argument("--gpu-shape", default="4x8x8")
    cmp_.add_argument("--seeds", type=int, default=1,
                      help="random-placement draws to average (config #5 "
                           "seed sweep)")
    cmp_.add_argument("--load-sweep", action="store_true",
                      help="also sweep offered load (70/80/90/95%%) and "
                           "report the acceptance band per load")
    cmp_.add_argument("--out")
    cmp_.set_defaults(fn=cmd_compare_topology)

    tr = sub.add_parser("train", help="train a model on a device mesh")
    tr.add_argument("--model", required=True)
    tr.add_argument("--steps", type=int, default=10)
    tr.add_argument("--batch-size", type=int, default=8)
    tr.add_argument("--seq-len", type=int, default=128)
    tr.add_argument("--lr", type=float, default=1e-3)
    tr.add_argument("--warmup-steps", type=int, default=0,
                    help="linear LR warmup steps (0 = none)")
    tr.add_argument("--decay-steps", type=int, default=None,
                    help="cosine-decay LR to zero over this many "
                         "post-warmup steps")
    tr.add_argument("--grad-clip", type=float, default=None,
                    help="global-norm gradient clipping threshold")
    tr.add_argument("--sp", type=int, default=1)
    tr.add_argument("--tp", type=int, default=1)
    tr.add_argument("--pp", type=int, default=1,
                    help="pipeline stages (>=2 trains the staged "
                         "PipelinedLM; incompatible with --sp/--tp/"
                         "--ring-attn)")
    tr.add_argument("--microbatches", type=int, default=4,
                    help="pipeline microbatch count M (bubble fraction "
                         "(pp-1)/(M+pp-1); only with --pp >= 2)")
    tr.add_argument("--pp-schedule", choices=("gpipe", "remat"),
                    default="gpipe",
                    help="pipeline activation-memory schedule: gpipe "
                         "stores per-tick stage internals, remat "
                         "recomputes them per microbatch")
    tr.add_argument("--devices", type=int,
                    help="use only the first N devices (default: all)")
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--flash-attn", action="store_true",
                    help="blockwise pallas attention core")
    tr.add_argument("--ring-attn", action="store_true",
                    help="sequence-shard over sp with ring attention "
                         "(implies seq sharding; with --flash-attn, the "
                         "ring-flash composition)")
    tr.add_argument("--data", help="flat binary token file (see data/)")
    tr.add_argument("--data-dtype", default="uint16")
    tr.add_argument("--host-shard", default=None, metavar="INDEX,COUNT",
                    help="multi-host input split: this host yields every "
                         "COUNT-th batch starting at INDEX (streams "
                         "partition the epoch exactly; resume offsets "
                         "stay host-count-independent)")
    tr.add_argument("--ckpt", help="save final state here (orbax)")
    tr.add_argument("--restore", help="resume from this checkpoint")
    tr.set_defaults(fn=cmd_train)

    obs = sub.add_parser("obs", help="observability utilities (trace export)")
    obs_sub = obs.add_subparsers(dest="obs_cmd", required=True)
    exp = obs_sub.add_parser(
        "export",
        help="convert a run's events.jsonl into a ui.perfetto.dev-loadable "
             "Chrome trace-event JSON",
    )
    exp.add_argument("--events", required=True, metavar="EVENTS_JSONL",
                     help="events.jsonl written by `run --events --out`")
    exp.add_argument("--out", required=True, metavar="TRACE_JSON")
    exp.set_defaults(fn=cmd_obs_export)

    prof = sub.add_parser("profile", help="fit goodput curves on live devices")
    prof.add_argument("--model", action="append", required=True)
    prof.add_argument("--ks", default="1,2,4,8,16,32,64")
    prof.add_argument("--generation", default="v5e")
    prof.add_argument("--batch-size", type=int, default=8)
    prof.add_argument("--seq-len", type=int, default=128)
    prof.add_argument("--sp", type=int, default=1,
                      help="sequence-parallel degree of each measured mesh")
    prof.add_argument("--tp", type=int, default=1,
                      help="tensor-parallel degree of each measured mesh")
    prof.add_argument("--pp", type=int, default=1,
                      help="pipeline stages of each measured mesh (>=2 "
                           "measures the staged PipelinedLM; dp-only "
                           "composition)")
    prof.add_argument("--curves", required=True)
    prof.add_argument("--trace-dir",
                      help="also capture an xprof trace of the step here")
    prof.set_defaults(fn=cmd_profile)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
