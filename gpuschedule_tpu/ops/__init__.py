"""Pallas TPU kernels for the benchmark models.

The hot-op layer the package docstring promises: hand-written kernels for
ops where explicit VMEM blocking beats what XLA fusion produces.  Each op
degrades gracefully off-TPU (pallas interpret mode), so the same code path
runs in CPU-mesh tests and on real chips.
"""

from gpuschedule_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
