"""Dense-attention oracle shared by the kernel tests and backward passes.

One implementation, imported by both the pallas flash kernel
(:mod:`gpuschedule_tpu.ops.flash_attention` — its recompute backward) and
the ring-attention layer/tests (:mod:`gpuschedule_tpu.parallel.ringattn`),
so the numerical ground truth cannot drift between them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # mask value: exp(NEG_INF - m) underflows to exactly 0 in f32


def dense_attention(q, k, v, *, causal: bool) -> jax.Array:
    """Plain (B, S, H, D) attention; f32 math, input dtype out."""
    d = q.shape[-1]
    logits = jnp.einsum(
        "blhd,bmhd->bhlm", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.float32(d))
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhlm,bmhd->blhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)
