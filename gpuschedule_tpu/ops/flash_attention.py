"""Flash attention (forward + blockwise backward) in pallas (TPU).

Blockwise causal attention that never materializes the (S, S) score
matrix — and never holds more than one K/V *block* in VMEM: the grid is
(batch*heads, q-blocks, k-blocks) with the K/V block index innermost, so
pallas streams (block_k, d) tiles HBM→VMEM while the online-softmax state
(running max, denominator, weighted numerator) is carried across k steps
in VMEM scratch.

**Memory contract (forward AND backward).**  Peak on-chip footprint is
O(block_q·d + block_k·d) per (batch, head) — independent of S — in both
directions.  The forward saves only the per-row logsumexp (O(S) per
batch·head, lane-replicated f32); the backward is the standard
flash-attention-2 structure: two more blockwise kernels recompute the
probabilities per (q-block, k-block) tile from q/k and the saved
logsumexp, accumulating dq in one pass (k innermost) and dk/dv in a
second (q innermost).  No (S, S) intermediate exists anywhere — the
long-context property holds end-to-end through training, not just
inference (the round-3 backward was a dense XLA recompute; see
``tests/test_flash_attention.py::test_backward_never_materializes_s_by_s``
for the executable form of this contract).

**Dtype policy.**  Matmuls run in the *input* dtype with f32 accumulation
(``preferred_element_type``): bf16 q/k/v — the model zoo's compute dtype —
hits the MXU at full bf16 rate, while the online-softmax state, logsumexp,
and every probability/score stays f32.  The probability operand of the
p·V / pᵀ·dO / dsᵀ·q dots is cast down to the value dtype (standard
flash-attention-2 practice); f32 inputs keep the all-f32 numerics.

Row statistics (running max / denominator / logsumexp / delta) are kept
**lane-replicated at width 128** in VMEM and HBM — the layout Mosaic's
tiling expects (f32 tiles are (8, 128); a (block_q, 1) scratch is
narrower than one lane tile).  Reads reduce over the replicated lanes
(``max``), writes broadcast back, so arbitrary block sizes still work.

Head dim and sequence length are padded to lane/block multiples and
unpadded on the way out, so any model shape works.  This is the
single-chip sibling of the cross-chip ring in
:mod:`gpuschedule_tpu.parallel.ringattn`: same math, different memory
system (VMEM blocking vs ICI ppermute).

Off-TPU the kernels run in pallas interpret mode automatically, so CPU
tests exercise the very same code path the chip compiles.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gpuschedule_tpu.ops.reference import NEG_INF, dense_attention

# Lane width of the replicated row-statistic arrays (m, l, lse, delta):
# the f32 VMEM tile is (8, 128), so row vectors are stored broadcast
# across 128 lanes and reduced (max) back to (rows, 1) on read.
LANES = 128


def _reference(q, k, v, causal):
    """Positional-arg shim over the shared oracle (test-facing name)."""
    return dense_attention(q, k, v, causal=causal)


def _pick_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row(block) -> jax.Array:
    """(rows, 1) row statistic from a lane-replicated (rows, LANES) block."""
    return jnp.max(block, axis=-1, keepdims=True)


def _rep(rowvec, rows: int) -> jax.Array:
    """Broadcast a (rows, 1) row statistic back to the (rows, LANES) layout."""
    return jnp.broadcast_to(rowvec, (rows, LANES))


def _mask(qi, kb, *, block_q, block_k, causal, seq_len):
    """Validity mask for the (block_q, block_k) score tile at (qi, kb):
    padding columns beyond seq_len are dead; causal kills cols > rows."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    valid = cols < seq_len
    if causal:
        valid = jnp.logical_and(valid, rows >= cols)
    return valid


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, block_q, block_k, causal, sm_scale, seq_len,
):
    """Grid (bh, qi, kb), kb innermost: scratch carries the online-softmax
    state across k blocks of one (bh, qi); the output block and the row
    logsumexp (the only residual the backward needs) are written on the
    last k step."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _update():
        # Dots run in the INPUT dtype (bf16 on the train path -> full MXU
        # rate) with f32 accumulation; sm_scale is applied to the f32
        # product, not the operand, so bf16 q loses nothing to the scale.
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        valid = _mask(
            qi, kb, block_q=block_q, block_k=block_k, causal=causal,
            seq_len=seq_len,
        )
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = _row(m_ref[...]), _row(l_ref[...])
        acc_prev = acc_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = _rep(m_new, block_q)
        l_ref[...] = _rep(l_prev * corr + p.sum(axis=-1, keepdims=True), block_q)
        acc_ref[...] = acc_prev * corr + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )

    if causal:
        # k blocks wholly above the diagonal contribute nothing
        @pl.when(kb * block_k <= qi * block_q + (block_q - 1))
        def _():
            _update()
    else:
        _update()

    @pl.when(kb == nk - 1)
    def _finalize():
        l_fin = jnp.maximum(_row(l_ref[...]), 1e-30)
        o_ref[0] = (acc_ref[...] / l_fin).astype(o_ref.dtype)
        lse_ref[0] = _rep(_row(m_ref[...]) + jnp.log(l_fin), block_q)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, block_q, block_k, causal, sm_scale, seq_len,
):
    """dq pass: grid (bh, qi, kb), kb innermost — one q block accumulates
    its gradient across the k blocks it attended to, recomputing p from
    q/k and the saved logsumexp (never the full score matrix)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _update():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        g_blk = g_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        valid = _mask(
            qi, kb, block_q=block_q, block_k=block_k, causal=causal,
            seq_len=seq_len,
        )
        s = jnp.where(valid, s, NEG_INF)
        # normalized probabilities via the saved logsumexp; masked entries
        # underflow to exactly 0 (NEG_INF - finite)
        p = jnp.exp(s - _row(lse_ref[0]))
        dp = jnp.dot(g_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - _row(delta_ref[0])) * sm_scale
        acc_ref[...] += jnp.dot(
            ds.astype(k_blk.dtype), k_blk, preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(kb * block_k <= qi * block_q + (block_q - 1))
        def _():
            _update()
    else:
        _update()

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, block_q, block_k, causal, sm_scale, seq_len,
):
    """dk/dv pass: grid (bh, kb, qi), qi innermost — one k/v block
    accumulates its gradient across the q blocks that attended to it."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _update():
        q = q_ref[0]  # unscaled: ds carries sm_scale
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        g_blk = g_ref[0]
        s = (
            jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * sm_scale
        )
        valid = _mask(
            qi, kb, block_q=block_q, block_k=block_k, causal=causal,
            seq_len=seq_len,
        )
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - _row(lse_ref[0]))
        dv_acc[...] += jnp.dot(
            p.T.astype(g_blk.dtype), g_blk, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(g_blk, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - _row(delta_ref[0])) * sm_scale
        dk_acc[...] += jnp.dot(
            ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32
        )

    if causal:
        # q blocks wholly above this k block see none of it
        @pl.when(qi * block_q + (block_q - 1) >= kb * block_k)
        def _():
            _update()
    else:
        _update()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _kv_fetch_idx(block_q: int, block_k: int, causal: bool):
    """BlockSpec index_map for K/V fetches on a (bh, q-block, k-block)
    grid.  Causal: blocks wholly above the diagonal are skipped by the
    kernels' ``pl.when`` guards, but the BlockSpec would still DMA them —
    clamp the fetch index to the diagonal block instead (already
    resident; the revisit is free), so masked steps move no new HBM
    bytes.  The clamp formula mirrors the kernels' skip condition
    ``kb*block_k <= qi*block_q + block_q - 1`` exactly; one definition
    serves forward and backward so the two can never drift."""
    if not causal:
        return lambda i, j, kb: (i, kb, 0)
    return lambda i, j, kb: (
        i, jnp.minimum(kb, (j * block_q + block_q - 1) // block_k), 0
    )


def _q_fetch_idx(block_q: int, block_k: int, causal: bool):
    """Mirror of :func:`_kv_fetch_idx` for q/g/lse/delta fetches on the
    dk/dv pass's (bh, k-block, q-block) grid: q blocks wholly above the
    current k block see none of it (skip condition
    ``qi*block_q + block_q - 1 >= kb*block_k``), so clamp their fetch to
    the first contributing q block."""
    if not causal:
        return lambda i, j, qi: (i, qi, 0)
    return lambda i, j, qi: (
        i, jnp.maximum(qi, (j * block_k) // block_q), 0
    )


def _effective_blocks(s: int, block_q: int, block_k: int) -> tuple[int, int]:
    """Clamp block sizes to the sequence rounded up to one lane tile, so
    large defaults never force a short sequence to pad to lcm(blocks).
    When the clamped pair's PADDED length — S rounded up to one lcm
    multiple — still overshoots that cap (mismatched sizes, e.g.
    (256, 384) for S=300 -> lcm 768; or (64, 96) for S=193, whose lcm
    192 fits the 256 cap but whose padding rounds to 384), collapse to
    one full-sequence tile pair — strictly less padded work than padding
    past the lane round-up — but only while cap stays at or below the
    default block_k scale (<= 1024, a 4 MB f32 score tile + K/V
    double-buffers, comfortably inside v5e VMEM): collapsing at larger S
    would materialize the very O(S, S) tile the kernel exists to avoid
    (cap=2048 alone is a 16.8 MB tile — over a v5e's VMEM).  Past that
    bound, mismatched custom blocks keep their lcm padding: more padded
    FLOPs, bounded VMEM.  The bound matters for the (512, 1024) defaults:
    S=640 clamps to (512, 640), lcm 2560 — collapsing to (640, 640) pads
    nothing, while the lcm would pad 4x.  Deterministic in (s, blocks):
    the backward recomputes the identical clamp, keeping its padded
    layout aligned with the forward's saved lse."""
    cap = -(-s // LANES) * LANES
    bq, bk = min(block_q, cap), min(block_k, cap)
    # Collapse when the PADDED length (S rounded up to one lcm multiple)
    # overshoots the lane round-up — not merely when the lcm itself does:
    # lcm(64, 96)=192 <= cap=256 at S=193, yet padding rounds 193 up to
    # 384, 1.5x the rows a (cap, cap) tile needs.  (Hypothesis-found,
    # tests/test_flash_attention.py::test_effective_blocks_properties.)
    pad = s + (-s) % math.lcm(bq, bk)
    if pad > cap and cap <= 1024:
        bq = bk = cap
    return bq, bk


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _prep(x, b, s, h, d, s_mult):
    """(B, S, H, D) -> (B*H, S_pad, D_pad): the kernel-facing layout."""
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    return _pad_to(_pad_to(x, 1, s_mult), 2, 128)


def _unprep(x, b, s, h, d):
    """(B*H, S_pad, D_pad) -> (B, S, H, D): drop padding, restore layout."""
    x = x[:, :s, :d].reshape(b, h, s, d)
    return jnp.transpose(x, (0, 2, 1, 3))


def _flash_fwd_impl(q, k, v, *, causal, block_q, block_k, interpret,
                    out_dtype=None):
    """Returns (out, lse) — lse in the padded lane-replicated
    (B*H, S_pad, LANES) layout the backward kernels consume directly.
    ``out_dtype`` overrides the output dtype (the ring chunk path asks
    for f32 so per-hop contributions are not rounded before its f32
    accumulation); default follows q."""
    b, s, h, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    block_q, block_k = _effective_blocks(s, block_q, block_k)
    # S padded to a common multiple of both block sizes so every K/V block
    # in the grid is fully in-bounds and every valid column is visited
    s_mult = math.lcm(block_q, block_k)
    qp = _prep(q, b, s, h, d, s_mult)
    kp = _prep(k, b, s, h, d, s_mult)
    vp = _prep(v, b, s, h, d, s_mult)
    bh, s_pad, d_pad = qp.shape

    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        seq_len=s,
    )
    kv_idx = _kv_fetch_idx(block_q, block_k, causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, s_pad // block_q, s_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), kv_idx),
            pl.BlockSpec((1, block_k, d_pad), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_pad, d_pad), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, s_pad, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d_pad), jnp.float32),   # running numerator
        ],
        # bh and q-block iterations are independent (state is carried only
        # across kb): declaring them parallel lets Mosaic overlap grid
        # steps instead of serializing on an assumed loop dependency —
        # the per-step overhead, not HBM, bounds this kernel at these
        # block counts (ROOFLINE.md)
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret if interpret is not None else _pick_interpret(),
    )(qp, kp, vp)
    return _unprep(out, b, s, h, d), lse


def _flash_bwd_impl(q, k, v, out, lse, g, *, causal, block_q, block_k, interpret,
                    out_dtype=None):
    """Blockwise dq/dk/dv from the saved lse (flash-attention-2 backward).
    ``out_dtype`` as in :func:`_flash_fwd_impl` (grad dtype override)."""
    b, s, h, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    block_q, block_k = _effective_blocks(s, block_q, block_k)
    s_mult = math.lcm(block_q, block_k)
    qp = _prep(q, b, s, h, d, s_mult)
    kp = _prep(k, b, s, h, d, s_mult)
    vp = _prep(v, b, s, h, d, s_mult)
    gp = _prep(g, b, s, h, d, s_mult)
    bh, s_pad, d_pad = qp.shape
    nq, nk = s_pad // block_q, s_pad // block_k

    # delta_i = dO_i . O_i (rowwise): O(S) like lse, computed densely in
    # XLA (a fused elementwise-reduce, no S x S term), then laid out
    # lane-replicated for the kernels.  Padded rows have g = 0 => delta 0.
    delta = jnp.einsum(
        "bshd,bshd->bsh", g.astype(jnp.float32), out.astype(jnp.float32)
    )
    delta = jnp.transpose(delta, (0, 2, 1)).reshape(bh, s)
    delta = jnp.broadcast_to(
        _pad_to(delta, 1, s_mult)[..., None], (bh, s_pad, LANES)
    )

    interp = interpret if interpret is not None else _pick_interpret()
    opts = dict(
        block_q=block_q, block_k=block_k, causal=causal, sm_scale=sm_scale,
        seq_len=s,
    )
    kv_idx_b = _kv_fetch_idx(block_q, block_k, causal)
    q_idx_b = _q_fetch_idx(block_q, block_k, causal)
    lse_spec_q = pl.BlockSpec((1, block_q, LANES), lambda i, j, kb: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **opts),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), kv_idx_b),
            pl.BlockSpec((1, block_k, d_pad), kv_idx_b),
            pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
            lse_spec_q,
            lse_spec_q,
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d_pad), out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(qp, kp, vp, gp, lse, delta)

    lse_spec_k = pl.BlockSpec((1, block_q, LANES), q_idx_b)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **opts),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), q_idx_b),
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_q, d_pad), q_idx_b),
            lse_spec_k,
            lse_spec_k,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, qi: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_pad, d_pad), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, s_pad, d_pad), out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        # state carried across qi only: bh and k-block dims are parallel
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(qp, kp, vp, gp, lse, delta)

    return (
        _unprep(dq, b, s, h, d),
        _unprep(dk, b, s, h, d),
        _unprep(dv, b, s, h, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_chunk_fwd(
    q, k, v, *, causal, block_q=512, block_k=1024, interpret=None
):
    """(out, lse_rows) for one (q-chunk, k-chunk) pair — the per-chunk op
    of the cross-chip ring composition (parallel/ringflash.py).

    ``lse_rows`` comes back in plain (B, H, S) row layout so the ring can
    merge partial results with the associative (out, lse) flash merge.
    Not differentiable on its own: the ring defines its OWN custom vjp
    (a second ring pass over :func:`flash_chunk_bwd`), which is why this
    returns the raw forward pieces instead of routing through ``_flash``.
    """
    b, s, h, d = q.shape
    out, lse = _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, out_dtype=jnp.float32,
    )
    return out, lse[:, :s, 0].reshape(b, h, s)


def flash_chunk_bwd(
    q, k, v, out, lse_rows, g, *, causal, block_q=512, block_k=1024,
    interpret=None,
):
    """(dq, dk, dv) contribution of one (q-chunk, k-chunk) pair given the
    GLOBAL logsumexp: the flash-attention-2 identity p = exp(s − lse)
    yields exactly-normalized probabilities per pair, so per-pair
    contributions sum to the true gradient — the property that lets a
    ring accumulate dk/dv as each block passes by.  ``out``/``g`` are the
    final merged output and its cotangent (delta is recomputed from them
    per call; O(S·d), no (S, S) term)."""
    b, s, h, d = q.shape
    bq, bk = _effective_blocks(s, block_q, block_k)
    s_mult = math.lcm(bq, bk)
    s_pad = s + ((-s) % s_mult)
    lse_flat = _pad_to(lse_rows.reshape(b * h, s), 1, s_mult)
    lse_full = jnp.broadcast_to(
        lse_flat[..., None], (b * h, s_pad, LANES)
    )
    return _flash_bwd_impl(
        q, k, v, out, lse_full, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret, out_dtype=jnp.float32,
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise attention over (B, S, H, D); differentiable end-to-end
    with O(block·d) on-chip memory in BOTH directions — the backward is
    blockwise too (saved-logsumexp recompute per tile), so training with
    long sequences never materializes an (S, S) intermediate.

    Default blocks are the measured v5e optimum (tools/kernel_bench.py
    on the real chip, b2 S4096 h8 bf16, KERNEL_BENCH_r05.jsonl): the
    kernels are per-grid-step-overhead-bound (ROOFLINE.md), so the
    fewest-steps pairs win: (512, 1024) ranks first both by interleaved
    repeated-median wall clocks and by xprof device time
    (TRACE_r05.jsonl: 2.87 ms/iter fwd+bwd at b2 S4096 d128 against a
    1.82 ms executed-FLOPs roofline, ~42% of v5e bf16 peak, 5.3x faster
    than the dense-XLA path on device; (128, 128) costs 4.7x more
    device time).  Standalone wall clocks additionally pay a
    session-varying per-dispatch tunnel constant — trust device traces
    (tools/trace_flash.py) and whole-model steps: at S=32k the 4x
    grid-step reduction compounds into 0.088 -> 0.205 MFU on the full
    train step (LONGCTX_r05.json, ~0.5% spread across three runs).
    Blocks are clamped to the sequence's lane-tile round-up so short
    sequences never pad to the large default.

    ``interpret=None`` auto-selects pallas interpret mode off-TPU.  The
    call signature matches the model zoo's ``attn_fn`` hook, so
    ``ShardedTrainer(..., flash_attn=True)`` drops it into any LM."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected (B, S, H, D), got {q.shape}")
    return _flash(q, k, v, causal, block_q, block_k, interpret)
