"""Flash attention forward kernel in pallas (TPU).

Blockwise causal attention that never materializes the (S, S) score
matrix — and never holds more than one K/V *block* in VMEM: the grid is
(batch*heads, q-blocks, k-blocks) with the K/V block index innermost, so
pallas streams (block_k, d) tiles HBM→VMEM while the online-softmax state
(running max, denominator, weighted numerator) is carried across k steps
in VMEM scratch.  Peak on-chip footprint is O(block_q * d + block_k * d),
independent of S — the property that makes long sequences fit.  This is
the single-chip sibling of the cross-chip ring in
:mod:`gpuschedule_tpu.parallel.ringattn`: same math, different memory
system (VMEM blocking vs ICI ppermute).

Backward runs as a dense XLA recompute (``jax.custom_vjp`` over the
shared oracle in :mod:`gpuschedule_tpu.ops.reference`).  Head dim and
sequence length are padded to lane/block multiples and unpadded on the
way out, so any model shape works.

Off-TPU the kernel runs in pallas interpret mode automatically, so CPU
tests exercise the very same code path the chip compiles.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gpuschedule_tpu.ops.reference import NEG_INF, dense_attention

def _reference(q, k, v, causal):
    """Positional-arg shim over the shared oracle (test-facing name)."""
    return dense_attention(q, k, v, causal=causal)


def _pick_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_q, block_k, causal, sm_scale, seq_len,
):
    """Grid (bh, qi, kb), kb innermost: scratch carries the online-softmax
    state across k blocks of one (bh, qi); the output block is written on
    the last k step."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _update():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = cols < seq_len  # mask sequence padding
        if causal:
            valid = jnp.logical_and(valid, rows >= cols)
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev, acc_prev = m_ref[...], l_ref[...], acc_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_prev * corr + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )

    if causal:
        # k blocks wholly above the diagonal contribute nothing
        @pl.when(kb * block_k <= qi * block_q + (block_q - 1))
        def _():
            _update()
    else:
        _update()

    @pl.when(kb == nk - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd_impl(q, k, v, *, causal, block_q, block_k, interpret):
    b, s, h, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    # S padded to a common multiple of both block sizes so every K/V block
    # in the grid is fully in-bounds and every valid column is visited
    s_mult = math.lcm(block_q, block_k)

    def prep(x):  # (B, S, H, D) -> (B*H, S_pad, D_pad)
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
        return _pad_to(_pad_to(x, 1, s_mult), 2, 128)

    qp, kp, vp = prep(q), prep(k), prep(v)
    bh, s_pad, d_pad = qp.shape

    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        seq_len=s,
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, s_pad // block_q, s_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),       # running max
            pltpu.VMEM((block_q, 1), jnp.float32),       # running denom
            pltpu.VMEM((block_q, d_pad), jnp.float32),   # running numerator
        ],
        interpret=interpret if interpret is not None else _pick_interpret(),
    )(qp, kp, vp)
    out = out[:, :s, :d].reshape(b, h, s, d)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    # Dense XLA recompute: correctness-first backward.  The forward kernel
    # is where the O(S^2) activation memory was; grads reuse autodiff.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: dense_attention(q, k, v, causal=causal), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise attention over (B, S, H, D); differentiable.

    ``interpret=None`` auto-selects pallas interpret mode off-TPU.  The
    call signature matches the model zoo's ``attn_fn`` hook, so
    ``ShardedTrainer(..., flash_attn=True)`` drops it into any LM."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected (B, S, H, D), got {q.shape}")
    return _flash(q, k, v, causal, block_q, block_k, interpret)
