"""``python -m gpuschedule_tpu ...`` — the same CLI as ``cli.main``."""

import sys

from gpuschedule_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
