"""Input pipelines: token datasets and device-prefetched batch iterators.

The host→device feed for :class:`~gpuschedule_tpu.parallel.ShardedTrainer`
(its ``make_batch`` covers benchmarks; real training reads data).  Design
follows the TPU input recipe: batches materialize on host (numpy,
memory-mapped), are placed with the trainer's batch sharding via
``jax.device_put``, and a small prefetch queue keeps N batches in flight
so host IO overlaps device steps.
"""

from gpuschedule_tpu.data.loader import (
    TokenFileDataset,
    prefetch_to_device,
    synthetic_lm_batches,
)

__all__ = ["TokenFileDataset", "synthetic_lm_batches", "prefetch_to_device"]
