"""Token datasets + device prefetch.

Three pieces, composable and small:

- :class:`TokenFileDataset` — a flat binary token file (any integer
  dtype), memory-mapped, cut into fixed (batch, seq) blocks.  Mmap keeps
  the host working set at one batch regardless of corpus size; epochs
  reshuffle block order deterministically per seed.
- :func:`synthetic_lm_batches` — the zero-IO stand-in with the same
  iterator contract (benchmarks, tests, profiling).
- :func:`prefetch_to_device` — wraps any host-batch iterator, placing
  each batch with ``jax.device_put`` (optionally with a ``Sharding``) and
  keeping ``size`` batches in flight: transfers overlap the device's
  current step, the standard TPU input-pipeline pattern.

The loader is sharding-agnostic on purpose: pass the trainer's
``batch_sharding`` and the same iterator feeds a 1-chip run or a dp/sp
mesh — placement, not the reader, changes.
"""

from __future__ import annotations

import collections
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

__all__ = ["TokenFileDataset", "synthetic_lm_batches", "prefetch_to_device"]


class TokenFileDataset:
    """Fixed-shape LM batches from a flat binary token file.

    ``path`` holds tokens as a 1-D array of ``dtype``; blocks of
    ``batch * seq_len`` consecutive tokens become one (batch, seq_len)
    int32 batch.  Block order shuffles per (epoch, seed); the tail that
    doesn't fill a block is dropped (static shapes — XLA compiles one
    program).
    """

    def __init__(
        self,
        path: str | Path,
        *,
        batch_size: int,
        seq_len: int,
        dtype: str = "uint16",
        seed: int = 0,
    ):
        self.path = Path(path)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        if self.batch_size < 1 or self.seq_len < 1:
            raise ValueError("batch_size and seq_len must be >= 1")
        self.seed = seed
        self._tokens = np.memmap(self.path, dtype=np.dtype(dtype), mode="r")
        self.block = self.batch_size * self.seq_len
        self.num_batches = len(self._tokens) // self.block
        if self.num_batches == 0:
            raise ValueError(
                f"{self.path} holds {len(self._tokens)} tokens; "
                f"one batch needs {self.block}"
            )

    def __len__(self) -> int:
        return self.num_batches

    def batches(
        self, *, epoch: int = 0, start: int = 0,
        host_shard: "tuple[int, int] | None" = None,
    ) -> Iterator[np.ndarray]:
        """Yield every batch once, order shuffled per (seed, epoch).

        ``start`` skips that many batches of the epoch in O(1) — resume
        jumps straight to its position instead of reading and discarding
        every already-consumed batch.

        ``host_shard=(index, count)`` is the multi-host split: host
        ``index`` of ``count`` yields only its every-``count``-th batch
        of the SAME (seed, epoch) permutation, so the hosts' streams
        partition the epoch exactly (disjoint, union = full epoch) with
        zero coordination — each host mmaps the same file and reads only
        its own blocks.  ``start`` stays in *global* stream positions so
        resume arithmetic is host-count-independent."""
        order = np.random.default_rng((self.seed, epoch)).permutation(
            self.num_batches
        )
        idx, count = _check_host_shard(host_shard)
        first = start + ((idx - start) % count)  # first host-owned pos >= start
        for pos in range(first, self.num_batches, count):
            off = int(order[pos]) * self.block  # byte-block offset
            chunk = np.asarray(self._tokens[off:off + self.block])
            yield chunk.astype(np.int32).reshape(self.batch_size, self.seq_len)

    @staticmethod
    def write(tokens, path: str | Path, *, dtype: str = "uint16") -> Path:
        """Write a token array as a dataset file (test/tooling helper).

        Refuses token ids outside the target dtype's range — np.astype
        would silently wrap them (vocab > 65536 under the uint16 default)
        and training would run on corrupted data."""
        arr = np.asarray(tokens)
        info = np.iinfo(np.dtype(dtype))
        if arr.size and (arr.min() < info.min or arr.max() > info.max):
            raise ValueError(
                f"token ids span [{arr.min()}, {arr.max()}], outside "
                f"{dtype}'s [{info.min}, {info.max}]; pick a wider dtype"
            )
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        arr.astype(np.dtype(dtype)).tofile(path)
        return path


def _check_host_shard(host_shard) -> "tuple[int, int]":
    if host_shard is None:
        return 0, 1
    idx, count = host_shard
    if count < 1 or not (0 <= idx < count):
        raise ValueError(f"host_shard must be (index, count), 0 <= index < count; got {host_shard}")
    return int(idx), int(count)


def synthetic_lm_batches(
    *,
    batch_size: int,
    seq_len: int,
    vocab: int,
    num_batches: int,
    seed: int = 0,
    start: int = 0,
    host_shard: "tuple[int, int] | None" = None,
) -> Iterator[np.ndarray]:
    """Deterministic random token batches with the dataset iterator
    contract — the zero-IO feed for benchmarks and profiling.

    Each batch is keyed by (seed, index), so ``start`` resumes the stream
    at any position in O(1): batch i is identical whether the stream was
    consumed from 0 or entered at i.  ``host_shard=(index, count)``
    splits the stream across hosts exactly like
    :meth:`TokenFileDataset.batches` (global positions, per-host
    every-``count``-th batch)."""
    idx, count = _check_host_shard(host_shard)
    first = start + ((idx - start) % count)  # first host-owned pos >= start
    for i in range(first, num_batches, count):
        yield np.random.default_rng((seed, i)).integers(
            0, vocab, size=(batch_size, seq_len), dtype=np.int32
        )


def prefetch_to_device(
    iterator,
    *,
    size: int = 2,
    sharding: Optional[object] = None,
):
    """Keep ``size`` device-placed batches in flight ahead of the consumer.

    ``jax.device_put`` is async: enqueueing the transfer returns
    immediately, so while the device runs step N the host is already
    copying batches N+1..N+size.  Pass the trainer's ``batch_sharding``
    to land shards directly on their mesh positions.
    """
    import jax

    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    queue: collections.deque = collections.deque()

    def put(batch):
        return jax.device_put(batch, sharding) if sharding is not None else (
            jax.device_put(batch)
        )

    for batch in iterator:
        queue.append(put(batch))
        if len(queue) == size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
