"""Ring flash attention: blockwise pallas kernels inside the ring.

The composition the two long-context cores point at (see the decision
surface in :mod:`gpuschedule_tpu.parallel.ringattn`): the sequence is
sharded over the mesh's ``sp`` axis and K/V blocks rotate by
``lax.ppermute`` exactly as in :func:`ring_attention` — but the per
chunk-pair product is the VMEM-blocked flash kernel
(:func:`gpuschedule_tpu.ops.flash_attention.flash_chunk_fwd`) instead of
a dense (S/P, S/P) einsum, so on-chip memory is O(block·d) at BOTH
levels: across chips (ring, O(S/P) activations) and within a chip
(pallas, block-sized tiles).  No (S/P, S/P) score matrix exists anywhere.

**Forward.**  Each chunk pair returns (out, lse); partial results merge
with the associative flash merge — softmax over the union of key sets:

    lse_new = logaddexp(lse_a, lse_b)
    out_new = out_a·e^(lse_a − lse_new) + out_b·e^(lse_b − lse_new)

Causality is decided per pair by ring position (``lax.cond``): the
diagonal pair runs the causal kernel, past pairs run unmasked, and
future pairs skip the kernel entirely — the branch is real on TPU, so
the causal half of the work is not just masked but *not executed*.

**Backward** is its own second ring pass (a custom vjp, NOT autodiff
through the forward loop — that would save every visiting K/V block and
re-materialize O(S) residuals per device).  Residuals are only the local
(q, k, v, out, lse): the flash-attention-2 identity p = exp(s − lse)
makes per-pair gradient contributions exact given the *global* lse, so
each device accumulates dq locally while dk/dv accumulators ride the
ring WITH their K/V block — after P rotations every block arrives home
carrying its full gradient.  Comm volume is 2× the forward's (k, v, dk,
dv per hop), the standard ring-attention backward cost.

Off-TPU the inner kernels run in pallas interpret mode (same code path),
so the 8-device CPU-mesh tests exercise the full composition.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gpuschedule_tpu.ops.flash_attention import (
    flash_attention,
    flash_chunk_bwd,
    flash_chunk_fwd,
)
from gpuschedule_tpu.ops.reference import NEG_INF
from gpuschedule_tpu.parallel.ringattn import resolve_ring_mesh


def _merge(out_run, lse_run, out_i, lse_i):
    """Fold one chunk pair's (out, lse) into the running (f32) pair.

    NEG_INF is a finite sentinel (-1e30, ops/reference.py), so logaddexp
    and the weights stay finite with no nan guard: a skipped pair merges
    zero output at vanishing weight, and a row no pair has touched yet
    merges two zeros (at ~half weight each — still exactly zero)."""
    lse_new = jnp.logaddexp(lse_run, lse_i)
    # (B, H, L) row weights onto (B, L, H, D) outputs
    wr = jnp.transpose(jnp.exp(lse_run - lse_new), (0, 2, 1))[..., None]
    wi = jnp.transpose(jnp.exp(lse_i - lse_new), (0, 2, 1))[..., None]
    return out_run * wr + out_i * wi, lse_new


def _make_local(sp_size, axis, causal, block_q, block_k, interpret):
    """The per-device body (inside shard_map) with its ring-pass vjp."""
    kw = dict(block_q=block_q, block_k=block_k, interpret=interpret)
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    def _forward(q, k, v):
        b, l, h, d = q.shape
        my = lax.axis_index(axis)
        out_run = jnp.zeros((b, l, h, d), jnp.float32)
        lse_run = jnp.full((b, h, l), NEG_INF, jnp.float32)
        k_blk, v_blk = k, v
        for step in range(sp_size):
            src = (my - step) % sp_size

            def diag(k_blk=k_blk, v_blk=v_blk):
                return flash_chunk_fwd(q, k_blk, v_blk, causal=True, **kw)

            def full(k_blk=k_blk, v_blk=v_blk):
                return flash_chunk_fwd(q, k_blk, v_blk, causal=False, **kw)

            def skip():
                # dtypes must match the kernel branches: chunk outputs
                # are f32 regardless of input dtype (out_dtype override)
                return (
                    jnp.zeros((b, l, h, d), jnp.float32),
                    jnp.full((b, h, l), NEG_INF, jnp.float32),
                )

            if causal:
                out_i, lse_i = lax.cond(
                    src == my,
                    diag,
                    lambda: lax.cond(src < my, full, skip),
                )
            else:
                out_i, lse_i = full()
            out_run, lse_run = _merge(out_run, lse_run, out_i, lse_i)
            if step + 1 < sp_size:
                k_blk = lax.ppermute(k_blk, axis, perm)
                v_blk = lax.ppermute(v_blk, axis, perm)
        return out_run.astype(q.dtype), lse_run

    @jax.custom_vjp
    def local(q, k, v):
        return _forward(q, k, v)[0]

    def fwd(q, k, v):
        out, lse = _forward(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, out, lse = res
        my = lax.axis_index(axis)
        dq = jnp.zeros(q.shape, jnp.float32)
        dk_acc = jnp.zeros(k.shape, jnp.float32)
        dv_acc = jnp.zeros(v.shape, jnp.float32)
        k_blk, v_blk = k, v
        for step in range(sp_size):
            src = (my - step) % sp_size

            def diag(k_blk=k_blk, v_blk=v_blk):
                return flash_chunk_bwd(
                    q, k_blk, v_blk, out, lse, g, causal=True, **kw
                )

            def full(k_blk=k_blk, v_blk=v_blk):
                return flash_chunk_bwd(
                    q, k_blk, v_blk, out, lse, g, causal=False, **kw
                )

            def skip():
                return (
                    jnp.zeros(q.shape, jnp.float32),
                    jnp.zeros(k.shape, jnp.float32),
                    jnp.zeros(v.shape, jnp.float32),
                )

            if causal:
                dq_c, dk_c, dv_c = lax.cond(
                    src == my,
                    diag,
                    lambda: lax.cond(src < my, full, skip),
                )
            else:
                dq_c, dk_c, dv_c = full()
            dq = dq + dq_c
            dk_acc = dk_acc + dk_c
            dv_acc = dv_acc + dv_c
            # the gradient accumulator rides the ring WITH its block and
            # needs all P hops to arrive home; K/V themselves are done
            # after the last compute (P-1 hops), like the forward
            if step + 1 < sp_size:
                k_blk = lax.ppermute(k_blk, axis, perm)
                v_blk = lax.ppermute(v_blk, axis, perm)
            dk_acc = lax.ppermute(dk_acc, axis, perm)
            dv_acc = lax.ppermute(dv_acc, axis, perm)
        return (
            dq.astype(q.dtype),
            dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype),
        )

    local.defvjp(fwd, bwd)
    return local


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal attention over (B, S, H, D) with S sharded on mesh axis
    ``axis`` and the flash kernel as the per-chunk op.  Same calling
    contract as :func:`gpuschedule_tpu.parallel.ringattn.ring_attention`
    (mesh handling shared via ``resolve_ring_mesh``).  ``sp == 1``
    degenerates to per-device :func:`flash_attention` — still blockwise,
    no ring, but still shard_mapped over dp/tp: a bare pallas call has no
    GSPMD partitioning rule, so dp>1 activations must be split *before*
    the kernel (same guard as the trainer's flash branch)."""
    shape, spec, head_axis = resolve_ring_mesh(mesh, axis)
    sp_size = shape[axis]
    if sp_size == 1:
        fa_spec = P("dp", None, head_axis, None)
        return jax.shard_map(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, block_q=block_q, block_k=block_k,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(fa_spec, fa_spec, fa_spec),
            out_specs=fa_spec,
            check_vma=False,
        )(q, k, v)
    fn = _make_local(sp_size, axis, causal, block_q, block_k, interpret)
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call emits ShapeDtypeStructs without vma info (same
        # reason as the trainer's flash shard_map)
        check_vma=False,
    )(q, k, v)
