"""Ring attention: causal self-attention over a sequence-sharded axis.

Long-context path (task brief: "ring attention or all-to-all
sequence/context parallelism for long sequences").  Activations are
sharded over the mesh's ``sp`` axis; no device ever materializes the full
(S, S) score matrix or the full K/V.  Each of the P devices holds an
S/P-length block of Q, K, V and runs P rounds:

1. attend its local Q block to the K/V block it currently holds, folding
   the result into an **online-softmax accumulator** (running max,
   denominator, weighted-value numerator — the flash-attention recurrence,
   so partial results combine exactly);
2. pass its K/V block to the next device with ``lax.ppermute`` — a
   neighbor exchange that rides one ICI hop per round, which is what makes
   the ring layout TPU-native: total bytes moved equal one all-gather of
   K/V, but with only nearest-neighbor traffic and O(S/P) peak memory.

Causality is enforced with global positions derived from
``lax.axis_index``, so block pairs wholly in the future contribute nothing
(their logits are masked to -inf before the accumulator update).

The inner function assumes it runs inside ``shard_map``;
:func:`ring_attention` wraps it over an explicit ``mesh=`` (what the
trainer passes) or, failing that, the ambient mesh set with
``jax.sharding.set_mesh``/``use_abstract_mesh``.  Note the legacy
``with mesh:`` context does NOT populate that ambient mesh in JAX 0.9 —
pass ``mesh=`` explicitly there.

**Choosing a long-context core** (the decision surface the trainer's
``ring_attn``/``flash_attn`` flags expose):

- ``flash_attn`` — one device holds the whole sequence; the pallas
  kernels (:mod:`gpuschedule_tpu.ops.flash_attention`) keep on-chip
  memory at O(block·d) in BOTH directions.  Right whenever S fits one
  chip's HBM as activations (S=32k trains on one v5e this way —
  ``bench.py --longctx``).
- ``ring_attn`` (this module) — S itself is sharded over sp chips; each
  round computes a dense (S/P, S/P) chunk-pair product.  Right when the
  sequence (or its activations) exceeds one chip and S/P is moderate;
  per-chunk memory is O((S/P)^2) scores.
- ``ring_attn + flash_attn`` — the composition
  (:mod:`gpuschedule_tpu.parallel.ringflash`): this ring's ppermute
  rotation with the pallas kernel as the per-chunk op and a second-ring
  pass backward.  O(block·d) on-chip at both levels; the config for
  sequences too big for one chip at large S/P.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gpuschedule_tpu.ops.reference import NEG_INF, dense_attention


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    sp_size: int,
    axis: str,
    causal: bool,
) -> jax.Array:
    """Per-device body (inside shard_map): q/k/v are (B, L, H, D) local
    blocks of the (B, S, H, D) sequence, L = S / sp_size."""
    b, l_q, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qf = q.astype(jnp.float32) * scale
    my_idx = lax.axis_index(axis)

    m = jnp.full((b, h, l_q), NEG_INF, jnp.float32)        # running max
    denom = jnp.zeros((b, h, l_q), jnp.float32)          # running sum exp
    num = jnp.zeros((b, h, l_q, d), jnp.float32)         # running sum exp*V

    k_blk, v_blk = k, v
    pos_q = my_idx * l_q + jnp.arange(l_q)
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    for step in range(sp_size):
        # after `step` rotations, this device holds the block that started
        # on device (my_idx - step) mod P
        src = (my_idx - step) % sp_size
        logits = jnp.einsum(
            "blhd,bmhd->bhlm", qf, k_blk.astype(jnp.float32)
        )
        if causal:
            pos_k = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
            mask = pos_q[:, None] >= pos_k[None, :]
            logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p_ij = jnp.exp(logits - m_new[..., None])
        denom = denom * corr + p_ij.sum(axis=-1)
        num = num * corr[..., None] + jnp.einsum(
            "bhlm,bmhd->bhld", p_ij, v_blk.astype(jnp.float32)
        )
        m = m_new
        if step + 1 < sp_size:
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)

    out = num / jnp.maximum(denom[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B, L, H, D)


def resolve_ring_mesh(mesh: Optional[Mesh], axis: str):
    """(mesh shape mapping, (B, S, H, D) ring spec, head_axis) — the
    mesh-resolution contract shared by the dense ring and the flash
    composition (:mod:`gpuschedule_tpu.parallel.ringflash`).  With
    ``mesh=None`` the ambient mesh from ``jax.sharding.set_mesh`` is used
    (the legacy ``with mesh:`` context does not set it — pass ``mesh=``
    there).  Heads stay sharded over tp when that axis exists (all math
    is per-head, so head-sharding composes with the ring for free)."""
    if mesh is None:
        shape = jax.sharding.get_abstract_mesh().shape  # empty dict if unset
        if axis not in shape:
            raise ValueError(
                f"no ambient mesh with axis {axis!r} (set_mesh not in "
                f"effect); pass mesh= explicitly"
            )
    else:
        shape = mesh.shape
    head_axis = "tp" if "tp" in shape else None
    return shape, P("dp", axis, head_axis, None), head_axis


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Causal attention over (B, S, H, D) with S sharded on mesh axis
    ``axis``; batch stays sharded on ``dp``.  Mesh handling per
    :func:`resolve_ring_mesh`."""
    shape, spec, _ = resolve_ring_mesh(mesh, axis)
    sp_size = shape[axis]
    if sp_size == 1:
        # degenerate ring: plain (still memory-efficient enough) attention
        return _plain_causal_attention(q, k, v, causal=causal)
    fn = partial(
        _ring_attention_local, sp_size=sp_size, axis=axis, causal=causal
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def _plain_causal_attention(q, k, v, *, causal: bool) -> jax.Array:
    """Reference implementation — the shared oracle from ops/reference.py
    (one ground truth for both the ring layer and the pallas kernel)."""
    return dense_attention(q, k, v, causal=causal)
