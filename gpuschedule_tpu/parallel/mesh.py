"""Device mesh construction.

One mesh, three named axes — ``dp`` (data), ``sp`` (sequence), ``tp``
(tensor) — covering the parallelism dimensions the framework schedules and
profiles.  ``make_mesh`` factors however many devices exist (real TPU
chips, or a virtual CPU mesh under ``--xla_force_host_platform_device_count``)
into that axis order, putting ``tp`` innermost so tensor-parallel
collectives ride the fastest ICI hops (the scaling-book layout recipe).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("dp", "sp", "tp")


def make_mesh(
    *,
    dp: Optional[int] = None,
    sp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(dp, sp, tp)`` mesh over ``devices`` (default: all).

    ``dp`` defaults to "whatever is left": n_devices // (sp * tp).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if sp < 1 or tp < 1:
        raise ValueError(f"axis sizes must be >= 1: sp={sp}, tp={tp}")
    if n % (sp * tp) != 0:
        raise ValueError(f"{n} devices not divisible by sp*tp={sp * tp}")
    inferred_dp = n // (sp * tp)
    if dp is None:
        dp = inferred_dp
    if dp * sp * tp != n:
        raise ValueError(f"dp*sp*tp={dp * sp * tp} != {n} devices")
    grid = np.array(devs).reshape(dp, sp, tp)
    return Mesh(grid, AXES)
