"""Device mesh construction.

One mesh, four named axes — ``pp`` (pipeline), ``dp`` (data), ``sp``
(sequence), ``tp`` (tensor) — covering the parallelism dimensions the
framework schedules and profiles.  ``make_mesh`` factors however many
devices exist (real TPU chips, or a virtual CPU mesh under
``--xla_force_host_platform_device_count``) into that axis order: ``tp``
innermost so tensor-parallel collectives ride the fastest ICI hops, and
``pp`` outermost because pipeline traffic is point-to-point once per
microbatch — the least bandwidth-hungry axis (the scaling-book layout
recipe).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("pp", "dp", "sp", "tp")


def make_mesh(
    *,
    dp: Optional[int] = None,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(pp, dp, sp, tp)`` mesh over ``devices`` (default: all).

    ``dp`` defaults to "whatever is left": n_devices // (pp * sp * tp).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if sp < 1 or tp < 1 or pp < 1:
        raise ValueError(f"axis sizes must be >= 1: pp={pp}, sp={sp}, tp={tp}")
    if n % (pp * sp * tp) != 0:
        raise ValueError(f"{n} devices not divisible by pp*sp*tp={pp * sp * tp}")
    inferred_dp = n // (pp * sp * tp)
    if dp is None:
        dp = inferred_dp
    if pp * dp * sp * tp != n:
        raise ValueError(f"pp*dp*sp*tp={pp * dp * sp * tp} != {n} devices")
    grid = np.array(devs).reshape(pp, dp, sp, tp)
    return Mesh(grid, AXES)
