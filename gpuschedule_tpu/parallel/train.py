"""Sharded train step: the framework's unit of measured work.

The profiler measures "one optimizer step of model M on a k-chip slice"
(SURVEY.md §3.5); this module builds that step the TPU-native way:

- **dp**: batch dim sharded; XLA turns the gradient sum into a psum over
  the ``dp`` axis (the NCCL-allreduce equivalent, compiled not called).
- **tp**: megatron-style column/row parameter splits via
  :func:`param_partition_spec`; XLA inserts the all-gathers/reduce-scatters.
- **sp**: sequence dim of activations sharded (long-context path); the
  attention all-to-all/all-gather falls out of the sharding propagation.

Everything is one ``jax.jit`` with NamedShardings — no per-collective
code, no process groups.  ``donate_argnums`` recycles param/opt buffers so
HBM holds one copy of the state.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from gpuschedule_tpu.models import build_model
from gpuschedule_tpu.models.config import CnnConfig
from gpuschedule_tpu.obs.tracer import get_tracer


def make_optimizer(
    learning_rate: float,
    *,
    warmup_steps: int = 0,
    decay_steps: Optional[int] = None,
    grad_clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """adamw with the standard training-stack trimmings, all opt-in:
    linear warmup over ``warmup_steps``, cosine decay to zero over
    ``decay_steps`` (counted after warmup), and global-norm gradient
    clipping at ``grad_clip``.  Defaults reproduce plain
    ``optax.adamw(learning_rate)`` exactly — the goldens and every
    existing trainer call are byte-for-byte unchanged."""
    if decay_steps:
        sched = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=warmup_steps + decay_steps,
        )
    elif warmup_steps:
        sched = optax.warmup_constant_schedule(
            init_value=0.0, peak_value=learning_rate,
            warmup_steps=warmup_steps,
        )
    else:
        sched = learning_rate
    tx = optax.adamw(sched)
    if grad_clip is not None:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
    return tx


def param_partition_spec(path: Tuple, value: Any) -> P:
    """Megatron-style tp sharding rule for a transformer param.

    ``path`` is a flax param path (tuple of DictKey names).  Column-parallel
    layers (qkv projections, MLP up-projection, lm head) split their output
    features over ``tp``; row-parallel layers (attention out, MLP down)
    split their input features, so the pair needs exactly one collective.
    Vocab embedding splits over vocab.  Everything else is replicated.
    """
    names = [getattr(k, "key", str(k)) for k in path]
    leaf_shape = getattr(value, "shape", ())
    ndim = len(leaf_shape)

    def spec_for(axis_idx: int) -> P:
        parts = [None] * ndim
        parts[axis_idx] = "tp"
        return P(*parts)

    if "moe" in names:
        # expert parallelism over the tp axis (ep-over-tp): the leading
        # expert dim of every expert weight/bias shards; the router stays
        # replicated so each device routes its own tokens
        if any(n in names for n in ("w_up", "w_down", "b_up", "b_down")):
            return spec_for(0)
        return P()
    if "embed" in names and "embedding" in names:
        return spec_for(0)  # (vocab, d): shard vocab
    if "kernel" in names:
        if any(n in names for n in ("query", "key", "value")):
            return spec_for(1)  # (d, heads, head_dim): shard heads (column)
        if "out" in names and "attn" in names:
            return spec_for(0)  # (heads, head_dim, d): shard heads (row)
        if "up" in names:
            return spec_for(ndim - 1)  # (d, ff): column
        if "down" in names:
            return spec_for(0)  # (ff, d): row
        if "lm_head" in names:
            return spec_for(ndim - 1)  # (d, vocab): column
    if "bias" in names and "up" in names:
        return spec_for(0)  # (ff,): follows the column split
    return P()  # LN scales, pos embed, remaining biases: replicated


class ShardedTrainer:
    """Owns a model + mesh + optimizer and exposes one jitted step.

    This is what the profiler times and what ``__graft_entry__`` dry-runs:
    construct with a mesh of any (dp, sp, tp) factorization, call
    :meth:`init` once, then :meth:`step` per iteration.
    """

    def __init__(
        self,
        model_name: str,
        mesh: Mesh,
        *,
        batch_size: int = 8,
        seq_len: int = 128,
        learning_rate: float = 1e-3,
        seq_shard: bool = False,
        ring_attn: bool = False,
        flash_attn: bool = False,
        moe_aux_weight: float = 1e-2,
        warmup_steps: int = 0,
        decay_steps: Optional[int] = None,
        grad_clip: Optional[float] = None,
    ):
        # weight of the sown Switch load-balancing loss (MoE configs only;
        # a no-op for dense models, whose sow collection is empty)
        self.moe_aux_weight = moe_aux_weight
        attn_fn = None
        if ring_attn and flash_attn:
            # Composition: sequence-parallel ring ACROSS chips with the
            # blockwise pallas kernel WITHIN each chip — O(block*d) on-chip
            # at both levels (parallel/ringflash.py).  The long-context
            # config for sequences too big for one chip.
            if not seq_shard:
                raise ValueError("ring_attn requires seq_shard=True")
            from gpuschedule_tpu.parallel.ringflash import ring_flash_attention

            attn_fn = partial(ring_flash_attention, mesh=mesh, causal=True)
        elif ring_attn:
            # Long-context core: sequence-sharded ring attention over the
            # sp axis (parallel/ringattn.py) instead of dense attention.
            if not seq_shard:
                raise ValueError("ring_attn requires seq_shard=True")
            from gpuschedule_tpu.parallel.ringattn import ring_attention

            attn_fn = partial(ring_attention, mesh=mesh, causal=True)
        elif flash_attn:
            # Single-device blockwise core (ops/flash_attention.py): pallas
            # runs per device, so shard_map over the batch/head axes; the
            # sequence stays whole on each device (use ring_attn to shard it).
            # Memory contract: O(block*d) on-chip in BOTH directions — the
            # backward is blockwise too (saved-logsumexp recompute), so
            # training long sequences never materializes (S, S) anywhere.
            if seq_shard:
                raise ValueError("flash_attn keeps S per-device; use ring_attn "
                                 "for sequence sharding")
            from gpuschedule_tpu.ops import flash_attention

            fa_spec = P("dp", None, "tp" if mesh.shape["tp"] > 1 else None, None)

            def attn_fn(q, k, v):
                return jax.shard_map(
                    lambda q, k, v: flash_attention(q, k, v, causal=True),
                    mesh=mesh,
                    in_specs=(fa_spec, fa_spec, fa_spec),
                    out_specs=fa_spec,
                    # pallas_call emits ShapeDtypeStruct without vma info;
                    # the kernel is elementwise-independent per device, so
                    # the varying-mesh-axes check adds nothing here
                    check_vma=False,
                )(q, k, v)
        self.model, self.cfg = build_model(model_name, attn_fn=attn_fn)
        self.is_image = isinstance(self.cfg, CnnConfig)
        self.mesh = mesh
        if not self.is_image and seq_len > self.cfg.max_seq:
            raise ValueError(f"seq_len {seq_len} > model max_seq {self.cfg.max_seq}")
        dp = mesh.shape["dp"]
        sp = mesh.shape["sp"]
        if batch_size % dp != 0:
            raise ValueError(f"batch {batch_size} not divisible by dp={dp}")
        if seq_shard and self.is_image:
            raise ValueError("seq_shard applies to LM sequences, not images")
        if seq_shard and seq_len % sp != 0:
            raise ValueError(f"seq {seq_len} not divisible by sp={sp}")
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.tx = make_optimizer(
            learning_rate, warmup_steps=warmup_steps,
            decay_steps=decay_steps, grad_clip=grad_clip,
        )
        if self.is_image:
            # (images bhwc, labels b): batch dim sharded over dp
            self.batch_sharding = (
                NamedSharding(mesh, P("dp", None, None, None)),
                NamedSharding(mesh, P("dp")),
            )
        else:
            self.batch_sharding = NamedSharding(
                mesh, P("dp", "sp" if seq_shard and sp > 1 else None)
            )

        def constrain_params(params):
            return jax.tree_util.tree_map_with_path(
                lambda path, v: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, param_partition_spec(path, v))
                ),
                params,
            )

        self._constrain = constrain_params

        def example_input():
            if self.is_image:
                s = self.cfg.image_size
                return jnp.zeros((batch_size, s, s, 3), dtype=jnp.float32)
            return jnp.zeros((batch_size, seq_len), dtype=jnp.int32)

        def init_fn(rng):
            variables = self.model.init(rng, example_input())
            # keep ONLY the trainable collection: MoE layers sow a
            # "moe_losses" collection at trace time, which must not leak
            # into the optimizer state
            params = {"params": variables["params"]}
            params = constrain_params(params)
            # opt state leaves are elementwise views of params; sharding
            # propagates from the constraint above
            opt_state = self.tx.init(params)
            return params, opt_state

        self._init = jax.jit(init_fn)

        def loss_fn(params, batch):
            if self.is_image:
                images, labels = batch
                logits = self.model.apply(params, images)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()
            # mutable: collect the sown MoE load-balancing losses (empty
            # dict for dense models — no cost, one code path)
            logits, mods = self.model.apply(
                params, batch, mutable=["moe_losses"]
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1, :], batch[:, 1:]
            ).mean()
            aux_terms = jax.tree_util.tree_leaves(mods.get("moe_losses", {}))
            if aux_terms:
                ce = ce + self.moe_aux_weight * sum(
                    jnp.asarray(a, jnp.float32).mean() for a in aux_terms
                )
            return ce

        def step_fn(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = constrain_params(params)
            return params, opt_state, loss

        # donate state buffers: one live copy of params/opt in HBM
        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

    # ------------------------------------------------------------------ #

    def init(self, seed: int = 0):
        """Initialize (params, opt_state), sharded per the partition rules."""
        with self.mesh:
            return self._init(jax.random.PRNGKey(seed))

    def make_batch(self, seed: int = 0):
        """A device-placed random batch with the dp(/sp) sharding: a token
        array for LMs, an (images, labels) pair for the CNN family."""
        key = jax.random.PRNGKey(seed)
        if self.is_image:
            s = self.cfg.image_size
            k1, k2 = jax.random.split(key)
            images = jax.random.normal(
                k1, (self.batch_size, s, s, 3), dtype=jnp.float32
            )
            labels = jax.random.randint(
                k2, (self.batch_size,), 0, self.cfg.num_classes, dtype=jnp.int32
            )
            return jax.device_put((images, labels), self.batch_sharding)
        tokens = jax.random.randint(
            key,
            (self.batch_size, self.seq_len),
            0,
            self.cfg.vocab,
            dtype=jnp.int32,
        )
        return jax.device_put(tokens, self.batch_sharding)

    def step(self, state, tokens):
        """One optimizer step; returns (new_state, loss).

        With the obs tracer enabled, every step is recorded as a span with
        step-time and tokens/s.  The span is fenced by a host readback of the
        loss (the only fence this image's transport honors — see
        profiler/harness.py), so tracing serializes dispatch with execution:
        honest per-step walls, at the cost of losing async overlap while the
        tracer is on.  Tracing off (the default) is the bare jitted dispatch.
        """
        params, opt_state = state
        tracer = get_tracer()
        if not tracer.enabled:
            with self.mesh:
                params, opt_state, loss = self._step(params, opt_state, tokens)
            return (params, opt_state), loss
        t0 = time.perf_counter()
        with self.mesh:
            params, opt_state, loss = self._step(params, opt_state, tokens)
        loss_val = float(loss)  # fence: the readback makes wall time real
        dt = time.perf_counter() - t0
        n_tokens = self.batch_size * (1 if self.is_image else self.seq_len)
        tracer.record(
            "train.step",
            wall_start=t0,
            wall_dur=dt,
            cat="train",
            step_time_s=round(dt, 6),
            tokens=n_tokens,
            tokens_per_s=round(n_tokens / dt, 1) if dt > 0 else None,
            loss=loss_val,
        )
        return (params, opt_state), loss

    def step_fn_and_args(self, seed: int = 0):
        """(jitted_fn, example_args) — the __graft_entry__ contract shape."""
        state = self.init(seed)
        tokens = self.make_batch(seed)
        return self._step, (state[0], state[1], tokens)
