"""Real checkpoint/restore for sharded train state (orbax-backed).

The scheduler layer MODELS checkpoint/restore cost (``sim/overhead.py``:
suspend, migrate, and grow-shrink charge seconds derived from model and
slice size — SURVEY.md §5 "Checkpoint / resume").  This module is the
mechanism those seconds stand for: save a :class:`ShardedTrainer`'s
(params, opt_state) to disk and restore it — onto the SAME mesh, or onto
a DIFFERENT one.

Cross-mesh restore is the TPU-native piece.  The reference's elastic
moves serialize through a filesystem checkpoint because NCCL process
groups cannot re-shape in place; here a resize/migration is just
``jax.device_put`` onto the new mesh's ``NamedSharding``s — XLA moves the
bytes (over ICI when live, from the checkpoint when cold), and the same
partition-spec rules that shard a fresh init re-shard the restored state.
So Gandiva grow-shrink and Optimus resize map onto: checkpoint (or keep
live), rebuild the trainer on the new slice, ``restore``/``reshard``.

Orbax handles the on-disk format (async-capable, per-shard files); the
sharding metadata comes from the TARGET trainer, not the checkpoint, so a
state saved from a dp=4 mesh restores cleanly onto dp=2·tp=2.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Tuple

import jax

__all__ = ["save_state", "restore_state", "reshard_state"]


def _target_shardings(trainer, state) -> Tuple[Any, Any]:
    """(params, opt_state) NamedSharding pytrees for ``trainer``'s mesh,
    derived from the same partition-spec rules init uses (single source
    of sharding truth: parallel/train.py param_partition_spec)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from gpuschedule_tpu.parallel.train import param_partition_spec

    params, opt_state = state
    param_sh = jax.tree_util.tree_map_with_path(
        lambda path, v: NamedSharding(
            trainer.mesh, param_partition_spec(path, v)
        ),
        params,
    )

    # opt-state leaves mirror param leaves (adam moments) or are scalars
    # (step counts): shard by shape match against the param rule, else
    # replicate.  tree_map_with_path over the opt_state gives paths whose
    # param-name suffix matches the param tree's, so reuse the rule.
    def opt_spec(path, v):
        if getattr(v, "ndim", 0) == 0:
            return NamedSharding(trainer.mesh, P())
        return NamedSharding(trainer.mesh, param_partition_spec(path, v))

    opt_sh = jax.tree_util.tree_map_with_path(opt_spec, opt_state)
    return param_sh, opt_sh


def save_state(state, path: str | Path, *, overwrite: bool = True) -> str:
    """Write (params, opt_state) to ``path`` (orbax PyTree checkpoint).

    Works for any mesh/sharding: orbax records per-leaf shape/dtype and
    gathers shards as needed.  ``overwrite=True`` (default) replaces an
    existing checkpoint at the path — the scheduler's suspend/migrate
    cycle saves the same job repeatedly.  Returns the checkpoint path.
    """
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=overwrite)
    return str(path)


def restore_state(trainer, path: str | Path):
    """Load a checkpoint onto ``trainer``'s mesh with its shardings.

    The checkpoint may have been saved from a different mesh shape
    (elastic resize, migration across slices): restore targets are built
    from the TARGET trainer's partition rules, so each device reads
    exactly its shard of the new layout.
    """
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    # abstract target: shapes/dtypes from a cost-free eval of init —
    # also the tree-structure template (orbax flattens tuples to lists
    # on disk; the item template restores the original containers)
    abstract = jax.eval_shape(lambda: trainer.init(seed=0))
    shardings = _target_shardings(trainer, abstract)

    def to_restore_arg(leaf, sharding):
        return ocp.ArrayRestoreArgs(
            sharding=sharding, global_shape=leaf.shape, dtype=leaf.dtype
        )

    restore_args = jax.tree.map(to_restore_arg, abstract, shardings)
    with ocp.PyTreeCheckpointer() as ckptr:
        try:
            return ckptr.restore(
                path,
                args=ocp.args.PyTreeRestore(
                    item=abstract, restore_args=restore_args
                ),
            )
        except ValueError as e:
            # orbax's structure-mismatch error never says WHY the trees
            # differ; name the likely causes instead of re-raising bare
            raise ValueError(
                f"checkpoint at {path} does not match the target trainer's "
                "state structure. Likely causes: a different model config, "
                "or different optimizer hyperparameters "
                "(warmup_steps/decay_steps/grad_clip change the opt_state "
                "pytree). A different MESH shape alone is fine — that "
                f"resharding is supported. Original error: {e}"
            ) from e


def reshard_state(trainer, state):
    """Live re-shard: place ``state`` onto ``trainer``'s mesh/shardings.

    The in-memory half of an elastic move — no filesystem round trip;
    XLA transfers each shard to its new home (ICI when source and target
    devices overlap a live slice).  ``state`` may come from a trainer
    with a different mesh factorization.
    """
    param_sh, opt_sh = _target_shardings(trainer, state)
    params, opt_state = state
    return (
        jax.device_put(params, param_sh),
        jax.device_put(opt_state, opt_sh),
    )
