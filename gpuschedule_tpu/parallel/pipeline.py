"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

The TPU-native formulation: the pipeline IS a collective program.  Each
pp rank holds one stage's parameters (stage-stacked pytrees sharded on
their leading dim); microbatches flow stage-to-stage via
``lax.ppermute`` inside one ``shard_map``, and the whole schedule —
fill, steady state, drain: ``M + S - 1`` ticks for M microbatches over S
stages — is a single ``lax.scan`` that ``jax.grad`` differentiates
through directly, ppermute's transpose being the reverse permute.  No
per-stage processes, no send/recv framework, no hand-written backward
schedule: the 1F1B-ish interleaving falls out of autodiff's reverse
sweep.  This is the reference's pipeline-parallel analogue done the XLA
way (same design recipe as the ring in :mod:`.ringattn`; scaling-book
"pipelining" chapter pattern).

Off the critical path before the wave arrives (and after it drains) a
stage computes on zeros; those outputs are never read, and the cost is
the standard (S-1)/(M+S-1) bubble.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

try:  # optax ships with the [profiler] extra, like the rest of parallel/
    import optax
except ImportError:  # pragma: no cover - pipeline needs the extra anyway
    optax = None

__all__ = ["pipeline_apply", "stack_stage_params", "PipelinedLM"]


def stack_stage_params(params_list):
    """Stack per-stage parameter pytrees on a new leading (stage) dim —
    the layout ``pipeline_apply`` shards over pp."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params,
    microbatches: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    with_aux: bool = False,
    schedule: str = "gpipe",
) -> "jax.Array | tuple[jax.Array, jax.Array]":
    """Run ``stage_fn`` as a ``pp``-deep pipeline over microbatches.

    ``stage_fn(stage_params, x) -> y`` (or ``-> (y, aux_scalar)`` when
    ``with_aux=True`` — the annotation's tuple case) must map activations to
    same-shaped activations (a transformer block); ``stacked_params``
    leaves carry a leading stage dim equal to the mesh's ``pp`` extent;
    ``microbatches`` is ``(M, mb, ...)``.  Returns the last stage's
    outputs, ``(M, mb, ...)``, replicated across pp (a psum over the
    stage mask).  Differentiable end-to-end.

    ``with_aux=True`` changes the stage contract to
    ``stage_fn(params, x) -> (y, aux_scalar)`` (e.g. MoE load-balancing
    losses sown inside the stage) and returns ``(outputs, aux)`` where
    ``aux`` is the per-microbatch mean of the valid contributions,
    summed across stages and averaged over dp columns.  Bubble ticks —
    where a stage chews zeros that belong to no microbatch — are masked
    out of the accumulation, not just discarded with their activations.

    ``schedule`` picks the activation-memory strategy (round-4 verdict:
    the GPipe tradeoff — live activations ~ ticks x microbatch — was
    documented but unmitigated):

    - ``"gpipe"`` (default): autodiff stores every stage's INTERNAL
      activations (attention scores, MLP hidden) for all M+S-1 ticks —
      fastest backward, O(M) x per-stage-internals memory.
    - ``"remat"``: each tick's stage computation is ``jax.checkpoint``-ed,
      so the backward sweep recomputes stage internals from the tick's
      boundary input; only the O(mb)-sized boundary activations survive
      per tick.  Live internals drop from O(M x block-internals) to ONE
      microbatch's worth at a time (recompute-per-microbatch — the
      bubble schedule is unchanged, losses are numerically identical).
    """
    if schedule not in ("gpipe", "remat"):
        raise ValueError(f"schedule must be 'gpipe' or 'remat', got {schedule!r}")
    if schedule == "remat":
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = mesh.shape[axis]
    # the aux reduction below averages over "dp" only; an sp/tp axis of
    # extent > 1 would leave the P() out_spec's replication claim silently
    # wrong on those axes (check_vma=False skips the proof), so reject
    # meshes this formulation does not actually support
    extra = {
        name: size
        for name, size in mesh.shape.items()
        if name not in (axis, "dp") and size > 1
    }
    if extra:
        raise ValueError(
            f"pipeline_apply supports ({axis}, dp) meshes only; "
            f"got extra axes {extra}"
        )
    if microbatches.ndim < 2:
        raise ValueError(
            f"microbatches must be (M, microbatch, ...), got {microbatches.shape}"
        )
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked params carry {lead} stages but mesh {axis}={n_stages}"
        )
    dp = mesh.shape.get("dp", 1)
    if microbatches.shape[1] % dp != 0:
        raise ValueError(
            f"microbatch size {microbatches.shape[1]} not divisible by dp={dp}"
        )
    m = microbatches.shape[0]
    ticks = m + n_stages - 1

    def per_device(params_stacked, xs):
        # in_spec P(axis) leaves a unit stage dim; strip it
        params = jax.tree.map(lambda a: a[0], params_stacked)
        stage = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]  # stage i -> i+1

        def tick(carry, t):
            send_buf, out, aux_total = carry
            # what stage-1 produced last tick arrives here; ranks with no
            # source (stage 0) receive zeros, which they never read
            recv = jax.lax.ppermute(send_buf, axis, perm)
            mb = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), keepdims=False
            )
            x = jnp.where(stage == 0, mb, recv)
            if with_aux:
                y, aux = stage_fn(params, x)
                # this stage holds microbatch t-stage this tick; bubble
                # ticks sow garbage that must not reach the aux sum
                live = t - stage
                valid = jnp.logical_and(live >= 0, live < m)
                aux_total = aux_total + jnp.where(
                    valid, jnp.asarray(aux, jnp.float32), 0.0
                )
            else:
                y = stage_fn(params, x)
            # the last stage finished microbatch t-(S-1) this tick
            done = t - (n_stages - 1)
            write = jnp.logical_and(done >= 0, stage == n_stages - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, jax.lax.dynamic_index_in_dim(
                    out, jnp.clip(done, 0, m - 1), keepdims=False
                )), jnp.clip(done, 0, m - 1), axis=0,
            )
            return (y, upd, aux_total), None

        init = (jnp.zeros_like(xs[0]), jnp.zeros_like(xs), jnp.float32(0))
        (_, out, aux_total), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # replicate the last stage's result across pp so the caller sees
        # one coherent array
        mask = (stage == n_stages - 1).astype(out.dtype)
        result = jax.lax.psum(out * mask, axis)
        if not with_aux:
            return result
        # sum the per-stage totals across pp, average over dp columns and
        # microbatches -> comparable to one full-batch sequential apply
        aux = jax.lax.psum(aux_total, axis)
        if dp > 1:
            aux = jax.lax.psum(aux, "dp") / dp
        return result, aux / m

    spec_params = jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params
    )
    # the microbatch dim shards over dp (each dp column pipelines its own
    # batch shard — pp and dp compose instead of dp replicating the work);
    # params replicate over dp automatically (spec names only `axis`)
    data_spec = P(None, "dp")
    return jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec_params, data_spec),
        # the psum over the stage mask (and, for aux, over pp/dp) makes
        # each output invariant where its spec is replicated
        out_specs=(data_spec, P()) if with_aux else data_spec,
        check_vma=False,
    )(stacked_params, microbatches)


class PipelinedLM:
    """A trainable LM with its block stack pipelined over the pp axis.

    The staged form of :class:`~gpuschedule_tpu.parallel.ShardedTrainer`'s
    model: embedding and head run at the boundaries (replicated — they
    are a small fraction of the FLOPs), and the ``n_layers`` transformer
    blocks split into ``pp`` equal stages driven by
    :func:`pipeline_apply`.  One ``jax.jit`` holds the whole train step —
    fwd pipeline, loss, the autodiff backward pipeline, and the adamw
    update — so the reverse-sweep schedule is compiled, not orchestrated.

    Correctness-first reference implementation: microbatch count M sets
    the bubble fraction (S-1)/(M+S-1); the per-tick activations the
    backward needs are stored by the scan (memory ~ ticks x microbatch),
    which is the GPipe tradeoff.
    """

    def __init__(
        self,
        model_name: str,
        mesh: Mesh,
        *,
        batch_size: int,
        seq_len: int,
        num_microbatches: int = 4,
        learning_rate: float = 1e-3,
        flash_attn: bool = False,
        moe_aux_weight: float = 1e-2,
        warmup_steps: int = 0,
        decay_steps: "int | None" = None,
        grad_clip: "float | None" = None,
        schedule: str = "gpipe",
    ):
        import flax.linen as nn

        from gpuschedule_tpu.models import MODEL_CONFIGS
        from gpuschedule_tpu.models.transformer import Block, Embedder, LMHead

        cfg = MODEL_CONFIGS[model_name]
        pp = mesh.shape["pp"]
        if pp < 2:
            raise ValueError(f"PipelinedLM needs a pp>=2 mesh, got pp={pp}")
        if cfg.n_layers % pp:
            raise ValueError(
                f"{model_name} has {cfg.n_layers} layers, not divisible by pp={pp}"
            )
        if batch_size % num_microbatches:
            raise ValueError(
                f"batch {batch_size} not divisible by {num_microbatches} microbatches"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.num_microbatches = num_microbatches
        self.layers_per_stage = cfg.n_layers // pp
        self.schedule = schedule
        # trainer-surface parity with ShardedTrainer so the train CLI and
        # the profiling harness can drive either interchangeably
        self.is_image = False
        from jax.sharding import NamedSharding

        self.batch_sharding = NamedSharding(mesh, P("dp", None))
        # honor the config's remat flag exactly like TransformerLM does:
        # long-sequence configs trade FLOPs for HBM inside each stage
        attn_fn = None
        if flash_attn:
            # the stage runs inside pipeline_apply's shard_map, so the
            # per-device pallas kernel needs no extra wrapping (the same
            # reason the trainer's flash branch shard_maps it itself)
            from gpuschedule_tpu.ops import flash_attention

            def attn_fn(q, k, v):
                return flash_attention(q, k, v, causal=True)

        self._block = (nn.remat(Block) if cfg.remat else Block)(cfg, attn_fn)
        self._embed = Embedder(cfg)
        self._head = LMHead(cfg)
        from gpuschedule_tpu.parallel.train import make_optimizer

        self.tx = make_optimizer(
            learning_rate, warmup_steps=warmup_steps,
            decay_steps=decay_steps, grad_clip=grad_clip,
        )
        self.moe_aux_weight = moe_aux_weight

        def stage_fn(stage_params, x):
            # mutable: collect the sown MoE load-balancing losses (the
            # collection is empty for dense blocks -> aux stays 0); the
            # pipeline masks bubble-tick contributions (pipeline_apply
            # with_aux docstring)
            aux = jnp.float32(0)
            for i in range(self.layers_per_stage):  # static unroll
                x, mods = self._block.apply(
                    stage_params[f"layer{i}"], x, mutable=["moe_losses"]
                )
                for t in jax.tree_util.tree_leaves(mods.get("moe_losses", {})):
                    aux = aux + jnp.asarray(t, jnp.float32).mean()
            return x, aux

        def loss_fn(params, tokens):
            b, s = tokens.shape
            m = self.num_microbatches
            x = self._embed.apply(params["embed"], tokens)
            xs = x.reshape(m, b // m, s, cfg.d_model)
            ys, aux = pipeline_apply(
                stage_fn, params["stages"], xs, mesh=mesh, with_aux=True,
                schedule=schedule,
            )
            logits = self._head.apply(params["head"], ys.reshape(b, s, -1))
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1, :], tokens[:, 1:]
            ).mean()
            return ce + self.moe_aux_weight * aux

        def step_fn(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._loss_fn = loss_fn
        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

    # ------------------------------------------------------------------ #

    def init(self, seed: int = 0):
        """(params, opt_state): embed/head boundaries + pp-stacked stages."""
        cfg = self.cfg
        pp = self.mesh.shape["pp"]
        keys = jax.random.split(jax.random.PRNGKey(seed), cfg.n_layers + 2)
        tokens = jnp.zeros((2, min(8, self.seq_len)), dtype=jnp.int32)
        e_params = self._embed.init(keys[0], tokens)
        x = self._embed.apply(e_params, tokens)
        h_params = self._head.init(keys[1], x)
        per_stage = []
        k = 0
        for _ in range(pp):
            stage = {}
            for i in range(self.layers_per_stage):
                # keep ONLY the trainable collection: MoE blocks sow
                # their aux loss during init too, and a sown scalar in
                # the stage pytree would leak into the optimizer state
                variables = self._block.init(keys[2 + k], x)
                stage[f"layer{i}"] = {"params": variables["params"]}
                k += 1
            per_stage.append(stage)
        params = {
            "embed": e_params,
            "head": h_params,
            "stages": stack_stage_params(per_stage),
        }
        return params, self.tx.init(params)

    def make_batch(self, seed: int = 0):
        key = jax.random.PRNGKey(seed)
        return jax.random.randint(
            key, (self.batch_size, self.seq_len), 0, self.cfg.vocab,
            dtype=jnp.int32,
        )

    def step(self, state, tokens):
        """One pipelined optimizer step; returns (new_state, loss)."""
        params, opt_state = state
        with self.mesh:
            params, opt_state, loss = self._step(params, opt_state, tokens)
        return (params, opt_state), loss

    def reference_loss(self, params, tokens):
        """The same math with the blocks applied sequentially (no
        pipeline) — the parity oracle for tests.  Exact for dense blocks
        at any microbatch count; for MoE the pipelined aux is the mean of
        per-microbatch, per-dp-column values of a statistic nonlinear in
        the routing probabilities, so parity is exact only at
        num_microbatches=1 AND dp=1, statistical beyond either."""
        cfg = self.cfg
        pp = self.mesh.shape["pp"]
        x = self._embed.apply(params["embed"], tokens)
        aux = jnp.float32(0)
        for s in range(pp):
            stage = jax.tree.map(lambda a: a[s], params["stages"])
            for i in range(self.layers_per_stage):
                x, mods = self._block.apply(
                    stage[f"layer{i}"], x, mutable=["moe_losses"]
                )
                for t in jax.tree_util.tree_leaves(mods.get("moe_losses", {})):
                    aux = aux + jnp.asarray(t, jnp.float32).mean()
        logits = self._head.apply(params["head"], x)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1, :], tokens[:, 1:]
        ).mean()
        return ce + self.moe_aux_weight * aux
