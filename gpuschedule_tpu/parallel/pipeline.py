"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

The TPU-native formulation: the pipeline IS a collective program.  Each
pp rank holds one stage's parameters (stage-stacked pytrees sharded on
their leading dim); microbatches flow stage-to-stage via
``lax.ppermute`` inside one ``shard_map``, and the whole schedule —
fill, steady state, drain: ``M + S - 1`` ticks for M microbatches over S
stages — is a single ``lax.scan`` that ``jax.grad`` differentiates
through directly, ppermute's transpose being the reverse permute.  No
per-stage processes, no send/recv framework, no hand-written backward
schedule: the 1F1B-ish interleaving falls out of autodiff's reverse
sweep.  This is the reference's pipeline-parallel analogue done the XLA
way (same design recipe as the ring in :mod:`.ringattn`; scaling-book
"pipelining" chapter pattern).

Off the critical path before the wave arrives (and after it drains) a
stage computes on zeros; those outputs are never read, and the cost is
the standard (S-1)/(M+S-1) bubble.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(params_list):
    """Stack per-stage parameter pytrees on a new leading (stage) dim —
    the layout ``pipeline_apply`` shards over pp."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params,
    microbatches: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
) -> jax.Array:
    """Run ``stage_fn`` as a ``pp``-deep pipeline over microbatches.

    ``stage_fn(stage_params, x) -> y`` must map activations to
    same-shaped activations (a transformer block); ``stacked_params``
    leaves carry a leading stage dim equal to the mesh's ``pp`` extent;
    ``microbatches`` is ``(M, mb, ...)``.  Returns the last stage's
    outputs, ``(M, mb, ...)``, replicated across pp (a psum over the
    stage mask).  Differentiable end-to-end.
    """
    n_stages = mesh.shape[axis]
    if microbatches.ndim < 2:
        raise ValueError(
            f"microbatches must be (M, microbatch, ...), got {microbatches.shape}"
        )
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked params carry {lead} stages but mesh {axis}={n_stages}"
        )
    dp = mesh.shape.get("dp", 1)
    if microbatches.shape[1] % dp != 0:
        raise ValueError(
            f"microbatch size {microbatches.shape[1]} not divisible by dp={dp}"
        )
    m = microbatches.shape[0]
    ticks = m + n_stages - 1

    def per_device(params_stacked, xs):
        # in_spec P(axis) leaves a unit stage dim; strip it
        params = jax.tree.map(lambda a: a[0], params_stacked)
        stage = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]  # stage i -> i+1

        def tick(carry, t):
            send_buf, out = carry
            # what stage-1 produced last tick arrives here; ranks with no
            # source (stage 0) receive zeros, which they never read
            recv = jax.lax.ppermute(send_buf, axis, perm)
            mb = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), keepdims=False
            )
            x = jnp.where(stage == 0, mb, recv)
            y = stage_fn(params, x)
            # the last stage finished microbatch t-(S-1) this tick
            done = t - (n_stages - 1)
            write = jnp.logical_and(done >= 0, stage == n_stages - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, jax.lax.dynamic_index_in_dim(
                    out, jnp.clip(done, 0, m - 1), keepdims=False
                )), jnp.clip(done, 0, m - 1), axis=0,
            )
            return (y, upd), None

        init = (jnp.zeros_like(xs[0]), jnp.zeros_like(xs))
        (_, out), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # replicate the last stage's result across pp so the caller sees
        # one coherent array
        mask = (stage == n_stages - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    spec_params = jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params
    )
    # the microbatch dim shards over dp (each dp column pipelines its own
    # batch shard — pp and dp compose instead of dp replicating the work);
    # params replicate over dp automatically (spec names only `axis`)
    data_spec = P(None, "dp")
    return jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec_params, data_spec),
        out_specs=data_spec,
        check_vma=False,  # psum over the stage mask makes the output invariant
    )(stacked_params, microbatches)
