"""Parallelism layer: device meshes and sharded train steps.

Where the reference reaches NCCL through ``torch.distributed`` process
groups (SURVEY.md §2 "Distributed communication backend"), this package is
pure ``jax.sharding``: build a named Mesh over the devices (ICI within a
slice), annotate parameter/activation shardings (dp / tp / sp axes), and
let XLA insert the collectives.  Nothing here spawns processes — under
``jax.distributed`` the same code runs multi-host unchanged.
"""

from gpuschedule_tpu.parallel.checkpoint import (
    reshard_state,
    restore_state,
    save_state,
)
from gpuschedule_tpu.parallel.mesh import make_mesh
from gpuschedule_tpu.parallel.pipeline import (
    PipelinedLM,
    pipeline_apply,
    stack_stage_params,
)
from gpuschedule_tpu.parallel.ringattn import ring_attention
from gpuschedule_tpu.parallel.ringflash import ring_flash_attention
from gpuschedule_tpu.parallel.train import (
    ShardedTrainer,
    make_optimizer,
    param_partition_spec,
)

__all__ = [
    "make_mesh",
    "ring_attention",
    "ring_flash_attention",
    "ShardedTrainer",
    "make_optimizer",
    "param_partition_spec",
    "save_state",
    "restore_state",
    "reshard_state",
    "pipeline_apply",
    "stack_stage_params",
    "PipelinedLM",
]
