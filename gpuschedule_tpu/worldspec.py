"""The world-flag -> config-hash table: ONE source of truth (ISSUE 13).

Every CLI knob reachable from ``_add_world_args`` or defined on a
world-building subparser (``run``, ``whatif``) is accounted for here, in
exactly one of three buckets:

- ``HASHED``: always part of the experiment config hash, under its
  argparse dest name (the hash dict key EQUALS the dest, which is what
  keeps every historical hash byte-identical — do not rename either side
  independently).
- ``HASHED_WHEN_ARMED``: rides the hash only when armed (value differs
  from the disarmed default AND is truthy) — a knob-off run's hash (and
  therefore its run_id and events header) must stay byte-identical to
  what it was before the knob existed.
- ``UNHASHED``: deliberately outside the hash, each with a one-line
  justification.  Output/telemetry knobs never change replay semantics;
  policy-side knobs are excluded so ``compare`` accepts policy-A-vs-B
  runs of the same seeded world.

``cli.py:_run_config_hash`` consumes this table at runtime; the contract
linter's config-hash coverage rule (``gpuschedule_tpu/lint/``, GS4xx)
cross-checks it statically against the argparse definitions — a flag
added to ``_add_world_args`` or ``run`` without a row here is a lint
failure, which is what turns silent hash drift into a CI-gated defect
(see docs/static-analysis.md).
"""

from __future__ import annotations

# Always hashed: dest name == hash key, values taken verbatim from args.
HASHED = (
    "cluster",
    "chips",
    "dims",
    "pods",
    "gpu_shape",
    "placement",
    "placement_seed",
    "philly",
    "trace",
    "synthetic",
    "seed",
    "arrival_rate",
    "mean_duration",
    "failure_rate",
    "util_min",
    "max_job_chips",
    "max_time",
    "faults",
)

# Hashed only when armed: dest -> disarmed default.  The knob joins the
# hash dict (key == dest, value == the armed arg value) only when the
# value is truthy and differs from the disarmed default:
# - net: only present when --net is on — a net-free run's hash must stay
#   byte-identical to before the net layer existed (ISSUE 4);
# - accounting: v2 changes the float-summation contract (ISSUE 11:
#   closure replaces byte-identity), so it IS experiment config — but
#   only when armed, keeping every historical v1 hash byte-identical.
HASHED_WHEN_ARMED = {
    "net": None,
    "accounting": "v1",
}

# Deliberately unhashed, each with its one-line justification — the
# linter refuses empty reasons (GS403).
UNHASHED = {
    # -- policy-side world flags (the hash covers cluster + trace +
    #    faults, deliberately NOT the policy, so policy-A-vs-B runs of
    #    the same world stay compare-compatible) --
    "policy": "policy identity is deliberately outside the experiment "
              "hash so A-vs-B policy runs of one world are comparable",
    "policy_arg": "policy constructor kwargs are policy identity, not "
                  "world config",
    "curves": "goodput curve cache feeds the optimus policy, not the "
              "world",
    "online": "live profiling is an optimus policy input, not world "
              "config",
    # -- run-only output / telemetry knobs (replay-neutral by pinned
    #    byte-identity contracts) --
    "out": "output directory choice never changes replay semantics",
    "prefix": "output filename prefix only",
    "events": "event recording is observational; recorded runs are "
              "byte-identical to unrecorded ones",
    "perfetto": "trace export is derived from the event stream, "
                "replay-neutral",
    "spans": "span tracing is gated at <=2% overhead and replay-neutral",
    "attrib": "attribution is additive bookkeeping; off-path runs are "
              "byte-identical (ISSUE 5 pinned)",
    "sample_interval": "sample events never perturb the replay "
                       "(byte-identity pinned, ISSUE 5)",
    "sample_on_change": "on-change samples never perturb the replay "
                        "(byte-identity pinned, ISSUE 10)",
    "self_profile": "wall-clock self-profiling leaves replay output "
                    "byte-identical (ISSUE 10 pinned)",
    "cache_stats": "cache telemetry harvests counters after the replay "
                   "finished",
    "prom": "metrics exposition format output only",
    "history": "history rows record results; they never feed back into "
               "the replay",
    "snapshot": "periodic snapshot writes are between-batch and "
                "replay-neutral (resume byte-identity pinned, ISSUE 11)",
    "flush_events": "sink flush cadence changes when bytes reach disk, "
                    "never which bytes (tailable-sink contract, "
                    "ISSUE 15)",
    "snapshot_every": "snapshot cadence, replay-neutral with --snapshot",
    "resume": "a resumed run's world comes from the snapshot, not the "
              "flags; finished outputs are byte-identical under v1",
    # -- whatif-only query flags (ISSUE 12): they select what to ASK of
    #    the mirrored world — queries evaluate on speculative forks and
    #    are never part of the world's identity --
    "at": "the mirror instant selects where to pause, not which world",
    "horizon": "speculative-replay budget per query, fork-side only",
    "pool": "worker-process count; serial and pooled documents are "
            "pinned identical",
    "admit": "admit queries evaluate on forks of the mirrored world",
    "drain": "drain queries evaluate on forks of the mirrored world",
    "swap_policy": "policy-swap queries evaluate on forks; policy is "
                   "outside the hash by design",
    "trace_out": "merged fleet-trace export is derived telemetry; "
                 "disarmed and armed runs are byte-identical "
                 "(ISSUE 16 pinned)",
    # -- serve-only daemon flags (ISSUE 18): the HTTP edge over the
    #    mirrored world — where it listens and how it drains never
    #    touch which world it serves (served-vs-offline byte identity
    #    pinned by tests/test_serve.py) --
    "host": "listen address is deployment plumbing, not world config",
    "port": "listen port is deployment plumbing, not world config",
    "follow": "stream drive mode; the alert sequence is pinned "
              "identical across batch/replay/follow (ISSUE 15)",
    "replay": "stream drive mode; alert sequence pinned identical "
              "across modes (ISSUE 15)",
    "speed": "replay pacing delays delivery only; alert content is "
             "keyed to sim time alone",
    "poll": "follow-mode poll cadence is wall-clock delivery, never "
            "alert content",
    "idle_timeout": "follow-mode stop condition, delivery-side only",
    "max_wall": "wall-clock serving budget, delivery-side only",
    "rules": "detector thresholds select what to alert on, not which "
             "world runs; the rules hash rides the alert header",
    "window": "detector window length, alert-side only (rides the "
              "alert header's rules hash)",
    "alerts": "alert side-stream output path only",
    "max_inflight": "admission-queue depth backpressures askers; "
                    "served documents are pinned identical to offline",
    "self_slo": "the daemon's own SLO thresholds watch the server, "
                "not the world",
    "drain_s": "shutdown drain budget is wall-clock edge behavior "
               "only",
}


def hash_config(args) -> dict:
    """The experiment-config dict ``cli.py:_run_config_hash`` digests —
    built from the table above so the hash computation and the linter's
    coverage rule read the same source of truth."""
    config = {dest: getattr(args, dest) for dest in HASHED}
    for dest, disarmed in HASHED_WHEN_ARMED.items():
        value = getattr(args, dest, disarmed)
        if value and value != disarmed:
            config[dest] = value
    return config
