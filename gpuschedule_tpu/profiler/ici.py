"""Analytic ICI/DCN collective cost model.

The reference measured NCCL allreduce directly on GPUs; here the collective
term is computed from first principles over the slice geometry the
allocator granted, because (a) only one physical chip exists in this
environment and (b) the analytic ring-allreduce bound is tight on TPU tori
(the scaling-book recipe).  Calibration against the measured single-chip
step (``harness``) absorbs constant factors; the 10% MAPE contract is
tested against this model's own synthetic curves (SURVEY.md §7).

Ring allreduce of B bytes over k participants moves ``2(k-1)/k * B`` bytes
through each link; on a torus axis with wraparound the ring uses both
directions, doubling effective bandwidth.  Multi-axis slices allreduce
per-axis (the standard N-D torus decomposition), so axes contribute
additively with each axis reducing its own extent.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from gpuschedule_tpu.cluster.tpu import DCN_GBPS, GENERATIONS, SliceGeometry

LATENCY_S = 1e-6  # per-hop launch latency floor per collective phase


def allreduce_seconds(
    bytes_per_chip: float,
    k: int,
    *,
    link_gbps: float,
    bidirectional: bool = False,
) -> float:
    """Ring-allreduce time for ``bytes_per_chip`` over ``k`` chips on one
    axis with per-link bandwidth ``link_gbps`` (Gbit/s)."""
    if k <= 1:
        return 0.0
    bw_bytes = link_gbps / 8.0 * 1e9 * (2.0 if bidirectional else 1.0)
    wire = 2.0 * (k - 1) / k * bytes_per_chip / bw_bytes
    return wire + (k - 1) * LATENCY_S


def slice_allreduce_seconds(
    bytes_per_chip: float,
    geom: SliceGeometry,
    *,
    generation: str,
) -> float:
    """Allreduce time over a granted slice, axis-decomposed.

    Each torus axis of extent > 1 runs a ring over that axis; the payload
    shrinks by the preceding axis's reduction factor as the N-D
    decomposition proceeds.  Wraparound axes (full torus extent) get the
    bidirectional ring.
    """
    spec = GENERATIONS[generation]
    total = 0.0
    remaining = float(bytes_per_chip)
    for extent, wraps in zip(geom.shape, geom.wrap_axes):
        if extent <= 1:
            continue
        total += allreduce_seconds(
            remaining,
            extent,
            link_gbps=spec["ici_gbps_per_link"],
            bidirectional=wraps,
        )
        remaining /= extent
    return total


def dp_gradient_bytes(param_count: int, *, dtype_bytes: int = 4) -> float:
    """Gradient payload per chip for data-parallel sync (f32 grads)."""
    return float(param_count) * dtype_bytes


def cross_pod_allreduce_seconds(
    bytes_per_chip: float, num_pods: int, *, dcn_gbps: float = DCN_GBPS
) -> float:
    """DCN-tier allreduce across pods (slices never span pods; multi-pod
    jobs sync over the datacenter network).

    ``dcn_gbps`` is the per-host DCN bandwidth the ring actually gets: the
    static planner passes the nominal :data:`DCN_GBPS`; the shared-fabric
    contention model (net/) passes each job's max-min fair share, which is
    how contention stretches this term dynamically.  ``dcn_gbps <= 0``
    (a fully degraded uplink) returns ``inf`` — the sync never completes
    until bandwidth comes back."""
    if num_pods <= 1:
        return 0.0
    if dcn_gbps <= 0.0:
        return math.inf
    bw_bytes = dcn_gbps / 8.0 * 1e9
    return 2.0 * (num_pods - 1) / num_pods * bytes_per_chip / bw_bytes + (
        num_pods - 1
    ) * 10 * LATENCY_S
