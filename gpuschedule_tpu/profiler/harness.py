"""Online measurement harness: jitted step timing over a device mesh.

The TPU-native replacement for the reference's starred process boundary
(SURVEY.md §3.2: torchrun spawn → DDP step × N iters → NCCL allreduce):
no processes are launched — the "microbenchmark" is a jitted sharded train
step executed on whatever mesh the caller provides, timed wall-clock after a
compile+warmup phase (SURVEY.md §5 "Tracing/profiling": the JAX profiler
path).

**Fencing caveat (measured on this image's axon TPU tunnel):**
``block_until_ready`` returns before device execution completes on that
PJRT transport — timing against it reads dispatch latency (~30 us),
reporting physically impossible TFLOP/s.  The only reliable fence is a host
readback.  :func:`time_steps` therefore times *blocks* of data-dependent
steps (each step consumes the previous state, forcing sequential
execution) fenced by one ``float(loss)`` readback, which amortizes the
tunnel round-trip across the block.
"""

from __future__ import annotations

import statistics
import time
import warnings
from typing import Dict, List, Optional, Sequence

from gpuschedule_tpu.models import MODEL_CONFIGS
from gpuschedule_tpu.obs.tracer import get_tracer
from gpuschedule_tpu.profiler.goodput import (
    CurveCache,
    GoodputCurve,
    fit_step_time_curve,
    synthesize_step_times,
)


def time_steps(step_fn, state, tokens, *, iters: int, repeats: int = 3):
    """Median seconds/step over ``repeats`` blocks of ``iters`` chained steps.

    ``step_fn(state, tokens) -> (state, loss)``.  Each block is fenced by a
    host readback of the final loss (see module docstring); within a block
    the state chain forces the device to run the steps back-to-back.
    Returns ``(seconds_per_step, final_state)``.
    """
    if iters < 1 or repeats < 1:
        raise ValueError(f"iters/repeats must be >= 1, got {iters}/{repeats}")
    tracer = get_tracer()
    block_times: List[float] = []
    loss = None
    for block in range(repeats):
        with tracer.span(
            "profiler.block", cat="profiler", block=block, iters=iters
        ) as sp:
            t0 = time.perf_counter()
            for _ in range(iters):
                state, loss = step_fn(state, tokens)
            float(loss)  # host readback: the only fence this transport honors
            block_s = (time.perf_counter() - t0) / iters
            sp.set(s_per_step=block_s)
        block_times.append(block_s)
    return statistics.median(block_times), state


def time_callable(fn, *args, iters: int = 8, warmup: int = 2) -> float:
    """Mean seconds per call of ``fn(*args)`` with the host-readback fence
    this transport requires (see module docstring) — one readback fences
    the whole jitted program, since all outputs are one TPU computation.
    The single timing recipe shared by bench.py's kernel attribution and
    tools/kernel_bench.py, so fencing fixes land in one place."""
    import jax
    import jax.numpy as jnp

    out = None
    for _ in range(warmup):
        out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))
    return (time.perf_counter() - t0) / iters


def _mesh_trainer(
    model_name, devices, batch_size, seq_len, *,
    sp: int = 1, tp: int = 1, pp: int = 1, seq_shard: bool = False,
    warmup: int = 1, num_microbatches: int = 4,
):
    """Shared setup for measurement and trace capture: a (dp, sp, tp) mesh
    over the devices — dp takes whatever the sp/tp factors leave — with
    batch rounded down to a dp multiple (one fallback formula, so the
    traced step is exactly the measured step), compile fenced.

    ``pp >= 2`` builds the staged :class:`PipelinedLM` on a (pp, dp) mesh
    instead (round-4 verdict #5: pp is a first-class measurement target);
    sp/tp must stay 1 — the pipeline composes with dp only."""
    import jax

    from gpuschedule_tpu.parallel import PipelinedLM, ShardedTrainer, make_mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    if sp < 1 or tp < 1 or pp < 1 or len(devs) % (sp * tp * pp) != 0:
        raise ValueError(
            f"{len(devs)} devices do not factor as dp x sp={sp} x tp={tp} "
            f"x pp={pp}"
        )
    if pp > 1 and (sp > 1 or tp > 1):
        raise ValueError(f"pp={pp} composes with dp only; got sp={sp}, tp={tp}")
    dp = len(devs) // (sp * tp * pp)
    if pp > 1:
        mesh = make_mesh(dp=dp, pp=pp, devices=devs)
        # batch must split into M microbatches whose size divides dp
        bs = max(batch_size - batch_size % (num_microbatches * dp),
                 num_microbatches * dp)
        if bs != batch_size:
            # same cross-k comparability hazard as the dp-branch warning
            # below, at pipeline granularity (num_microbatches * dp)
            warnings.warn(
                f"batch {batch_size} not divisible by microbatches*dp="
                f"{num_microbatches * dp}: measuring batch {bs} instead — "
                f"step times at this k are NOT comparable to ks that kept "
                f"the requested batch; use a batch size divisible by "
                f"num_microbatches * every profiled dp",
                stacklevel=3,
            )
        trainer = PipelinedLM(
            model_name, mesh, batch_size=bs, seq_len=seq_len,
            num_microbatches=num_microbatches,
        )
    else:
        mesh = make_mesh(dp=dp, sp=sp, tp=tp, devices=devs)
        bs = batch_size
        if bs % dp != 0:
            bs = max(dp, bs - bs % dp)
            # A silent round-down poisons cross-k comparisons: a curve fit
            # over ks where some points secretly ran a smaller global batch
            # mixes workloads (the round-5 hold-out failure: ks {3, 6}
            # measured batch 6 against batch-8 fit points and broke the
            # 10% MAPE band).  Warn so operators pick a batch every k
            # divides (e.g. lcm of the ks) instead of trusting the bias.
            warnings.warn(
                f"batch {batch_size} not divisible by dp={dp}: measuring "
                f"batch {bs} instead — step times at this k are NOT "
                f"comparable to ks that kept the full batch; use a batch "
                f"size divisible by every profiled k",
                stacklevel=3,
            )
        trainer = ShardedTrainer(
            model_name, mesh, batch_size=bs, seq_len=seq_len, seq_shard=seq_shard
        )
    state = trainer.init(seed=0)
    batch = trainer.make_batch(seed=0)
    for _ in range(max(1, warmup)):  # first step compiles
        state, loss = trainer.step(state, batch)
    float(loss)  # fence warmup/compile
    return trainer, state, batch


def measure_step_time(
    model_name: str,
    *,
    devices: Optional[Sequence] = None,
    batch_size: int = 8,
    seq_len: int = 128,
    warmup: int = 2,
    iters: int = 10,
    repeats: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    seq_shard: bool = False,
    num_microbatches: int = 4,
) -> float:
    """Median seconds per optimizer step on a (dp, sp, tp) mesh over
    ``devices`` (dp is inferred as ``len(devices) / (sp * tp * pp)``; the
    round-3 verdict's "profile-able over an arbitrary Mesh" gap).
    ``pp >= 2`` measures the staged pipeline trainer instead.

    ``repeats=1`` keeps live-profiling device time at ``iters`` steps per
    (model, k) point; bench.py uses more blocks for a stabler median."""
    import jax

    k = len(devices) if devices is not None else len(jax.devices())
    with get_tracer().span(
        "profiler.measure_step_time", cat="profiler",
        model=model_name, k=k, sp=sp, tp=tp, pp=pp,
    ) as sp_:
        trainer, state, batch = _mesh_trainer(
            model_name, devices, batch_size, seq_len,
            sp=sp, tp=tp, pp=pp, seq_shard=seq_shard, warmup=warmup,
            num_microbatches=num_microbatches,
        )
        step_s, _ = time_steps(trainer.step, state, batch, iters=iters, repeats=repeats)
        sp_.set(step_s=step_s)
    return step_s


def capture_trace(
    model_name: str,
    out_dir,
    *,
    devices: Optional[Sequence] = None,
    batch_size: int = 8,
    seq_len: int = 128,
    steps: int = 3,
    sp: int = 1,
    tp: int = 1,
) -> str:
    """Capture an xprof (TensorBoard-viewable) trace of the train step.

    The deep-inspection path of the tracing subsystem (SURVEY.md §5
    "Tracing/profiling": ``jax.profiler.trace`` around jitted steps):
    wall-clock medians come from :func:`time_steps`; this produces the
    per-op timeline for when a number needs explaining.  ``sp``/``tp``
    must match the measurement they explain — the traced step is built by
    the same ``_mesh_trainer`` as the measured one.  Returns the
    directory path; view with ``tensorboard --logdir`` or xprof.
    """
    import jax

    with get_tracer().span(
        "profiler.capture_trace", cat="profiler", model=model_name, steps=steps
    ):
        trainer, state, batch = _mesh_trainer(
            model_name, devices, batch_size, seq_len,
            sp=sp, tp=tp, seq_shard=sp > 1,
        )
        with jax.profiler.trace(str(out_dir)):
            for _ in range(steps):
                state, loss = trainer.step(state, batch)
            float(loss)  # host fence inside the trace window
    return str(out_dir)


def profile_model(
    model_name: str,
    *,
    ks: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
    generation: str = "v5e",
    devices: Optional[Sequence] = None,
    batch_size: int = 8,
    seq_len: int = 128,
    cache: Optional[CurveCache] = None,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
) -> GoodputCurve:
    """Fit a goodput curve for ``model_name``, measuring what the hardware
    allows and extending analytically.

    Every k <= len(devices) is measured on a real (dp, sp, tp) mesh with
    dp = k/(sp*tp) — so tp/sp-sharded configurations are first-class
    measurement targets, not just dp (the round-3 verdict's harness gap);
    ``pp >= 2`` measures the staged pipeline trainer on (pp, dp) meshes
    (round-4 verdict #5 — a pp curve lands in the cache like any other).
    Larger k are synthesized from the smallest measured unit + the
    analytic ICI allreduce over the slice shape the allocator would grant
    (SURVEY.md §7 "Step-time model fidelity" — the one-chip mitigation);
    the dp-sync payload per chip shrinks by tp (params tp-sharded) and by
    pp (each stage holds 1/pp of the layers).  The fitted curve is stored
    in ``cache`` when given.
    """
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    cfg = MODEL_CONFIGS[model_name]
    if pp > 1 and (sp > 1 or tp > 1):
        raise ValueError(f"pp={pp} composes with dp only; got sp={sp}, tp={tp}")
    unit = sp * tp * pp  # smallest k that forms one model replica
    bad = [k for k in ks if k % unit]
    if bad:
        raise ValueError(f"ks {bad} not divisible by sp*tp*pp={unit}")

    # an sp axis only means something when the sequence is sharded over
    # it — without seq_shard the "sp mesh" would silently measure a
    # smaller dp mesh and mislabel the cached curve
    seq_shard = sp > 1
    measured: Dict[int, float] = {}
    with get_tracer().span(
        "profiler.profile_model", cat="profiler",
        model=model_name, ks=list(ks), generation=generation,
    ) as prof_sp:
        for k in ks:
            if k <= len(devs):
                measured[k] = measure_step_time(
                    model_name,
                    devices=devs[:k],
                    batch_size=batch_size,
                    seq_len=seq_len,
                    sp=sp,
                    tp=tp,
                    pp=pp,
                    seq_shard=seq_shard,
                )
        prof_sp.set(measured_ks=sorted(measured))
    synth_ks = [k for k in ks if k not in measured]
    if synth_ks and unit not in measured:
        # the analytic extension anchors on the smallest-replica point;
        # measure it only when synthesis actually needs it (an all-
        # measured request must not burn extra device time or inject an
        # unrequested point into the fit)
        if unit > len(devs):
            raise ValueError(
                f"sp*tp*pp={unit} exceeds the {len(devs)} available devices; "
                "nothing is measurable"
            )
        measured[unit] = measure_step_time(
            model_name, devices=devs[:unit], batch_size=batch_size,
            seq_len=seq_len, sp=sp, tp=tp, pp=pp, seq_shard=seq_shard,
        )
    points = dict(measured)
    # per-chip dp-grad payload: tp shards the params, pp splits the layers
    per_chip_params = cfg.param_count // (tp * pp)
    if synth_ks:
        synth = synthesize_step_times(
            single_chip_step_s=measured[unit],
            param_count=per_chip_params,
            generation=generation,
            ks=synth_ks,
            unit=unit,
        )
        points.update(dict(zip(synth_ks, synth)))

    # Fit the smooth family on intra-pod points only: the three-parameter
    # family cannot represent the ICI->DCN step discontinuity at the pod
    # boundary, so multislice points would corrupt the intra-pod fit.  The
    # curve instead carries (pod_chips, dcn_grad_bytes) and adds the
    # analytic DCN phase in step_time_dcn — the same cross-pod term the
    # synthesized points above used, so planning and synthesis agree.
    import math as _math

    from gpuschedule_tpu.cluster.tpu import GENERATIONS
    from gpuschedule_tpu.profiler.ici import dp_gradient_bytes as _dp_bytes

    pod = _math.prod(GENERATIONS[generation]["pod_dims"])
    intra = {k: v for k, v in points.items() if k <= pod}
    if intra:
        curve = fit_step_time_curve(sorted(intra), [intra[k] for k in sorted(intra)])
        curve = GoodputCurve(
            curve.theta,
            pod_chips=pod,
            dcn_grad_bytes=_dp_bytes(per_chip_params),
        )
    else:
        # every requested k lies beyond one pod: the synthesized points
        # already carry the DCN phase, so fit the smooth family on them
        # and leave the curve non-multislice-aware — step_time_dcn adding
        # the phase AGAIN on top of a DCN-baked fit would double-count it
        # (consumers then keep the conservative one-pod growth cap)
        curve = fit_step_time_curve(
            sorted(points), [points[k] for k in sorted(points)]
        )
    if cache is not None:
        # sp/tp/pp variants get their own cache key: the scheduler's replay
        # looks curves up by bare model name, and a dp curve silently
        # replaced by a parallelism variant would feed it wrong step times
        if sp == 1 and tp == 1 and pp == 1:
            key = model_name
        elif pp == 1:
            key = f"{model_name}@sp{sp}tp{tp}"
        else:
            key = f"{model_name}@sp{sp}tp{tp}pp{pp}"
        cache.put(
            key,
            curve,
            source=(
                f"measured<= {len(devs)} chips (sp={sp}, tp={tp}, pp={pp}), "
                f"analytic beyond ({generation})"
            ),
            points=points,
        )
        cache.save()
    return curve
