"""Throughput profiler: the reference's NCCL microbenchmark subsystem,
rebuilt as a JAX/XLA step-time harness (BASELINE.json north_star).

Where the reference launches ``torch.distributed`` DDP + NCCL allreduce
runs per candidate world size and fits goodput-vs-#GPUs curves (SURVEY.md
§2 "Throughput profiler", §3.5), this package:

- measures a jitted sharded train step with ``block_until_ready`` wall
  clock (:mod:`harness`) — the JAX profiler path;
- models the collective term analytically from slice geometry and ICI
  bandwidth (:mod:`ici`) so goodput-vs-#chips extends beyond the chips
  physically present (single-chip calibration, SURVEY.md §7 "Step-time
  model fidelity");
- fits the Optimus-family curve and caches parameters on disk
  (:mod:`goodput`) so trace replay runs device-free (SURVEY.md §4).
"""

from gpuschedule_tpu.profiler.goodput import (
    CurveCache,
    GoodputCurve,
    fit_step_time_curve,
)
from gpuschedule_tpu.profiler.ici import allreduce_seconds, slice_allreduce_seconds

__all__ = [
    "CurveCache",
    "GoodputCurve",
    "fit_step_time_curve",
    "allreduce_seconds",
    "slice_allreduce_seconds",
]
