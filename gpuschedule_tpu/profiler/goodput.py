"""Goodput curves: the Optimus-family step-time model, fitting, and cache.

The curve family is the data-parallel scaling law the reference fits per
job/model (SURVEY.md §2 "Profiler / goodput model", Optimus EuroSys'18),
in alpha-beta form:

    step_time(k) = theta0 / k  +  theta1  +  theta2 * (k - 1)

theta0 = parallelizable compute, theta1 = serial work + the ring-allreduce
bandwidth asymptote (2B/bw * (1 - 1/k) folds into theta1 and theta0), and
theta2 = per-hop collective latency.  Note the naive ``theta2 * (k-1)/k``
comm term is NOT used: (k-1)/k = 1 - 1/k is a linear combination of the
other two features, making that family rank-deficient.  The model is
**linear in theta**, so fitting is a non-negative least squares solved by
lstsq + active-set clipping — no scipy dependency.

``CurveCache`` persists fitted parameters as JSON so trace replay and the
Optimus policy run device-free (SURVEY.md §4 "pre-fitted curve files").
``synthesize_curve`` builds the curve from a single-chip measurement plus
the analytic ICI term — the mitigation for having one physical chip
(SURVEY.md §7 "Step-time model fidelity").
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from gpuschedule_tpu.cluster.tpu import GENERATIONS, SliceGeometry, valid_slice_shapes
from gpuschedule_tpu.profiler.ici import (
    cross_pod_allreduce_seconds,
    dp_gradient_bytes,
    slice_allreduce_seconds,
)


@dataclass(frozen=True)
class GoodputCurve:
    """Fitted step-time curve for one model.

    ``pod_chips``/``dcn_grad_bytes`` (optional) make the curve
    *multislice-aware*: the smooth three-parameter family is fit on
    intra-pod points only (it cannot represent the ICI→DCN cliff — a step
    discontinuity at the pod boundary), and :meth:`step_time_dcn` adds the
    analytic cross-pod allreduce phase for k beyond one pod.  Schedulers
    must plan with ``step_time_dcn`` but enact speed from the plain
    ``speed_factor``: the sim engine charges the DCN toll separately
    through ``job.locality_factor`` (cluster/tpu.py
    ``_multislice_speed_factor``), so a DCN-aware enacted speed would
    double-count it.
    """

    theta: Tuple[float, float, float]
    pod_chips: Optional[int] = None      # multislice boundary (None: no DCN model)
    dcn_grad_bytes: Optional[float] = None  # per-chip dp-sync payload over DCN

    def step_time(self, k: int) -> float:
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        t0, t1, t2 = self.theta
        return t0 / k + t1 + t2 * (k - 1)

    @property
    def multislice_aware(self) -> bool:
        return self.pod_chips is not None and self.dcn_grad_bytes is not None

    def step_time_dcn(self, k: int, *, pod_chips: Optional[int] = None) -> float:
        """Planning-time step estimate including the DCN phase beyond one
        pod — the number marginal-gain decisions must use.  Falls back to
        the smooth family when the curve carries no multislice fields.

        ``pod_chips`` overrides the curve's own boundary: the cliff sits
        where the *cluster being scheduled* crosses pods (a curve profiled
        against the nominal v5e 256-chip pod would otherwise misplace the
        boundary on a custom-dims fleet)."""
        base = self.step_time(k)
        boundary = pod_chips if pod_chips is not None else self.pod_chips
        if boundary is not None and self.dcn_grad_bytes is not None and k > boundary:
            m = math.ceil(k / boundary)
            base += cross_pod_allreduce_seconds(self.dcn_grad_bytes, m)
        return base

    def throughput(self, k: int) -> float:
        """Steps per second at k chips."""
        return 1.0 / self.step_time(k)

    def speedup(self, k: int) -> float:
        """Throughput at k chips relative to one chip."""
        return self.step_time(1) / self.step_time(k)

    def speed_factor(self, k: int, ref_k: int) -> float:
        """Progress rate at k chips relative to the trace-declared ``ref_k``
        allocation — the engine's ``speed`` currency: wall time to finish
        W work on k chips = W * step_time(k) / step_time(ref_k)."""
        return self.step_time(ref_k) / self.step_time(k)

    def marginal_gain(self, k: int) -> float:
        """Throughput gained by the (k+1)-th chip — Optimus's allocation key."""
        return self.throughput(k + 1) - self.throughput(k)


def _design(ks: np.ndarray) -> np.ndarray:
    return np.stack([1.0 / ks, np.ones_like(ks), ks - 1.0], axis=1)


def fit_step_time_curve(
    ks: Sequence[int], times: Sequence[float]
) -> GoodputCurve:
    """Non-negative least squares fit of the curve family to measurements.

    lstsq first; any negative component is clamped to zero and the fit
    re-solved over the remaining features (one active-set pass per
    component, at most 3 — exact for this tiny, well-conditioned family).
    """
    ks_arr = np.asarray(ks, dtype=np.float64)
    ts = np.asarray(times, dtype=np.float64)
    if ks_arr.shape != ts.shape or ks_arr.size == 0:
        raise ValueError("ks and times must be equal-length, non-empty")
    if np.any(ks_arr < 1) or np.any(ts <= 0):
        raise ValueError("need k >= 1 and positive times")

    X = _design(ks_arr)
    active = [0, 1, 2]
    theta = np.zeros(3)
    for _ in range(3):
        sol, *_ = np.linalg.lstsq(X[:, active], ts, rcond=None)
        if np.all(sol >= 0):
            theta[:] = 0.0
            theta[active] = sol
            break
        # drop the most negative component and re-solve
        drop = active[int(np.argmin(sol))]
        active = [a for a in active if a != drop]
        if not active:
            theta[:] = 0.0
            break
    else:
        theta[:] = 0.0
        if active:
            sol, *_ = np.linalg.lstsq(X[:, active], ts, rcond=None)
            theta[active] = np.maximum(sol, 0.0)
    return GoodputCurve(tuple(float(t) for t in theta))


def mape(curve: GoodputCurve, ks: Sequence[int], times: Sequence[float]) -> float:
    """Mean absolute percentage error of the curve vs measurements —
    the BASELINE.json 10% contract metric."""
    errs = [
        abs(curve.step_time(k) - t) / t for k, t in zip(ks, times)
    ]
    return float(np.mean(errs))


# --------------------------------------------------------------------- #
# single-chip calibration + analytic extension


def synthesize_step_times(
    *,
    single_chip_step_s: float,
    param_count: int,
    generation: str,
    ks: Sequence[int],
    serial_fraction: float = 0.02,
    unit: int = 1,
) -> List[float]:
    """Predict step_time(k) from one measured baseline + the analytic ICI
    term.

    ``unit`` is how many chips the measured baseline spanned (1 for a
    plain single-chip measurement; sp*tp when the smallest model replica
    is itself sharded): compute scales as (1 - serial_fraction)/(k/unit)
    — adding replicas, data-parallel.  The collective term is the
    axis-decomposed ring allreduce of ``param_count`` f32 gradients per
    chip (callers divide by tp for tp-sharded params) over the squarest
    valid slice shape for k (what the allocator would grant).
    """
    spec = GENERATIONS[generation]
    dims = spec["pod_dims"]
    pod_chips = math.prod(dims)
    comp = single_chip_step_s * (1.0 - serial_fraction)
    serial = single_chip_step_s * serial_fraction
    grad_bytes = dp_gradient_bytes(param_count)
    full_pod = SliceGeometry(
        pod=0,
        origin=tuple(0 for _ in dims),
        shape=tuple(dims),
        wrap_axes=tuple(True for _ in dims),
    )
    out = []
    for k in ks:
        if k % unit:
            raise ValueError(f"k={k} is not a multiple of the measured unit {unit}")
        if k > pod_chips:
            # multislice: m whole pods — per-pod ICI allreduce, then the
            # cross-pod DCN phase on the already-reduced payload (this is
            # where the ICI-vs-DCN cliff enters the goodput curves)
            m, rem = divmod(k, pod_chips)
            if rem:
                raise ValueError(
                    f"{k} chips exceed one {generation} pod ({pod_chips}) "
                    "and are not a whole-pod multiple"
                )
            comm = slice_allreduce_seconds(
                grad_bytes, full_pod, generation=generation
            ) + cross_pod_allreduce_seconds(grad_bytes, m)
            out.append(comp / (k // unit) + serial + comm)
            continue
        shapes = valid_slice_shapes(k, dims)
        if not shapes:
            raise ValueError(f"{k} is not a valid slice size on {dims}")
        shape = shapes[0]
        geom = SliceGeometry(
            pod=0,
            origin=tuple(0 for _ in shape),
            shape=shape,
            wrap_axes=tuple(s == d for s, d in zip(shape, dims)),
        )
        comm = slice_allreduce_seconds(grad_bytes, geom, generation=generation)
        out.append(comp / (k // unit) + serial + comm)
    return out


# --------------------------------------------------------------------- #
# on-disk cache


class CurveCache:
    """JSON-backed store of fitted curves keyed by model name.

    Format: {model: {"theta": [t0, t1, t2], "source": "...", "points":
    {k: step_s}}} — points are kept so curves can be refit or audited.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._curves: Dict[str, GoodputCurve] = {}
        self._meta: Dict[str, dict] = {}
        if self.path.exists():
            self.load()

    def load(self) -> None:
        raw = json.loads(self.path.read_text())
        for name, entry in raw.items():
            ms = entry.get("multislice") or {}
            self._curves[name] = GoodputCurve(
                tuple(entry["theta"]),
                pod_chips=ms.get("pod_chips"),
                dcn_grad_bytes=ms.get("dcn_grad_bytes"),
            )
            self._meta[name] = {
                k: v for k, v in entry.items() if k not in ("theta", "multislice")
            }

    def save(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        payload = {}
        for name, curve in self._curves.items():
            entry = {"theta": list(curve.theta), **self._meta.get(name, {})}
            if curve.multislice_aware:
                entry["multislice"] = {
                    "pod_chips": curve.pod_chips,
                    "dcn_grad_bytes": curve.dcn_grad_bytes,
                }
            payload[name] = entry
        self.path.write_text(json.dumps(payload, indent=2, sort_keys=True))

    def put(
        self,
        model: str,
        curve: GoodputCurve,
        *,
        source: str = "measured",
        points: Optional[Dict[int, float]] = None,
    ) -> None:
        self._curves[model] = curve
        meta: dict = {"source": source}
        if points:
            meta["points"] = {str(k): v for k, v in points.items()}
        self._meta[model] = meta

    def get(self, model: str) -> Optional[GoodputCurve]:
        return self._curves.get(model)

    def __contains__(self, model: str) -> bool:
        return model in self._curves

    def models(self) -> List[str]:
        return sorted(self._curves)
