"""Persistent process worker pool (ISSUE 12 tentpole).

The PR-7 sweep grids fan out through a fresh ``ProcessPoolExecutor`` per
call (and, after PR 8's crash resilience, a fresh pool per *retry
round*): fine for minutes-long sweep cells, hopeless for the digital
twin's what-if queries, where a worker must restore a mirrored engine
snapshot ONCE and then answer many sub-second queries against it.  This
module is the long-lived generalization both callers share:

- **warm workers**: each worker is one long-lived process with its own
  request queue; :meth:`WorkerPool.broadcast` runs a load function on
  every worker (shipping e.g. snapshot bytes) and the pool remembers the
  load so a respawned worker is re-warmed before it serves anything;
- **deterministic reassembly**: :meth:`WorkerPool.map` returns results
  in task order whatever the completion interleaving — the serial-vs-
  parallel byte-identity rule of docs/performance.md;
- **crash/retry semantics** (the PR-8 contract): a task whose worker
  crashed (OOM-kill, hard ``os._exit``) or raised is retried up to
  ``max_retries`` times with exponential backoff; only the failed task
  re-runs, on a freshly respawned (and re-warmed) worker when the old
  one died — no fresh-pool-per-round churn, surviving workers keep
  serving;
- **per-task fault isolation**: one dead worker takes down exactly its
  in-flight task, never its poolmates' (a ``ProcessPoolExecutor`` breaks
  the whole pool).

Tasks and their results cross process boundaries by pickle: task
functions must be module-level, and results must be picklable.  Pure
stdlib, jax-free (sim-core rule).
"""

from __future__ import annotations

import itertools
import multiprocessing
import pickle
import queue as queue_mod
import time
import traceback
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class WorkerCrashError(RuntimeError):
    """A worker process died (hard exit / kill) while running a task."""


class RemoteError(RuntimeError):
    """A task raised an exception that could not itself be pickled back;
    carries the remote traceback text."""


_POLL_S = 0.05  # response-queue poll granularity (liveness check cadence)


def _worker_main(wid: int, req_q, res_q) -> None:
    """Worker loop: apply ``fn(*args)`` per request, ship back
    ``(wid, task_id, ok, payload)``.  Warm state lives in the task
    functions' own module globals (see sim/whatif.py) — the pool itself
    is payload-agnostic."""
    while True:
        msg = req_q.get()
        if msg is None:
            break
        task_id, fn, args = msg
        try:
            out = fn(*args)
            ok = True
        except BaseException as e:  # noqa: BLE001 — everything crosses back
            out = e
            ok = False
        try:
            res_q.put((wid, task_id, ok, out))
        except Exception:
            # unpicklable result/exception: degrade to a text-carrying
            # error instead of wedging the parent's result loop
            res_q.put((wid, task_id, False, RemoteError(
                f"task {task_id} result not picklable: "
                f"{traceback.format_exc()}"
            )))


class _Worker:
    __slots__ = ("proc", "req_q")

    def __init__(self, proc, req_q):
        self.proc = proc
        self.req_q = req_q


class WorkerPool:
    """A persistent pool of ``workers`` warm processes.

    ``max_retries`` / ``backoff_s`` follow the PR-8 grid semantics: a
    failed task (worker crash or task exception) is retried up to
    ``max_retries`` times, sleeping ``backoff_s * 2^(attempt-1)``
    between attempts; exhausting the budget re-raises the last error.
    ``on_retry(task_index, attempt)`` (when given) is invoked once per
    retry — the hook :func:`gpuschedule_tpu.faults.sweep.grid_cells`
    adapts onto its ``retry_log`` contract.

    ``registry`` (any object with the ``MetricsRegistry.counter``
    surface; the pool stays import-free of the obs layer) surfaces pool
    lifecycle in the metrics plane (ISSUE 16):
    ``pool_worker_respawns_total`` counts dead workers respawned and
    ``pool_task_retries_total`` counts task attempts retried — the same
    events the ``retry_log`` records, now exportable via ``--prom`` and
    the history store.  ``self.respawns`` / ``self.retries`` mirror them
    as plain ints regardless.
    """

    def __init__(
        self,
        workers: int,
        *,
        max_retries: int = 2,
        backoff_s: float = 1.0,
        on_retry: Optional[Callable[[int, int], None]] = None,
        mp_context=None,
        registry=None,
    ):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self._ctx = mp_context or multiprocessing.get_context()
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.on_retry = on_retry
        self._res_q = self._ctx.Queue()
        self._task_ids = itertools.count()
        self._workers: Dict[int, _Worker] = {}
        # warm-state loads, replayed (in order) into every respawned
        # worker before it serves tasks: the "restore once" contract
        self._loads: List[Tuple[Callable, tuple]] = []
        self._closed = False
        self.respawns = 0
        self.retries = 0
        self._respawns_c = self._retries_c = None
        if registry is not None:
            self._respawns_c = registry.counter(
                "pool_worker_respawns_total",
                "dead pool workers respawned (and re-warmed)",
            )
            self._retries_c = registry.counter(
                "pool_task_retries_total",
                "pool task attempts retried after a crash or exception",
            )
        for wid in range(int(workers)):
            self._spawn(wid)

    # ------------------------------------------------------------------ #
    # lifecycle

    def _spawn(self, wid: int) -> None:
        req_q = self._ctx.Queue()
        proc = self._ctx.Process(
            target=_worker_main, args=(wid, req_q, self._res_q), daemon=True
        )
        proc.start()
        self._workers[wid] = _Worker(proc, req_q)

    def close(self) -> None:
        """Stop every worker (sentinel, then terminate stragglers)."""
        if self._closed:
            return
        self._closed = True
        for w in self._workers.values():
            try:
                w.req_q.put(None)
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for w in self._workers.values():
            w.proc.join(timeout=max(0.0, deadline - time.monotonic()))
            if w.proc.is_alive():
                w.proc.terminate()
                w.proc.join(timeout=1.0)
        self._workers.clear()

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def workers(self) -> int:
        return len(self._workers)

    # ------------------------------------------------------------------ #
    # scheduling core

    def _send(self, wid: int, fn: Callable, args: tuple) -> int:
        task_id = next(self._task_ids)
        self._workers[wid].req_q.put((task_id, fn, args))
        return task_id

    def _revive(self, wid: int) -> None:
        """Respawn a dead worker and replay the warm-state loads into its
        queue ahead of any task (FIFO per worker: the loads run first).
        Load acks are awaited lazily by the caller's result loop."""
        w = self._workers.get(wid)
        if w is not None:
            w.proc.join(timeout=0.1)
        self._spawn(wid)
        self.respawns += 1
        if self._respawns_c is not None:
            self._respawns_c.inc()
        for fn, args in self._loads:
            # fire-and-forget: a failing replayed load surfaces when the
            # worker's next task crashes or errors, which retries it
            self._workers[wid].req_q.put((next(self._task_ids), fn, args))

    def _note_retry(self) -> None:
        self.retries += 1
        if self._retries_c is not None:
            self._retries_c.inc()

    def broadcast(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on EVERY worker (warm-state load), blocking
        until each acknowledged.  The load is remembered and replayed
        into any worker respawned later, so warm state survives crashes.
        A worker whose load keeps failing after ``max_retries`` respawns
        takes the pool down (without its state the pool would silently
        serve from cold workers)."""
        if self._closed:
            raise RuntimeError("broadcast on a closed pool")
        pending: Dict[int, int] = {}   # task_id -> wid
        attempts: Dict[int, int] = dict.fromkeys(self._workers, 0)
        for wid in sorted(self._workers):
            pending[self._send(wid, fn, args)] = wid
        while pending:
            try:
                wid, task_id, ok, payload = self._res_q.get(timeout=_POLL_S)
            except queue_mod.Empty:
                for task_id, wid in list(pending.items()):
                    if not self._workers[wid].proc.is_alive():
                        del pending[task_id]
                        attempts[wid] += 1
                        if attempts[wid] > self.max_retries:
                            raise WorkerCrashError(
                                f"worker {wid} died {attempts[wid]}x "
                                "during warm-state load"
                            )
                        self._note_retry()
                        time.sleep(
                            self.backoff_s * (2 ** (attempts[wid] - 1))
                        )
                        self._revive(wid)
                        pending[self._send(wid, fn, args)] = wid
                continue
            if task_id not in pending:
                continue  # stale ack from a replaced incarnation
            del pending[task_id]
            if not ok:
                attempts[wid] += 1
                if attempts[wid] > self.max_retries:
                    raise payload
                self._note_retry()
                time.sleep(self.backoff_s * (2 ** (attempts[wid] - 1)))
                pending[self._send(wid, fn, args)] = wid
        self._loads.append((fn, args))

    def map(
        self,
        fn: Callable,
        items: Sequence[tuple],
        *,
        on_retry: Optional[Callable[[int, int], None]] = None,
        fleet=None,
    ) -> list:
        """``[fn(*item) for item in items]`` across the pool, results in
        item order.  Retries follow the pool's crash/retry semantics; a
        task exhausting its budget re-raises and abandons the rest.

        ``fleet`` (a :class:`gpuschedule_tpu.obs.fleet.FleetCollector`,
        duck-typed so the pool stays obs-import-free) arms cross-process
        tracing (ISSUE 16): each task ships wrapped with its trace-context
        envelope via ``fleet.task(fn, idx, args)``, and each *successful*
        result is unwrapped through ``fleet.absorb(idx, wid, payload)``,
        which records the worker's telemetry keyed by task index.  The
        retry discipline is structural: a crashed attempt's telemetry
        died with its process, a raised attempt's is never returned, and
        a retired incarnation's late success is dropped right here (the
        ``running.get(task_id) is None`` guard) before it could reach the
        collector — merged telemetry never double-counts an attempt."""
        if self._closed:
            raise RuntimeError("map on a closed pool")
        on_retry = on_retry or self.on_retry
        n = len(items)
        results: list = [None] * n
        done = 0
        next_item = 0
        attempts = [0] * n
        running: Dict[int, Tuple[int, int]] = {}  # task_id -> (index, wid)
        busy: Dict[int, int] = {}                 # wid -> task_id
        retry_at: List[Tuple[float, int]] = []    # (eligible time, index)
        ready: List[int] = []                     # indices eligible now

        def fill_workers() -> None:
            nonlocal next_item
            now = time.monotonic()
            while retry_at and retry_at[0][0] <= now:
                ready.append(retry_at.pop(0)[1])
            for wid in sorted(self._workers):
                if wid in busy:
                    continue
                if ready:
                    idx = ready.pop(0)
                elif next_item < n:
                    idx = next_item
                    next_item += 1
                else:
                    return
                if fleet is None:
                    task_id = self._send(wid, fn, tuple(items[idx]))
                else:
                    wfn, wargs = fleet.task(fn, idx, tuple(items[idx]))
                    task_id = self._send(wid, wfn, wargs)
                running[task_id] = (idx, wid)
                busy[wid] = task_id

        def fail(task_id: int, idx: int, wid: int, error: Exception) -> None:
            running.pop(task_id, None)
            if busy.get(wid) == task_id:
                del busy[wid]
            attempts[idx] += 1
            if attempts[idx] > self.max_retries:
                raise error
            self._note_retry()
            if on_retry is not None:
                on_retry(idx, attempts[idx])
            delay = self.backoff_s * (2 ** (attempts[idx] - 1))
            retry_at.append((time.monotonic() + delay, idx))
            retry_at.sort()

        fill_workers()
        while done < n:
            try:
                wid, task_id, ok, payload = self._res_q.get(timeout=_POLL_S)
            except queue_mod.Empty:
                for task_id, (idx, wid) in list(running.items()):
                    if not self._workers[wid].proc.is_alive():
                        fail(task_id, idx, wid, WorkerCrashError(
                            f"worker {wid} died running task {idx}"
                        ))
                        self._revive(wid)
                fill_workers()
                continue
            entry = running.get(task_id)
            if entry is None:
                continue  # warm-load ack or a retired incarnation's task
            idx, twid = entry
            if ok:
                del running[task_id]
                if busy.get(twid) == task_id:
                    del busy[twid]
                if fleet is None:
                    results[idx] = payload
                else:
                    results[idx] = fleet.absorb(idx, twid, payload)
                done += 1
            else:
                fail(task_id, idx, twid, payload)
            fill_workers()
        return results
