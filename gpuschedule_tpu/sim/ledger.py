"""Struct-of-arrays job accounting ledger — the v2 vectorized core (ISSUE 11).

Under ``--accounting v1`` (the default) the engine advances every running
job at every event batch, and the resulting chunk-per-batch float sums are
part of the byte-identity contract (docs/performance.md).  ``v2`` replaces
that contract with **exact-sum closure** (the goodput and attribution
decompositions still close against :class:`SimResult` to the last float,
under the v2 summation order) — which unlocks two things:

- **lazy integration**: :meth:`~gpuschedule_tpu.sim.job.Job.advance` is
  segment-exact for any ``dt``, so a policy that never reads running-job
  progress between events (``Policy.reads_progress = False``, e.g. FIFO)
  needs *no per-batch work at all* — each job integrates once per
  mutation instead of once per batch;
- **vectorized sync** for policies that *do* read progress every pass
  (DLAS attained service, SRTF remaining work, ...): this ledger mirrors
  the per-job hot state ``Job.advance`` integrates — ``executed_work``,
  ``attained_service``, ``overhead_remaining``, ``overhead_service``, the
  attribution run legs, ``last_update_time``, and the
  speed x locality x slow effective rate — into slot-indexed numpy
  columns anchored at each job's last mutation, so the per-batch sweep
  becomes a handful of masked array ops plus one scatter loop instead of
  a full Python ``advance`` per job.

Anchor discipline (what keeps the two views consistent):

- a slot's columns are (re)copied **from the job's own fields** at every
  engine mutation (bind / refresh / release ride ``try_start`` /
  ``set_speed`` / ``resize`` / ``migrate`` / net & straggler re-pricing /
  warning overhead / ``preempt`` / ``_finish`` / ``_revoke``), at which
  point the job is integrated to ``sim.now`` — the anchor time IS
  ``job.last_update_time``;
- :meth:`sync_all` evaluates each column **absolutely** from its anchor
  (never incrementally) and scatters into the job fields, so repeated
  syncs between mutations are idempotent and the arrays are a pure
  derived cache — the Job fields remain the single source of truth.

Slots are dense (swap-remove on release) so the vector ops run on a
contiguous prefix; capacity growth doubles and is the only "re-pack"
(``ledger_rebuild`` miss in the ISSUE 10 cache-telemetry family — slot
reuse within capacity is a hit).
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np

from gpuschedule_tpu.sim.job import Job

# column names, in bind/refresh copy order (one numpy float64 array each)
_COLUMNS = (
    "t0",       # anchor time == job.last_update_time at the last mutation
    "work",     # executed_work at anchor
    "service",  # attained_service at anchor
    "ovsvc",    # overhead_service at anchor
    "ov",       # overhead_remaining at anchor
    "eff",      # speed * locality_factor * slow_factor (the advance product)
    "chips",    # allocated_chips (float64: int*float == float*float exactly)
    "speed",    # components kept for the attribution leg split
    "loc",
    "slow",
    "cw",       # ckpt_write_s (0 = unpriced writes)
    "ce",       # ckpt_every (inf = unpriced; the division then yields 0)
)
# attribution run-leg anchor columns (armed only when attribution is on)
_LEG_COLUMNS = ("lw", "lp", "ln", "ls", "lo")
_LEG_KEYS = ("work", "policy-share", "net-degraded", "straggler", "overhead")

_INITIAL_CAPACITY = 64

# Below this many slotted jobs the masked-array sync costs more than it
# saves (a dozen array slices + tolist scatter vs a handful of plain
# ``advance`` calls), so :meth:`JobLedger.sync_all` falls back to the
# scalar loop.  Both paths bring the fields to the same reals (advance
# is segment-exact; the columns stay anchored at the last mutation
# either way), so the cutover moves only float summation order — inside
# the v2 closure contract, and deterministic per replay since the
# running count is.  Measured on the 1k-job / 64-chip DLAS overhead
# world: vector-always was ~2.4x the v1 advance sweep; with the cutover
# the same world runs at parity.
SCALAR_CUTOVER = 32


class JobLedger:
    """Slot-indexed numpy mirror of the running set's accounting state.

    ``vector=False`` (a ``reads_progress=False`` policy) keeps the ledger
    as a pure marker — no arrays, no per-mutation work — because the lazy
    path needs nothing synced between mutations.  ``vector=True``
    maintains the columns and serves :meth:`sync_all` as the engine's
    per-batch advance replacement.
    """

    def __init__(self, *, attribution: bool = False, vector: bool = True,
                 capacity: int = _INITIAL_CAPACITY):
        self.attribution = bool(attribution)
        self.vector = bool(vector)
        self.rebuild_hits = 0    # binds/releases served within capacity
        self.rebuild_misses = 0  # capacity growth (the only re-pack)
        self._n = 0
        self._slots: Dict[int, int] = {}      # id(job) -> slot
        self._jobs: List[Job] = []            # dense, slot-indexed
        self._cap = 0
        if self.vector:
            self._alloc(max(1, int(capacity)))

    # ------------------------------------------------------------------ #
    # slot lifecycle (engine mutation sites)

    def _alloc(self, cap: int) -> None:
        for name in _COLUMNS:
            old = getattr(self, "_" + name, None)
            arr = np.zeros(cap, dtype=np.float64)
            if old is not None:
                arr[: self._n] = old[: self._n]
            setattr(self, "_" + name, arr)
        if self.attribution:
            for name in _LEG_COLUMNS:
                old = getattr(self, "_" + name, None)
                arr = np.zeros(cap, dtype=np.float64)
                if old is not None:
                    arr[: self._n] = old[: self._n]
                setattr(self, "_" + name, arr)
        self._cap = cap

    def _fill(self, slot: int, job: Job) -> None:
        self._t0[slot] = job.last_update_time
        self._work[slot] = job.executed_work
        self._service[slot] = job.attained_service
        self._ovsvc[slot] = job.overhead_service
        self._ov[slot] = job.overhead_remaining
        self._eff[slot] = job.speed * job.locality_factor * job.slow_factor
        self._chips[slot] = job.allocated_chips
        self._speed[slot] = job.speed
        self._loc[slot] = job.locality_factor
        self._slow[slot] = job.slow_factor
        if job.ckpt_write_s > 0.0 and 0.0 < job.ckpt_every < math.inf:
            self._cw[slot] = job.ckpt_write_s
            self._ce[slot] = job.ckpt_every
        else:
            self._cw[slot] = 0.0
            self._ce[slot] = math.inf
        if self.attribution:
            a = job.attrib or {}
            for name, key in zip(_LEG_COLUMNS, _LEG_KEYS):
                getattr(self, "_" + name)[slot] = a.get(key, 0.0)

    def bind(self, job: Job) -> None:
        """Assign a slot to a newly-running job (fields already final and
        integrated to ``sim.now``)."""
        if not self.vector:
            return
        n = self._n
        if n == self._cap:
            self.rebuild_misses += 1
            self._alloc(self._cap * 2)
        else:
            self.rebuild_hits += 1
        self._slots[id(job)] = n
        if n == len(self._jobs):
            self._jobs.append(job)
        else:
            self._jobs[n] = job
        self._n = n + 1
        self._fill(n, job)

    def refresh(self, job: Job) -> None:
        """Re-anchor a running job after a mutation changed any of its
        rates/overhead/legs (the job is integrated to ``sim.now``)."""
        if not self.vector:
            return
        slot = self._slots.get(id(job))
        if slot is not None:
            self._fill(slot, job)

    def release(self, job: Job) -> None:
        """Drop a job leaving the running set (swap-remove keeps the
        columns dense; the moved job keeps its anchor values)."""
        if not self.vector:
            return
        slot = self._slots.pop(id(job), None)
        if slot is None:
            return
        self.rebuild_hits += 1
        last = self._n - 1
        if slot != last:
            moved = self._jobs[last]
            self._jobs[slot] = moved
            self._slots[id(moved)] = slot
            for name in _COLUMNS:
                arr = getattr(self, "_" + name)
                arr[slot] = arr[last]
            if self.attribution:
                for name in _LEG_COLUMNS:
                    arr = getattr(self, "_" + name)
                    arr[slot] = arr[last]
        self._n = last

    # ------------------------------------------------------------------ #
    # the per-batch vectorized advance (reads_progress policies)

    def sync_all(self, t: float) -> None:
        """Bring every slotted job's fields to ``t`` — the masked-array
        replacement for the v1 per-batch ``advance`` sweep.  Absolute
        evaluation from each slot's anchor; anchors are NOT moved (only a
        mutation re-anchors), so calling this once per batch re-derives,
        never re-accumulates."""
        n = self._n
        if n == 0:
            return
        jobs = self._jobs
        if n < SCALAR_CUTOVER:
            # small running set: the plain per-job advance is cheaper
            # than the numpy setup (see SCALAR_CUTOVER); anchors stay
            # put, so later vector syncs still evaluate absolutely
            for i in range(n):
                jobs[i].advance(t)
            return
        ov0 = self._ov[:n]
        eff = self._eff[:n]
        chips = self._chips[:n]
        dt = t - self._t0[:n]
        overheady = bool(ov0.any())
        priced = bool(self._cw[:n].any())
        if not overheady and not priced:
            run = dt
            burned = write = None
        else:
            burned = np.minimum(ov0, dt)
            rem = dt - burned
            if priced:
                pw = eff * self._cw[:n]
                write = rem * (pw / (self._ce[:n] + pw))
                run = rem - write
            else:
                write = None
                run = rem
        w = (self._work[:n] + eff * run).tolist()
        s = (self._service[:n] + chips * run).tolist()
        if not overheady and not priced and not self.attribution:
            for i in range(n):
                job = jobs[i]
                job.executed_work = w[i]
                job.attained_service = s[i]
                job.last_update_time = t
            return
        burned_l = burned.tolist() if burned is not None else None
        write_l = write.tolist() if write is not None else None
        if overheady or priced:
            wr = write if write is not None else 0.0
            bu = burned if burned is not None else 0.0
            ov_l = (ov0 - bu).tolist() if burned is not None else None
            ovsvc_l = ((self._ovsvc[:n] + chips * bu) + chips * wr).tolist()
        else:
            ov_l = ovsvc_l = None
        if self.attribution:
            speed = self._speed[:n]
            d_work = eff * run
            d_pol = (1.0 - speed) * run
            d_net = speed * (1.0 - self._loc[:n]) * run
            d_slow = speed * self._loc[:n] * (1.0 - self._slow[:n]) * run
            legs_d = [d_work.tolist(), d_pol.tolist(), d_net.tolist(),
                      d_slow.tolist()]
            legs_v = [(self._lw[:n] + d_work).tolist(),
                      (self._lp[:n] + d_pol).tolist(),
                      (self._ln[:n] + d_net).tolist(),
                      (self._ls[:n] + d_slow).tolist()]
            lo = self._lo[:n]
        for i in range(n):
            job = jobs[i]
            job.executed_work = w[i]
            job.attained_service = s[i]
            job.last_update_time = t
            d_over = 0.0
            if burned_l is not None:
                d_over += burned_l[i]
            if write_l is not None:
                d_over += write_l[i]
            if d_over:
                if ov_l is not None:
                    job.overhead_remaining = ov_l[i]
                job.overhead_service = ovsvc_l[i]
            if self.attribution:
                a = job.attrib
                for k, (dl, vl) in enumerate(zip(legs_d, legs_v)):
                    if dl[i]:
                        a[_LEG_KEYS[k]] = vl[i]
                if d_over:
                    a["overhead"] = float(lo[i]) + d_over

    def cache_stats(self) -> Dict[str, Dict[str, int]]:
        """``ledger_rebuild`` counters for the unified cache-telemetry
        family (ISSUE 10): slot churn served in place vs array growth."""
        return {
            "ledger_rebuild": {
                "hit": self.rebuild_hits,
                "miss": self.rebuild_misses,
            },
        }
