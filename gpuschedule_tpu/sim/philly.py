"""Microsoft Philly trace ingestion.

The reference replays the Philly cluster trace (SURVEY.md §2 "Trace data";
BASELINE.json configs #2/#5).  The published trace (Philly ATC'19 [P])
records, per job: an id, the virtual cluster (vc), a submission timestamp,
the requested GPU count, the run duration, and a completion status in
{Pass, Killed, Failed} — a faithful replayer must surface those statuses
as terminal states rather than treating every job as successful
(SURVEY.md §5 "Failure detection").

Two TPU-specific concerns live here, at ingestion (SURVEY.md §7 "Philly
trace fidelity"):

- **#GPU → slice mapping**: Philly gang sizes are arbitrary ints (1, 2,
  3, 5, 8, 24, ...); TPU slices are power-of-two sub-meshes.  Requests
  are rounded UP to the next valid slice size — capacity is never taken
  away from a job — with the raw GPU count kept in ``job.sched
  ["philly_num_gpus"]`` so analysis can compare against the original
  workload.  Jobs larger than ``max_chips`` (one pod by default) are
  clamped to it: the reference cluster ran jobs up to full-rack size and
  a slice cannot span pods.
- **Timestamps**: submission times may be absolute datetimes or float
  seconds; both parse to seconds relative to the trace origin so replay
  starts at t=0.

No reference file:line citations possible (/root/reference is an empty
mount — SURVEY.md §0).
"""

from __future__ import annotations

import csv
import random
from datetime import datetime, timezone
from pathlib import Path
from typing import List, Optional

from gpuschedule_tpu.cluster.tpu import next_pow2
from gpuschedule_tpu.sim.job import Job

# Philly-schema CSV columns.  Aliases cover the column spellings that
# appear across published derivatives of the trace.
PHILLY_FIELDS = ["jobid", "status", "vc", "submitted_time", "num_gpus", "duration"]
_ALIASES = {
    "jobid": ("jobid", "job_id", "id"),
    "status": ("status", "state"),
    "vc": ("vc", "user", "queue"),
    "submitted_time": ("submitted_time", "submit_time", "submitted"),
    "num_gpus": ("num_gpus", "num_gpu", "gpus"),
    "duration": ("duration", "run_time", "runtime"),
}

_TIME_FORMATS = ("%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S")

# Philly statuses (case-insensitive) -> native trace statuses.
_STATUS = {"pass": "Pass", "killed": "Killed", "failed": "Failed"}


def _parse_time(raw: str) -> float:
    """Float seconds, or a datetime string converted to epoch seconds."""
    try:
        return float(raw)
    except ValueError:
        pass
    for fmt in _TIME_FORMATS:
        try:
            # UTC, not host-local: a naive .timestamp() shifts across DST
            # transitions and varies by machine, distorting replay spacing
            return datetime.strptime(raw, fmt).replace(tzinfo=timezone.utc).timestamp()
        except ValueError:
            continue
    raise ValueError(f"unparseable submitted_time {raw!r}")


def _get(row: dict, field: str) -> Optional[str]:
    for alias in _ALIASES[field]:
        if alias in row and row[alias] not in (None, ""):
            return row[alias]
    return None


def load_philly_csv(
    path: str | Path,
    *,
    max_chips: int = 256,
    model_name: str = "transformer-small",
) -> List[Job]:
    """Parse a Philly-schema CSV into Jobs, mapped onto valid slice sizes.

    ``max_chips`` caps a single gang at one pod (BASELINE.json's v5p-256
    replay target).  Submission times are shifted so the earliest job
    submits at t=0.
    """
    rows = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            jobid = _get(row, "jobid")
            raw_time = _get(row, "submitted_time")
            duration = _get(row, "duration")
            if jobid is None or raw_time is None or duration is None:
                continue  # malformed row: trace derivatives contain a few
            status = _STATUS.get((_get(row, "status") or "pass").lower())
            if status is None:
                continue  # unknown status (e.g. still-running at capture)
            try:
                parsed_time = _parse_time(raw_time)
                num_gpus = int(float(_get(row, "num_gpus") or 1))
                parsed_duration = max(1.0, float(duration))
            except ValueError:
                continue  # unparseable values are malformed rows too
            if num_gpus < 1:
                num_gpus = 1
            rows.append(
                (
                    jobid,
                    parsed_time,
                    num_gpus,
                    parsed_duration,
                    status,
                    _get(row, "vc") or "",
                )
            )
    if not rows:
        return []
    origin = min(r[1] for r in rows)
    # clamp to the largest power of two <= max_chips: a raw min() against a
    # non-pow2 cap would produce a size no slice shape can realize
    cap = 1 << (max(1, max_chips).bit_length() - 1)
    jobs: List[Job] = []
    for jobid, t, num_gpus, duration, status, vc in rows:
        chips = min(next_pow2(num_gpus), cap)
        job = Job(
            job_id=str(jobid),
            submit_time=round(t - origin, 3),
            num_chips=chips,
            duration=duration,
            model_name=model_name,
            status=status,
            user=vc,
        )
        job.sched["philly_num_gpus"] = num_gpus
        jobs.append(job)
    jobs.sort(key=lambda j: (j.submit_time, j.job_id))
    return jobs


def save_philly_csv(jobs, path: str | Path) -> None:
    """Write jobs in the Philly schema (used for checked-in samples)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(PHILLY_FIELDS)
        for j in jobs:
            w.writerow(
                [
                    j.job_id,
                    j.status,
                    j.user,
                    j.submit_time,
                    j.sched.get("philly_num_gpus", j.num_chips),
                    j.duration,
                ]
            )


def generate_philly_like_trace(
    num_jobs: int,
    *,
    seed: int = 0,
    arrival_rate: float = 1.0 / 45.0,
) -> List[Job]:
    """Synthetic trace with the Philly workload's published shape [P]:

    - gang sizes heavily skewed to 1 GPU with a distributed tail, drawn
      from the raw (non-pow2) sizes Philly records so the slice-mapping
      path is exercised;
    - heavy-tailed durations (lognormal, minutes to days);
    - ~30% of jobs not Passing (Killed/Failed mix);
    - bursty arrivals (exponential with daytime burst factor).
    """
    rng = random.Random(seed)
    # (num_gpus, weight): raw Philly-style sizes incl. non-powers of two
    size_vals, size_weights = zip(*[
        (1, 0.55), (2, 0.12), (3, 0.03), (4, 0.10), (5, 0.02),
        (8, 0.10), (12, 0.02), (16, 0.04), (24, 0.01), (32, 0.01),
    ])
    status_vals, status_weights = zip(*[("Pass", 0.69), ("Killed", 0.17), ("Failed", 0.14)])
    jobs: List[Job] = []
    t = 0.0
    for i in range(num_jobs):
        burst = 0.4 if (int(t) // 3600) % 24 < 12 else 1.6  # bursty half-days
        t += rng.expovariate(arrival_rate) * burst
        num_gpus = rng.choices(size_vals, size_weights)[0]
        duration = max(60.0, rng.lognormvariate(7.0, 1.6))  # median ~18min
        status = rng.choices(status_vals, status_weights)[0]
        job = Job(
            job_id=f"phil{i:05d}",
            submit_time=round(t, 3),
            num_chips=next_pow2(num_gpus),
            duration=round(duration, 3),
            model_name="transformer-small",
            status=status,
            user=f"vc{rng.randrange(6)}",
        )
        job.sched["philly_num_gpus"] = num_gpus
        jobs.append(job)
    return jobs
