"""Microsoft Philly trace ingestion.

The reference replays the Philly cluster trace (SURVEY.md §2 "Trace data";
BASELINE.json configs #2/#5).  The published trace (Philly ATC'19 [P])
records, per job: an id, the virtual cluster (vc), a submission timestamp,
the requested GPU count, the run duration, and a completion status in
{Pass, Killed, Failed} — a faithful replayer must surface those statuses
as terminal states rather than treating every job as successful
(SURVEY.md §5 "Failure detection").

Two TPU-specific concerns live here, at ingestion (SURVEY.md §7 "Philly
trace fidelity"):

- **#GPU → slice mapping**: Philly gang sizes are arbitrary ints (1, 2,
  3, 5, 8, 24, ...); TPU slices are power-of-two sub-meshes.  Requests
  are rounded UP to the next valid slice size — capacity is never taken
  away from a job — with the raw GPU count kept in ``job.sched
  ["philly_num_gpus"]`` so analysis can compare against the original
  workload.  Jobs larger than ``max_chips`` (one pod by default) are
  clamped to it: the reference cluster ran jobs up to full-rack size and
  a slice cannot span pods.
- **Timestamps**: submission times may be absolute datetimes or float
  seconds; both parse to seconds relative to the trace origin so replay
  starts at t=0.

No reference file:line citations possible (/root/reference is an empty
mount — SURVEY.md §0).
"""

from __future__ import annotations

import csv
import math
import random
from datetime import datetime, timezone
from pathlib import Path
from typing import List, Optional

from gpuschedule_tpu.cluster.tpu import next_pow2
from gpuschedule_tpu.sim.job import Job

# Philly-schema CSV columns.  Aliases cover the column spellings that
# appear across published derivatives of the trace.
PHILLY_FIELDS = ["jobid", "status", "vc", "submitted_time", "num_gpus", "duration"]
_ALIASES = {
    "jobid": ("jobid", "job_id", "id"),
    "status": ("status", "state"),
    "vc": ("vc", "user", "queue"),
    "submitted_time": ("submitted_time", "submit_time", "submitted"),
    "num_gpus": ("num_gpus", "num_gpu", "gpus"),
    "duration": ("duration", "run_time", "runtime"),
}

_TIME_FORMATS = ("%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S")

# Philly statuses (case-insensitive) -> native trace statuses.
_STATUS = {"pass": "Pass", "killed": "Killed", "failed": "Failed"}


def _parse_time(raw: str) -> float:
    """Float seconds, or a datetime string converted to epoch seconds."""
    try:
        return float(raw)
    except ValueError:
        pass
    for fmt in _TIME_FORMATS:
        try:
            # UTC, not host-local: a naive .timestamp() shifts across DST
            # transitions and varies by machine, distorting replay spacing
            return datetime.strptime(raw, fmt).replace(tzinfo=timezone.utc).timestamp()
        except ValueError:
            continue
    raise ValueError(f"unparseable submitted_time {raw!r}")


def _get(row: dict, field: str) -> Optional[str]:
    for alias in _ALIASES[field]:
        if alias in row and row[alias] not in (None, ""):
            return row[alias]
    return None


def load_philly_csv(
    path: str | Path,
    *,
    max_chips: int = 256,
    model_name: str = "transformer-small",
    num_pods: int = 1,
) -> List[Job]:
    """Parse a Philly-schema CSV into Jobs, mapped onto valid slice sizes.

    ``max_chips`` is the single-slice cap — one pod (BASELINE.json's
    v5p-256 replay target).  With ``num_pods > 1``, gangs bigger than a
    pod are no longer clamped: they round up to whole-pod multiples
    (multislice over DCN, round-3 verdict missing #5), capped at the
    fleet.  Submission times are shifted so the earliest job submits at
    t=0.
    """
    rows = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            jobid = _get(row, "jobid")
            raw_time = _get(row, "submitted_time")
            duration = _get(row, "duration")
            if jobid is None or raw_time is None or duration is None:
                continue  # malformed row: trace derivatives contain a few
            status = _STATUS.get((_get(row, "status") or "pass").lower())
            if status is None:
                continue  # unknown status (e.g. still-running at capture)
            try:
                parsed_time = _parse_time(raw_time)
                num_gpus = int(float(_get(row, "num_gpus") or 1))
                parsed_duration = max(1.0, float(duration))
            except ValueError:
                continue  # unparseable values are malformed rows too
            if num_gpus < 1:
                num_gpus = 1
            rows.append(
                (
                    jobid,
                    parsed_time,
                    num_gpus,
                    parsed_duration,
                    status,
                    _get(row, "vc") or "",
                )
            )
    if not rows:
        return []
    origin = min(r[1] for r in rows)
    # clamp to the largest power of two <= max_chips: a raw min() against a
    # non-pow2 cap would produce a size no slice shape can realize
    cap = 1 << (max(1, max_chips).bit_length() - 1)
    jobs: List[Job] = []
    for jobid, t, num_gpus, duration, status, vc in rows:
        chips = next_pow2(num_gpus)
        if chips > cap:
            # whole-pod multiples over DCN when the fleet has them,
            # clamped to the fleet; single-pod fleets clamp as before
            pods_needed = min(max(1, num_pods), math.ceil(num_gpus / cap))
            chips = pods_needed * cap
        job = Job(
            job_id=str(jobid),
            submit_time=round(t - origin, 3),
            num_chips=chips,
            duration=duration,
            model_name=model_name,
            status=status,
            user=vc,
        )
        job.sched["philly_num_gpus"] = num_gpus
        jobs.append(job)
    jobs.sort(key=lambda j: (j.submit_time, j.job_id))
    return jobs


def save_philly_csv(jobs, path: str | Path) -> None:
    """Write jobs in the Philly schema (used for checked-in samples)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(PHILLY_FIELDS)
        for j in jobs:
            w.writerow(
                [
                    j.job_id,
                    j.status,
                    j.user,
                    j.submit_time,
                    j.sched.get("philly_num_gpus", j.num_chips),
                    j.duration,
                ]
            )


# ----------------------------------------------------------------------- #
# Calibration constants for the synthetic Philly-shaped generator.
#
# Provenance tags (this environment has no egress, so the published trace
# itself cannot be fetched — SURVEY.md §0):
#   [published] — exact aggregate of the released philly-traces dataset /
#                 the ATC'19 paper (Jeon et al., "Analysis of Large-Scale
#                 Multi-Tenant GPU Clusters for DNN Training Workloads").
#   [modeled]   — chosen to match the paper's qualitative/aggregate
#                 descriptions where exact per-bin values are not
#                 reproducible offline; each constant states what it is
#                 matching.

# [published] Completion-status mix: the released trace holds 96,260 jobs —
# 66,961 Pass, 18,204 Killed, 11,095 Failed ("about one third of jobs do
# not complete successfully", ATC'19 §3).
_STATUS_MIX = (("Pass", 0.6956), ("Killed", 0.1891), ("Failed", 0.1153))

# [published] Arrival rate: 96,260 jobs over the ~75-day trace window
# (Oct–Dec 2017) -> mean inter-arrival ~67 s.
PHILLY_MEAN_INTERARRIVAL_S = 67.3

# [modeled] Request-size mix by job count, matching ATC'19 §3.1/Fig. 2's
# shape: the large majority of jobs are single-GPU; multi-GPU jobs cluster
# at powers of two (2/4/8/16) with rare whales at 32/64 that nevertheless
# dominate GPU-hours; awkward raw sizes (3, 5, 12, 24) occur in the real
# trace and are retained to exercise the #GPU→slice mapping.
_SIZE_MIX = (
    (1, 0.70), (2, 0.08), (4, 0.07), (8, 0.06), (16, 0.04),
    (32, 0.015), (64, 0.005), (3, 0.01), (5, 0.01), (12, 0.005), (24, 0.005),
)

# [modeled] Duration distribution: lognormal with median 15 min and a heavy
# tail reaching multiple days — matching ATC'19's reported median job
# runtime in the tens of minutes with the top few percent of jobs consuming
# most GPU-time.  sigma=1.8 puts p99 around 16 h and the extreme tail at
# days.
_DUR_MEDIAN_S = 900.0
_DUR_SIGMA = 1.8
# [modeled] Status-duration correlation, ATC'19 §4 failure analysis: a
# large share of failures happen early (programming/config errors killed
# within minutes), while user-issued kills tend to land on long-running
# jobs the user gave up on.
_FAILED_EARLY_FRAC = 0.55          # failures that die in the first minutes
_FAILED_EARLY_MEDIAN_S = 120.0
_KILLED_DURATION_SCALE = 1.5

# [modeled] Diurnal/weekly load shape, ATC'19 §3/Fig. 3: submission rate
# peaks during working hours and dips overnight and on weekends.  The trace
# origin (t=0) is taken as Monday 00:00.
_DAYTIME_HOURS = range(9, 19)
_DAYTIME_RATE_X = 1.6
_NIGHT_RATE_X = 0.55
_WEEKEND_RATE_X = 0.6


def _arrival_rate_multiplier(t: float) -> float:
    hour = int(t // 3600) % 24
    day = int(t // 86400) % 7
    mult = _DAYTIME_RATE_X if hour in _DAYTIME_HOURS else _NIGHT_RATE_X
    if day >= 5:
        mult *= _WEEKEND_RATE_X
    return mult / _RATE_NORM


# Normalize the diurnal shape so its average over the 168-hour weekly cycle
# is exactly 1 — otherwise the shape would silently drag the realized mean
# rate ~12% off the [published] value the generator promises.
_RATE_NORM = 1.0
_RATE_NORM = sum(_arrival_rate_multiplier(h * 3600.0) for h in range(168)) / 168.0


def generate_philly_like_trace(
    num_jobs: int,
    *,
    seed: int = 0,
    arrival_rate: float = 1.0 / PHILLY_MEAN_INTERARRIVAL_S,
) -> List[Job]:
    """Synthetic trace calibrated to the published Philly workload shape.

    Every distribution constant above carries a ``[published]`` or
    ``[modeled]`` provenance tag; the genuine trace is unfetchable here, so
    this generator is the closest reproducible stand-in: exact on the
    aggregates the paper publishes (status mix, mean arrival rate), modeled
    on the shapes it describes (size skew, heavy-tailed durations,
    early-failure correlation, diurnal load).

    Deterministic per (num_jobs, seed): checked-in artifacts
    (``data/philly_sample.csv``, ``data/philly_10k.csv``) regenerate
    byte-identically via ``cli gen-trace --philly-like``.
    """
    rng = random.Random(seed)
    size_vals, size_weights = zip(*_SIZE_MIX)
    status_vals, status_weights = zip(*_STATUS_MIX)
    mu = math.log(_DUR_MEDIAN_S)
    mu_fail_early = math.log(_FAILED_EARLY_MEDIAN_S)
    jobs: List[Job] = []
    t = 0.0
    for i in range(num_jobs):
        # thinning by the diurnal multiplier: the local rate is
        # arrival_rate * multiplier, so the expected gap divides by it
        t += rng.expovariate(arrival_rate) / _arrival_rate_multiplier(t)
        num_gpus = rng.choices(size_vals, size_weights)[0]
        status = rng.choices(status_vals, status_weights)[0]
        if status == "Failed" and rng.random() < _FAILED_EARLY_FRAC:
            duration = rng.lognormvariate(mu_fail_early, 1.2)
        else:
            duration = rng.lognormvariate(mu, _DUR_SIGMA)
            if status == "Killed":
                duration *= _KILLED_DURATION_SCALE
        duration = max(30.0, duration)
        job = Job(
            job_id=f"phil{i:05d}",
            submit_time=round(t, 3),
            num_chips=next_pow2(num_gpus),
            duration=round(duration, 3),
            model_name="transformer-small",
            status=status,
            user=f"vc{rng.randrange(6)}",
        )
        job.sched["philly_num_gpus"] = num_gpus
        jobs.append(job)
    return jobs
