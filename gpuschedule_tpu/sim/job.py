"""Job model and progress accounting.

Mirrors the reference's per-job trace record (SURVEY.md §2 "Job model + trace
loader": id, submit_time, num_gpu, iterations, model, duration) with the GPU
request generalized to a TPU chip request, plus the runtime accounting every
policy needs:

- ``executed_work`` / ``remaining_work`` in *reference-speed seconds* — the
  progress currency for FIFO/SRTF and for deadline prediction;
- ``attained_service`` in *chip-seconds* — the Tiresias-LAS priority currency
  (SURVEY.md §2 "Policy: Tiresias LAS/DLAS");
- ``speed`` — the instantaneous progress rate.  1.0 means "running at the
  trace-declared allocation"; Optimus-style elastic policies set it from the
  fitted goodput curve when they grow/shrink a job (SURVEY.md §3.2);
- ``overhead_remaining`` — modeled preemption/migration cost: seconds of run
  time that must be burned before real work resumes (Gandiva suspend/resume
  and migration penalties are charged this way, SURVEY.md §3.3 / §5
  "Checkpoint / resume": costs are modeled, not real).
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

# Causal-attribution leg names (ISSUE 5 tentpole).  A job's whole lifetime
# decomposes into these legs, each an exact cumulative float in
# ``Job.attrib`` when attribution is armed:
#
# - WAIT_CAUSES blame queued/suspended intervals.  The cause is decided
#   once, when the interval *starts* (the engine's blame rule at arrival /
#   preempt / revoke time), and the whole interval is charged to it:
#   ``capacity`` (not enough healthy chips existed), ``fault-outage``
#   (enough chips existed but some were health-masked), ``net-outage``
#   (enough chips existed but they are held by gangs stalled at rate 0
#   by hard DCN-uplink outages — the capacity shortage IS the link
#   outage; distinct from the running ``net-degraded`` leg, which would
#   collide with it in the shared legs dict), ``admission`` (enough
#   nominally-free healthy chips existed — the delay is geometry or
#   scheduler ordering, not resource shortage), ``policy-preempt``
#   (the interval began with a policy preemption; the preempting rule's
#   machine-parseable code rides on the event).
# - RUN_LEGS split every running second: ``work`` (speed x locality x
#   slow — the reference-speed work-equivalent; sums to ~duration for a
#   finished job), ``policy-share`` ((1-speed) — time-sliced packing /
#   elastic shrink; negative when an elastic grow runs the job *faster*
#   than its trace speed), ``net-degraded`` (speed x (1-locality) —
#   interconnect stretch: DCN contention, static multislice toll, GPU
#   locality tiers), ``straggler`` (speed x locality x (1-slow) — the
#   gang running at a degraded chip's rate, faults/), ``overhead``
#   (modeled restart/migration/restore burn, including priced
#   checkpoint writes).
#
# The analyzer (obs/analyze.py) re-declares these names — the obs layer
# never imports the sim package at module load; tests pin the two equal.
WAIT_CAUSES = (
    "admission", "capacity", "fault-outage", "net-outage", "policy-preempt"
)
RUN_LEGS = ("work", "policy-share", "net-degraded", "straggler", "overhead")


class JobState(enum.Enum):
    """Lifecycle states of a simulated job."""

    PENDING = "pending"        # submitted, waiting for its first/next allocation
    RUNNING = "running"        # holds an allocation, accruing progress
    SUSPENDED = "suspended"    # preempted with resume intent (Gandiva time-slice)
    DONE = "done"              # ran to completion (trace status Pass)
    FAILED = "failed"          # trace-declared failure surfaced at completion
    KILLED = "killed"          # trace-declared kill surfaced at completion
    REJECTED = "rejected"      # admission control: gang size never satisfiable
                               # on this cluster; excluded from JCT aggregates

END_STATES = (JobState.DONE, JobState.FAILED, JobState.KILLED, JobState.REJECTED)

# Hoisted for Job.advance's hot path: the enum attribute lookup is not free
# at millions of calls per replay.
_RUNNING = JobState.RUNNING

# Map of trace-declared completion statuses (Philly schema, SURVEY.md §5
# "Failure detection": a faithful replayer must handle failed/killed jobs) to
# the terminal JobState a job enters once its trace duration has elapsed.
STATUS_TO_END_STATE = {
    "Pass": JobState.DONE,
    "Failed": JobState.FAILED,
    "Killed": JobState.KILLED,
}


@dataclass(slots=True)
class Job:
    """A single trace job.

    Parameters mirror one trace row; everything after ``status`` is runtime
    state owned by the simulation engine.

    ``slots=True`` (ISSUE 9): a million-job trace holds a million of these
    alive for the whole replay, and the per-instance ``__dict__`` roughly
    doubled the footprint; slots also shave the attribute loads off
    :meth:`advance`, the engine's hottest method.  Every runtime attribute
    is a declared field — policies get the ``sched`` dict for scratch
    state, never ad-hoc attributes.
    """

    job_id: str
    submit_time: float
    num_chips: int                      # requested gang size, in TPU chips
    duration: float                     # total service time (s) at requested size
    model_name: str = "transformer-tiny"
    iterations: Optional[int] = None    # optional iteration count (Optimus uses it)
    status: str = "Pass"                # trace-declared outcome: Pass|Failed|Killed
    user: str = ""                      # submitting user/vc (Philly has VCs)
    utilization: float = 1.0            # profiled device utilization in [0,1];
                                        # Gandiva's packing signal (SURVEY.md §3.3)
    sp: int = 1                         # declared sequence-parallel factor: one
    tp: int = 1                         # model replica spans sp*tp*pp chips,
    pp: int = 1                         # and goodput curves resolve to the
                                        # @sp{s}tp{t} / @sp{s}tp{t}pp{p} cache
                                        # variant when set (round-4 verdict #3:
                                        # parallelism-aware curves get a policy
                                        # consumer; pp mirrors the profiler's
                                        # pipeline-mesh keys)
    ckpt_interval: Optional[float] = None
                                        # work-seconds between checkpoints; a
                                        # fault rolls progress back to the last
                                        # multiple (None -> the fault plan's
                                        # RecoveryModel default, faults/)

    # ---- priced recovery (engine-armed from the fault plan, faults/) ----
    ckpt_write_s: float = 0.0           # seconds one periodic checkpoint write
                                        # takes (0 = free writes, the historical
                                        # model; advance() folds the cost into
                                        # the overhead leg when > 0)
    ckpt_every: float = math.inf        # work-seconds between priced writes
                                        # (the resolved checkpoint interval;
                                        # inf with ckpt_write_s=0 keeps the
                                        # write branch cold)
    ckpt_protected: Optional[float] = None
                                        # emergency-checkpoint watermark: work
                                        # protected by the newest warned spot
                                        # checkpoint — the rollback floor rises
                                        # to max(periodic multiple, this)

    # ---- runtime accounting (engine-owned) ----
    state: JobState = JobState.PENDING
    executed_work: float = 0.0          # reference-speed seconds of work done
    attained_service: float = 0.0       # chip-seconds of service received
    speed: float = 0.0                  # policy-set progress rate (0 unless RUNNING)
    locality_factor: float = 1.0        # allocation-quality multiplier set by the
                                        # engine from the granted placement: 1.0 on
                                        # TPU slices (contiguous by construction),
                                        # <1.0 for scattered GPU gangs (NVLink vs
                                        # PCIe vs cross-switch, cluster/gpu.py)
    slow_factor: float = 1.0            # straggler multiplier (faults/): the min
                                        # residual rate over the gang's chips —
                                        # a synchronous gang runs at its slowest
                                        # chip's rate; engine-set from the
                                        # cluster's degrade mask on every bind
    overhead_remaining: float = 0.0     # modeled restart cost still to burn (s)
    allocation: Optional[Any] = None    # cluster allocation handle when RUNNING
    allocated_chips: int = 0            # chips currently held (elastic != num_chips)

    first_start_time: Optional[float] = None
    end_time: Optional[float] = None
    last_update_time: float = 0.0       # progress integrated up to this sim time
    preempt_count: int = 0
    migration_count: int = 0
    fault_count: int = 0                # revocations by hardware faults (faults/)
    lost_work: float = 0.0              # reference-speed seconds rolled back to
                                        # the last checkpoint by fault revocations
    lost_service: float = 0.0           # chip-seconds attributed to rolled-back
                                        # work (goodput decomposition: the share
                                        # of attained_service that produced work
                                        # a fault later erased)
    overhead_service: float = 0.0       # chip-seconds spent burning
                                        # overhead_remaining (modeled restart /
                                        # migration / restore cost) while holding
                                        # chips — the decomposition's third leg
    epoch: int = 0                      # invalidates stale scheduled completions
    arrival_seq: int = 0                # submit-order index assigned by the engine
                                        # (numeric FIFO tie-break; 'j2' < 'j10')
    run_seq: int = 0                    # monotonic ticket stamped at every gang
                                        # start (ISSUE 9): the engine's running
                                        # set iterates in insertion order, which
                                        # is ascending run_seq — so any indexed
                                        # subset (fault victims, multislice
                                        # members) can reproduce the exact sweep
                                        # order of a full running-set scan by
                                        # sorting on this ticket

    # ---- causal attribution (engine-owned, ISSUE 5) ----
    # None keeps the attribution-off path allocation-free and byte-
    # identical; the engine sets it to {} when attribution is armed and
    # legs (WAIT_CAUSES / RUN_LEGS keys, exact cumulative seconds) appear
    # lazily as they first accrue.
    attrib: Optional[Dict[str, float]] = None
    blame_cause: Optional[str] = None   # cause of the open queued interval
    blame_since: float = 0.0            # when that interval started

    # scratch space for policies (queue index, profiling state, ...)
    sched: dict = field(default_factory=dict)

    # what-if placement pin (ISSUE 12): a per-job allocation hint the
    # engine merges into every try_start for this job — how an injected
    # "admit this job WHERE?" candidate forces its placement.  None (the
    # default) keeps try_start's hint handling byte-identical.
    pin_hint: Optional[dict] = None

    # ------------------------------------------------------------------ #

    @property
    def remaining_work(self) -> float:
        """Reference-speed seconds of service still owed to this job."""
        return max(0.0, self.duration - self.executed_work)

    @property
    def finished(self) -> bool:
        return self.state in END_STATES

    @property
    def end_state(self) -> JobState:
        """Terminal state declared by the trace for when this job completes."""
        return STATUS_TO_END_STATE.get(self.status, JobState.DONE)

    @property
    def effective_speed(self) -> float:
        """Actual progress rate: policy speed degraded by placement
        quality and any straggler chip in the gang (x1.0 is exact, so
        straggler-free replays keep bit-identical floats)."""
        return self.speed * self.locality_factor * self.slow_factor

    def remaining_runtime(self) -> float:
        """Wall-clock seconds to completion at the current speed (inf if idle)."""
        # effective_speed inlined (same expression as the property, so the
        # division sees bit-identical floats) — this is called twice per
        # completion at fleet scale
        es = self.speed * self.locality_factor * self.slow_factor
        if es <= 0.0:
            return float("inf")
        t = self.overhead_remaining + self.remaining_work / es
        if self.ckpt_write_s > 0.0 and 0.0 < self.ckpt_every < math.inf:
            # priced checkpoint writes stretch the remaining wall time by
            # one write per ckpt_every work-seconds still owed — the same
            # split advance() integrates, so predictions land on the
            # completion instant instead of firing early and re-predicting
            t += self.remaining_work * (self.ckpt_write_s / self.ckpt_every)
        return t

    def _accrue_run_legs(self, a: Dict[str, float], e: float, span: float) -> None:
        """Charge one productive interval's RUN_LEGS split (work +
        policy-share + net-degraded + straggler) into the attribution
        dict — the four-leg arithmetic both :meth:`advance` branches
        (priced-checkpoint-write and plain) used to repeat verbatim
        (ISSUE 11 satellite).  Expressions and dict insertion order are
        identical to the historical inline copies, so every attribution
        snapshot stays byte-for-byte (pinned by the closure grid in
        tests/test_attrib.py)."""
        a["work"] = a.get("work", 0.0) + e * span
        if self.speed != 1.0:
            a["policy-share"] = (
                a.get("policy-share", 0.0) + (1.0 - self.speed) * span
            )
        if self.locality_factor != 1.0:
            a["net-degraded"] = (
                a.get("net-degraded", 0.0)
                + self.speed * (1.0 - self.locality_factor) * span
            )
        if self.slow_factor != 1.0:
            a["straggler"] = (
                a.get("straggler", 0.0)
                + self.speed * self.locality_factor
                * (1.0 - self.slow_factor) * span
            )

    def advance(self, now: float) -> None:
        """Integrate progress from ``last_update_time`` to ``now``.

        Overhead (modeled suspend/resume or migration cost) is burned first at
        wall-clock rate; only the remainder of the interval accrues work and
        attained service.

        This is the engine's hottest method (every running job, every
        event batch): the running-state constant is hoisted and the
        effective-speed product inlined (same expression as the property,
        so every float is bit-identical) to keep the per-call overhead
        down at Philly scale.

        The arithmetic is **segment-exact for any ``dt``**: between two
        engine mutations a running job's rates are constant, so one call
        spanning the whole gap computes the same reals as v1's
        chunk-per-batch calls (the floats differ only in summation
        order).  The v2 accounting mode (ISSUE 11) leans on exactly this
        — it skips the per-batch sweep and advances each job lazily at
        its next mutation/read point, under the closure (not
        byte-identity) contract.
        """
        dt = now - self.last_update_time
        if dt < 0:
            raise ValueError(
                f"time went backwards for {self.job_id}: {self.last_update_time} -> {now}"
            )
        self.last_update_time = now
        if self.state is not _RUNNING or dt == 0.0:
            return
        if self.overhead_remaining > 0.0:
            burned = min(self.overhead_remaining, dt)
            self.overhead_remaining -= burned
            # chips are occupied but produce no work while overhead burns:
            # the restart-overhead leg of the goodput decomposition
            self.overhead_service += self.allocated_chips * burned
            if self.attrib is not None:
                self.attrib["overhead"] = self.attrib.get("overhead", 0.0) + burned
            dt -= burned
        if dt > 0.0:
            if self.ckpt_write_s > 0.0 and 0.0 < self.ckpt_every < math.inf:
                # Priced checkpoint writes (faults/recovery.py): the job
                # alternates ckpt_every work-seconds of progress with one
                # ckpt_write_s write, so the steady-state write share of
                # wall time is e*w / (every + e*w) at effective speed e.
                # The write share occupies chips without producing work —
                # the overhead leg — exactly like restore burn.  Gated on
                # the knob so free-write replays keep the branchless
                # arithmetic below bit for bit.
                e = self.effective_speed
                write = dt * (e * self.ckpt_write_s) / (
                    self.ckpt_every + e * self.ckpt_write_s
                )
                run = dt - write
                self.executed_work += e * run
                self.attained_service += self.allocated_chips * run
                self.overhead_service += self.allocated_chips * write
                if self.attrib is not None:
                    a = self.attrib
                    a["overhead"] = a.get("overhead", 0.0) + write
                    self._accrue_run_legs(a, e, run)
                return
            e = self.speed * self.locality_factor * self.slow_factor
            self.executed_work += e * dt
            self.attained_service += self.allocated_chips * dt
            if self.attrib is not None:
                # RUN_LEGS split of this productive interval: work +
                # policy-share + net-degraded + straggler == dt in real
                # arithmetic (s*l*f + (1-s) + s*(1-l) + s*l*(1-f) == 1);
                # the decomposition's own ordered sum absorbs the float
                # dust
                self._accrue_run_legs(self.attrib, e, dt)

    def jct(self) -> Optional[float]:
        """Job completion time (end - submit), once finished."""
        if self.end_time is None:
            return None
        return self.end_time - self.submit_time

    def queueing_delay(self) -> Optional[float]:
        """Delay between submission and first start."""
        if self.first_start_time is None:
            return None
        return self.first_start_time - self.submit_time

    def slowdown(self) -> Optional[float]:
        """JCT relative to a dedicated-cluster run (the trace duration at
        the requested gang).  1.0 = ran immediately with no interference;
        the fairness policies (Themis) minimize the tail of this ratio."""
        j = self.jct()
        if j is None:
            return None
        return j / max(self.duration, 1e-9)

    def __repr__(self) -> str:  # compact for debugging/log lines
        return (
            f"Job({self.job_id}, chips={self.num_chips}, state={self.state.value}, "
            f"work={self.executed_work:.1f}/{self.duration:.1f})"
        )
