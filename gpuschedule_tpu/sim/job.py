"""Job model and progress accounting.

Mirrors the reference's per-job trace record (SURVEY.md §2 "Job model + trace
loader": id, submit_time, num_gpu, iterations, model, duration) with the GPU
request generalized to a TPU chip request, plus the runtime accounting every
policy needs:

- ``executed_work`` / ``remaining_work`` in *reference-speed seconds* — the
  progress currency for FIFO/SRTF and for deadline prediction;
- ``attained_service`` in *chip-seconds* — the Tiresias-LAS priority currency
  (SURVEY.md §2 "Policy: Tiresias LAS/DLAS");
- ``speed`` — the instantaneous progress rate.  1.0 means "running at the
  trace-declared allocation"; Optimus-style elastic policies set it from the
  fitted goodput curve when they grow/shrink a job (SURVEY.md §3.2);
- ``overhead_remaining`` — modeled preemption/migration cost: seconds of run
  time that must be burned before real work resumes (Gandiva suspend/resume
  and migration penalties are charged this way, SURVEY.md §3.3 / §5
  "Checkpoint / resume": costs are modeled, not real).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

# Causal-attribution leg names (ISSUE 5 tentpole).  A job's whole lifetime
# decomposes into these legs, each an exact cumulative float in
# ``Job.attrib`` when attribution is armed:
#
# - WAIT_CAUSES blame queued/suspended intervals.  The cause is decided
#   once, when the interval *starts* (the engine's blame rule at arrival /
#   preempt / revoke time), and the whole interval is charged to it:
#   ``capacity`` (not enough healthy chips existed), ``fault-outage``
#   (enough chips existed but some were health-masked), ``admission``
#   (enough nominally-free healthy chips existed — the delay is geometry
#   or scheduler ordering, not resource shortage), ``policy-preempt``
#   (the interval began with a policy preemption; the preempting rule's
#   machine-parseable code rides on the event).
# - RUN_LEGS split every running second: ``work`` (speed x locality — the
#   reference-speed work-equivalent; sums to ~duration for a finished
#   job), ``policy-share`` ((1-speed) — time-sliced packing / elastic
#   shrink; negative when an elastic grow runs the job *faster* than its
#   trace speed), ``net-degraded`` (speed x (1-locality) — interconnect
#   stretch: DCN contention, static multislice toll, GPU locality tiers),
#   ``overhead`` (modeled restart/migration/restore burn).
#
# The analyzer (obs/analyze.py) re-declares these names — the obs layer
# never imports the sim package at module load; tests pin the two equal.
WAIT_CAUSES = ("admission", "capacity", "fault-outage", "policy-preempt")
RUN_LEGS = ("work", "policy-share", "net-degraded", "overhead")


class JobState(enum.Enum):
    """Lifecycle states of a simulated job."""

    PENDING = "pending"        # submitted, waiting for its first/next allocation
    RUNNING = "running"        # holds an allocation, accruing progress
    SUSPENDED = "suspended"    # preempted with resume intent (Gandiva time-slice)
    DONE = "done"              # ran to completion (trace status Pass)
    FAILED = "failed"          # trace-declared failure surfaced at completion
    KILLED = "killed"          # trace-declared kill surfaced at completion
    REJECTED = "rejected"      # admission control: gang size never satisfiable
                               # on this cluster; excluded from JCT aggregates

END_STATES = (JobState.DONE, JobState.FAILED, JobState.KILLED, JobState.REJECTED)

# Map of trace-declared completion statuses (Philly schema, SURVEY.md §5
# "Failure detection": a faithful replayer must handle failed/killed jobs) to
# the terminal JobState a job enters once its trace duration has elapsed.
STATUS_TO_END_STATE = {
    "Pass": JobState.DONE,
    "Failed": JobState.FAILED,
    "Killed": JobState.KILLED,
}


@dataclass
class Job:
    """A single trace job.

    Parameters mirror one trace row; everything after ``status`` is runtime
    state owned by the simulation engine.
    """

    job_id: str
    submit_time: float
    num_chips: int                      # requested gang size, in TPU chips
    duration: float                     # total service time (s) at requested size
    model_name: str = "transformer-tiny"
    iterations: Optional[int] = None    # optional iteration count (Optimus uses it)
    status: str = "Pass"                # trace-declared outcome: Pass|Failed|Killed
    user: str = ""                      # submitting user/vc (Philly has VCs)
    utilization: float = 1.0            # profiled device utilization in [0,1];
                                        # Gandiva's packing signal (SURVEY.md §3.3)
    sp: int = 1                         # declared sequence-parallel factor: one
    tp: int = 1                         # model replica spans sp*tp*pp chips,
    pp: int = 1                         # and goodput curves resolve to the
                                        # @sp{s}tp{t} / @sp{s}tp{t}pp{p} cache
                                        # variant when set (round-4 verdict #3:
                                        # parallelism-aware curves get a policy
                                        # consumer; pp mirrors the profiler's
                                        # pipeline-mesh keys)
    ckpt_interval: Optional[float] = None
                                        # work-seconds between checkpoints; a
                                        # fault rolls progress back to the last
                                        # multiple (None -> the fault plan's
                                        # RecoveryModel default, faults/)

    # ---- runtime accounting (engine-owned) ----
    state: JobState = JobState.PENDING
    executed_work: float = 0.0          # reference-speed seconds of work done
    attained_service: float = 0.0       # chip-seconds of service received
    speed: float = 0.0                  # policy-set progress rate (0 unless RUNNING)
    locality_factor: float = 1.0        # allocation-quality multiplier set by the
                                        # engine from the granted placement: 1.0 on
                                        # TPU slices (contiguous by construction),
                                        # <1.0 for scattered GPU gangs (NVLink vs
                                        # PCIe vs cross-switch, cluster/gpu.py)
    overhead_remaining: float = 0.0     # modeled restart cost still to burn (s)
    allocation: Optional[Any] = None    # cluster allocation handle when RUNNING
    allocated_chips: int = 0            # chips currently held (elastic != num_chips)

    first_start_time: Optional[float] = None
    end_time: Optional[float] = None
    last_update_time: float = 0.0       # progress integrated up to this sim time
    preempt_count: int = 0
    migration_count: int = 0
    fault_count: int = 0                # revocations by hardware faults (faults/)
    lost_work: float = 0.0              # reference-speed seconds rolled back to
                                        # the last checkpoint by fault revocations
    lost_service: float = 0.0           # chip-seconds attributed to rolled-back
                                        # work (goodput decomposition: the share
                                        # of attained_service that produced work
                                        # a fault later erased)
    overhead_service: float = 0.0       # chip-seconds spent burning
                                        # overhead_remaining (modeled restart /
                                        # migration / restore cost) while holding
                                        # chips — the decomposition's third leg
    epoch: int = 0                      # invalidates stale scheduled completions
    arrival_seq: int = 0                # submit-order index assigned by the engine
                                        # (numeric FIFO tie-break; 'j2' < 'j10')

    # ---- causal attribution (engine-owned, ISSUE 5) ----
    # None keeps the attribution-off path allocation-free and byte-
    # identical; the engine sets it to {} when attribution is armed and
    # legs (WAIT_CAUSES / RUN_LEGS keys, exact cumulative seconds) appear
    # lazily as they first accrue.
    attrib: Optional[Dict[str, float]] = None
    blame_cause: Optional[str] = None   # cause of the open queued interval
    blame_since: float = 0.0            # when that interval started

    # scratch space for policies (queue index, profiling state, ...)
    sched: dict = field(default_factory=dict)

    # ------------------------------------------------------------------ #

    @property
    def remaining_work(self) -> float:
        """Reference-speed seconds of service still owed to this job."""
        return max(0.0, self.duration - self.executed_work)

    @property
    def finished(self) -> bool:
        return self.state in END_STATES

    @property
    def end_state(self) -> JobState:
        """Terminal state declared by the trace for when this job completes."""
        return STATUS_TO_END_STATE.get(self.status, JobState.DONE)

    @property
    def effective_speed(self) -> float:
        """Actual progress rate: policy speed degraded by placement quality."""
        return self.speed * self.locality_factor

    def remaining_runtime(self) -> float:
        """Wall-clock seconds to completion at the current speed (inf if idle)."""
        if self.effective_speed <= 0.0:
            return float("inf")
        return self.overhead_remaining + self.remaining_work / self.effective_speed

    def advance(self, now: float) -> None:
        """Integrate progress from ``last_update_time`` to ``now``.

        Overhead (modeled suspend/resume or migration cost) is burned first at
        wall-clock rate; only the remainder of the interval accrues work and
        attained service.
        """
        dt = now - self.last_update_time
        if dt < 0:
            raise ValueError(
                f"time went backwards for {self.job_id}: {self.last_update_time} -> {now}"
            )
        self.last_update_time = now
        if self.state is not JobState.RUNNING or dt == 0.0:
            return
        if self.overhead_remaining > 0.0:
            burned = min(self.overhead_remaining, dt)
            self.overhead_remaining -= burned
            # chips are occupied but produce no work while overhead burns:
            # the restart-overhead leg of the goodput decomposition
            self.overhead_service += self.allocated_chips * burned
            if self.attrib is not None:
                self.attrib["overhead"] = self.attrib.get("overhead", 0.0) + burned
            dt -= burned
        if dt > 0.0:
            self.executed_work += self.effective_speed * dt
            self.attained_service += self.allocated_chips * dt
            if self.attrib is not None:
                # RUN_LEGS split of this productive interval: work +
                # policy-share + net-degraded == dt in real arithmetic
                # (s*l + (1-s) + s*(1-l) == 1); the decomposition's own
                # ordered sum absorbs the float dust
                a = self.attrib
                a["work"] = a.get("work", 0.0) + self.effective_speed * dt
                if self.speed != 1.0:
                    a["policy-share"] = (
                        a.get("policy-share", 0.0) + (1.0 - self.speed) * dt
                    )
                if self.locality_factor != 1.0:
                    a["net-degraded"] = (
                        a.get("net-degraded", 0.0)
                        + self.speed * (1.0 - self.locality_factor) * dt
                    )

    def jct(self) -> Optional[float]:
        """Job completion time (end - submit), once finished."""
        if self.end_time is None:
            return None
        return self.end_time - self.submit_time

    def queueing_delay(self) -> Optional[float]:
        """Delay between submission and first start."""
        if self.first_start_time is None:
            return None
        return self.first_start_time - self.submit_time

    def slowdown(self) -> Optional[float]:
        """JCT relative to a dedicated-cluster run (the trace duration at
        the requested gang).  1.0 = ran immediately with no interference;
        the fairness policies (Themis) minimize the tail of this ratio."""
        j = self.jct()
        if j is None:
            return None
        return j / max(self.duration, 1e-9)

    def __repr__(self) -> str:  # compact for debugging/log lines
        return (
            f"Job({self.job_id}, chips={self.num_chips}, state={self.state.value}, "
            f"work={self.executed_work:.1f}/{self.duration:.1f})"
        )
