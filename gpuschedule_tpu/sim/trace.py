"""Trace ingestion: synthetic Poisson workloads and CSV traces.

The reference replays Microsoft Philly traces and synthetic (Poisson) traces
(SURVEY.md §1 layer 4, §2 "Trace data").  This module provides:

- :func:`generate_poisson_trace` — synthetic open-arrival workload with
  Poisson inter-arrival times, mixed gang sizes, and heavy-tailed durations
  (the classic cluster-sim workload shape);
- :func:`load_trace_csv` / :func:`save_trace_csv` — the framework's native
  trace schema (one row per job);
- the Philly-schema loader lives in :mod:`gpuschedule_tpu.sim.philly`.

Determinism: all randomness flows through a caller-supplied seed so a fixed
(trace, cluster, policy) triple reproduces identical JCT/makespan numbers
run-to-run — that reproducibility is the integration-test strategy
(SURVEY.md §4 "Deterministic replay as the integration test").
"""

from __future__ import annotations

import csv
import random
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

from gpuschedule_tpu.sim.job import Job

# Native trace schema, one row per job.
TRACE_FIELDS = [
    "job_id",
    "submit_time",
    "num_chips",
    "duration",
    "model_name",
    "iterations",
    "status",
    "user",
    "utilization",
]

# Default gang-size mix: mostly small jobs with a tail of large ones, the
# empirical shape of the Philly workload (most jobs are 1-GPU; a minority are
# distributed) [P: Philly ATC'19].  Sizes are powers of two so they map onto
# valid TPU slice shapes without rounding.
DEFAULT_SIZE_WEIGHTS: Sequence[tuple[int, float]] = (
    (1, 0.45),
    (2, 0.15),
    (4, 0.15),
    (8, 0.13),
    (16, 0.07),
    (32, 0.04),
    (64, 0.01),
)

DEFAULT_MODELS: Sequence[str] = (
    "transformer-tiny",
    "transformer-small",
    "transformer-base",
    "mlp-wide",
)


def generate_poisson_trace(
    num_jobs: int,
    *,
    seed: int = 0,
    arrival_rate: float = 1.0 / 60.0,     # jobs per second (mean interarrival 60s)
    mean_duration: float = 3600.0,        # seconds; lognormal heavy tail
    sigma: float = 1.2,                   # lognormal shape for durations
    size_weights: Sequence[tuple[int, float]] = DEFAULT_SIZE_WEIGHTS,
    models: Sequence[str] = DEFAULT_MODELS,
    failure_rate: float = 0.0,            # fraction of jobs ending Failed/Killed
    util_range: tuple[float, float] = (1.0, 1.0),  # uniform profiled-utilization
                                          # draw; widen (e.g. (0.3, 1.0)) to give
                                          # Gandiva packing candidates
) -> List[Job]:
    """Generate an open-arrival synthetic trace.

    Inter-arrival times are exponential(arrival_rate); durations are lognormal
    scaled to the requested mean; gang sizes are drawn from ``size_weights``.
    With ``failure_rate`` > 0 a matching fraction of jobs carries a
    Failed/Killed trace status (fault-injection path, SURVEY.md §5).
    """
    rng = random.Random(seed)
    sizes = [s for s, _ in size_weights]
    weights = [w for _, w in size_weights]
    # Scale the lognormal so its mean equals mean_duration.
    import math

    mu = math.log(mean_duration) - sigma * sigma / 2.0

    jobs: List[Job] = []
    t = 0.0
    for i in range(num_jobs):
        t += rng.expovariate(arrival_rate)
        duration = max(1.0, rng.lognormvariate(mu, sigma))
        status = "Pass"
        if failure_rate > 0.0 and rng.random() < failure_rate:
            status = rng.choice(["Failed", "Killed"])
        lo, hi = util_range
        jobs.append(
            Job(
                job_id=f"j{i:05d}",
                submit_time=round(t, 3),
                num_chips=rng.choices(sizes, weights=weights)[0],
                duration=round(duration, 3),
                model_name=rng.choice(list(models)),
                iterations=max(1, int(duration)),  # 1 it/s nominal
                status=status,
                utilization=round(rng.uniform(lo, hi), 3),
            )
        )
    return jobs


def save_trace_csv(jobs: Iterable[Job], path: str | Path) -> None:
    """Write jobs in the native trace schema."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(TRACE_FIELDS)
        for j in jobs:
            w.writerow(
                [
                    j.job_id,
                    j.submit_time,
                    j.num_chips,
                    j.duration,
                    j.model_name,
                    j.iterations if j.iterations is not None else "",
                    j.status,
                    j.user,
                    j.utilization,
                ]
            )


def load_trace_csv(path: str | Path) -> List[Job]:
    """Load a native-schema trace CSV, sorted by submit time."""
    jobs: List[Job] = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            jobs.append(
                Job(
                    job_id=row["job_id"],
                    submit_time=float(row["submit_time"]),
                    num_chips=int(row["num_chips"]),
                    duration=float(row["duration"]),
                    model_name=row.get("model_name") or "transformer-tiny",
                    iterations=int(row["iterations"]) if row.get("iterations") else None,
                    status=row.get("status") or "Pass",
                    user=row.get("user") or "",
                    utilization=float(row["utilization"]) if row.get("utilization") else 1.0,
                )
            )
    jobs.sort(key=lambda j: (j.submit_time, j.job_id))
    return jobs
