"""Engine snapshot / restore / fork (ISSUE 11 tentpole).

One snapshot is the **complete** mid-replay engine state, serialized as a
single pickle graph so every cross-reference keeps its identity — the
jobs in the heap are the jobs in the pending set are the jobs the policy
holds scratch state for.  Restoring in a fresh process re-enters the run
loop between two batches and finishes the replay:

- **v1 accounting**: the resumed tail is byte-identical to the
  uninterrupted run — events.jsonl (truncated to the snapshot's recorded
  sink offset, then appended), jobs.csv, utilization.csv and
  counters.json all hash equal (tests/test_snapshot.py);
- **v2 accounting**: closure-exact under the documented v2 summation
  order (docs/performance.md).

What makes this tractable:

- the engine is **RNG-free by construction** — every stochastic stream
  (trace synthesis, fault schedules) is pregenerated into the spec list
  before the run starts, and the one live RNG in the stack (the GPU
  cluster's random placement scheme) pickles its exact stream state;
- id()-keyed indices (fault ids, warned-job sets, net members, link
  degrade sites) are remapped through stable fault-record indices across
  the process boundary;
- derived caches are shed or invalidated on restore (cluster
  ``__getstate__`` / ``restored()``, ``NetModel.restored()``), so a
  resume re-derives geometry instead of trusting pre-snapshot state;
- the v2 ledger is rebuilt from the restored running set (its columns
  are a pure derived cache of the job fields).

Format: ``MAGIC + pickle({"version": SNAPSHOT_VERSION, "state": ...})``,
written atomically (tmp + rename).  Bump :data:`SNAPSHOT_VERSION` when
the captured state changes incompatibly; loaders refuse mismatches
instead of mis-restoring.
"""

from __future__ import annotations

import io
import math
import os
import pickle
from pathlib import Path
from typing import Optional

MAGIC = b"GSTPU-SNAP\n"
SNAPSHOT_VERSION = 1

# Engine attributes that must NOT ride the pickle graph: process-bound
# objects (tracer, profiler, metrics with its file handles) and the
# id()-keyed indices that are captured in remapped form instead.
_ENGINE_SKIP = frozenset({
    "metrics", "_tracer", "_profiler", "_ledger", "_lv",
    "_fault_ids", "_warned_jobs", "_net_members",
})

# MetricsLog state that rides the snapshot (file handles and the registry
# are process-bound and excluded; the sink is captured as path + offset).
_METRICS_FIELDS = (
    "job_rows", "util_samples", "counters", "events",
    "max_util_samples", "_stride", "_sample_calls", "_last_t",
    "_last_frac", "_util_area", "_util_horizon", "_tail",
    "run_meta", "_header_emitted", "attribution", "record_events",
    "cache_telemetry", "_all_jobs",
)


def snapshot_state(sim, *, flush_sink: bool = True) -> dict:
    """The picklable state dict for one simulator (shared by file
    snapshots and in-memory forks)."""
    engine = {
        k: v for k, v in sim.__dict__.items() if k not in _ENGINE_SKIP
    }
    # id()-keyed indices, remapped through stable indices/lists
    records = sim.faults.records if sim.faults is not None else []
    fault_index = sim._fault_ids  # id(rec) -> stable index
    warned = {
        fault_index[key]: set(jobs)
        for key, jobs in sim._warned_jobs.items()
        if key in fault_index
    }
    net_members = list(sim._net_members.values())
    degrade_sites = None
    if sim.net is not None:
        sites = getattr(sim.net, "_degrade_sites", None)
        if sites:
            # engine-driven keys are id(record); foreign keys (direct API
            # users) cannot cross a process boundary and are dropped
            degrade_sites = {
                fault_index[key]: site
                for key, site in sites.items()
                if key in fault_index
            }
    metrics = sim.metrics
    sink_path = None
    sink_offset = None
    if metrics._sink_path is not None or metrics._sink_fh is not None:
        if flush_sink:
            metrics.flush_events()
        if metrics._sink_path is not None:
            sink_path = str(metrics._sink_path)
            fh = metrics._sink_fh
            if fh is not None:
                fh.flush()
                sink_offset = fh.tell()
            else:
                # lazy sink never opened: nothing streamed yet
                sink_offset = 0
        else:
            # caller-owned file object: position if it supports it
            try:
                metrics._sink_fh.flush()
                sink_offset = metrics._sink_fh.tell()
            except (OSError, ValueError, AttributeError):
                sink_offset = None
    mstate = {name: getattr(metrics, name) for name in _METRICS_FIELDS}
    return {
        "engine": engine,
        "records": records,
        "warned": warned,
        "net_members": net_members,
        "net_degrade_sites": degrade_sites,
        "metrics": mstate,
        "sink_path": sink_path,
        "sink_offset": sink_offset,
    }


def save_snapshot(sim, path) -> Path:
    """Atomically write ``sim``'s full state to ``path``."""
    out = Path(path)
    if out.parent and not out.parent.exists():
        out.parent.mkdir(parents=True, exist_ok=True)
    state = snapshot_state(sim)
    tmp = out.with_name(out.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        pickle.dump(
            {"version": SNAPSHOT_VERSION, "state": state}, f, protocol=4
        )
    os.replace(tmp, out)
    return out


class SnapshotError(ValueError):
    """Unreadable / wrong-magic / wrong-version snapshot file."""


def load_snapshot(path, *, metrics=None, events_sink=None, profiler=None):
    """Reconstruct a :class:`~gpuschedule_tpu.sim.engine.Simulator` from
    a snapshot file.

    ``metrics`` supplies a fresh :class:`MetricsLog` shell to restore the
    accumulated state into (one is built when omitted); ``events_sink``
    overrides the recorded sink path (the default reopens the recorded
    path, truncated to the recorded offset, so the resumed tail appends
    exactly where the snapshot left off).  The obs registry and tracer
    are process-bound and NOT resumed — counters.json and the event
    stream are exact; metrics.prom counts only the tail.
    """
    p = Path(path)
    try:
        raw = p.read_bytes()
    except OSError as e:
        raise SnapshotError(f"cannot read snapshot {p}: {e}") from None
    if not raw.startswith(MAGIC):
        raise SnapshotError(f"{p} is not an engine snapshot (bad magic)")
    try:
        doc = pickle.loads(raw[len(MAGIC):])
    except Exception as e:  # corrupt pickle: refuse loudly, not halfway
        raise SnapshotError(f"{p}: corrupt snapshot payload: {e}") from None
    version = doc.get("version")
    if version != SNAPSHOT_VERSION:
        raise SnapshotError(
            f"{p}: snapshot version {version!r} != supported "
            f"{SNAPSHOT_VERSION} (re-snapshot with this build)"
        )
    return _restore(doc["state"], metrics=metrics, events_sink=events_sink,
                    profiler=profiler)


def state_to_bytes(sim) -> bytes:
    """One simulator's complete state as a transportable byte string —
    what the what-if worker pool ships to each worker so it can mirror
    the live engine once and then serve many :func:`fork`-per-query
    replays (ISSUE 12).  The parent's sink stays attached and unflushed;
    clones built from these bytes always detach it."""
    state = snapshot_state(sim, flush_sink=False)
    buf = io.BytesIO()
    pickle.dump(state, buf, protocol=4)
    return buf.getvalue()


def clone_from_state_bytes(data: bytes):
    """Reconstruct a fully independent, silently-observing simulator
    from :func:`state_to_bytes` output — in this process or another.
    The clone carries the full accounting history but writes nowhere:
    no event stream, buffered events dropped, periodic snapshotting
    disarmed (a speculative replay must never overwrite the parent's
    checkpoint file).

    This is the what-if service's per-query fork: a paused mirror's
    state bytes are invariant across queries, so each worker serializes
    once and clones by unpickle alone — half the full dump+load round
    trip, and fork latency IS query latency (ISSUE 12).  The collector
    pauses across the load (burst allocation trips gc generations for
    ~15% of the latency; nothing here creates cycles)."""
    import gc

    paused = gc.isenabled()
    if paused:
        gc.disable()
    try:
        state = pickle.loads(data)
    finally:
        if paused:
            gc.enable()
    state["sink_path"] = None
    state["sink_offset"] = None
    clone = _restore(state, metrics=None, events_sink=False, profiler=None)
    clone.metrics.record_events = False
    clone.metrics.events = []
    clone._snap_path = None
    clone._snap_every = None
    clone._snap_next = math.inf
    return clone


def fork_simulator(sim):
    """In-memory deep copy via the same state capture (identity-preserving
    pickle round trip), with the event stream detached: the fork carries
    the full accounting history but writes nowhere.

    The collector is paused across the dump half too (the load half
    pauses inside :func:`clone_from_state_bytes`): pickling a
    100k-object graph allocates in bursts that trip gc generations
    several times, ~15% of fork latency."""
    import gc

    paused = gc.isenabled()
    if paused:
        gc.disable()
    try:
        data = state_to_bytes(sim)
    finally:
        if paused:
            gc.enable()
    return clone_from_state_bytes(data)


# --------------------------------------------------------------------- #
# restore internals


def _restore_metrics(state: dict, *, metrics=None, events_sink=None):
    from gpuschedule_tpu.sim.metrics import MetricsLog

    m = metrics if metrics is not None else MetricsLog()
    for name in _METRICS_FIELDS:
        setattr(m, name, state["metrics"][name])
    sink = None
    if events_sink is False:       # fork: explicitly no sink
        sink = None
    elif events_sink is not None:  # caller override
        sink = Path(events_sink)
    elif state["sink_path"] is not None:
        sink = Path(state["sink_path"])
    if sink is not None:
        offset = state["sink_offset"] or 0
        sink.parent.mkdir(parents=True, exist_ok=True)
        # reopen at the snapshot's byte offset: anything streamed after
        # the snapshot (the crashed tail) is discarded, and the resumed
        # replay appends exactly where the snapshot-consistent prefix
        # ends — what makes head + tail equal the uninterrupted bytes.
        # The offset only means anything for a file that actually holds
        # the prefix (the recorded sink, or a copy of it); clamp to the
        # file's real size so a fresh/shorter override sink gets the
        # tail appended from where it ends instead of a NUL-padded head
        cur = sink.stat().st_size if sink.exists() else 0
        offset = min(offset, cur)
        fh = open(sink, "a+")
        fh.truncate(offset)
        fh.seek(offset)
        m._sink_path = sink
        m._sink_fh = fh
        m._owns_sink = True
        m._sink_opened = True
    return m


def _restore(state: dict, *, metrics=None, events_sink=None, profiler=None):
    from gpuschedule_tpu.obs.tracer import get_tracer
    from gpuschedule_tpu.sim.engine import Simulator

    sim = object.__new__(Simulator)
    sim.__dict__.update(state["engine"])
    sim._tracer = get_tracer()
    sim._profiler = profiler
    sim.metrics = _restore_metrics(
        state, metrics=metrics, events_sink=events_sink
    )
    sim.metrics.attach_jobs(sim.jobs)
    # rebuild the id()-keyed indices against this process's identities
    records = state["records"]
    sim._fault_ids = {id(rec): i for i, rec in enumerate(records)}
    sim._warned_jobs = {
        id(records[i]): jobs for i, jobs in state["warned"].items()
    }
    sim._net_members = {id(j): j for j in state["net_members"]}
    if sim.net is not None:
        if state["net_degrade_sites"] is not None:
            sim.net._degrade_sites = {
                id(records[i]): site
                for i, site in state["net_degrade_sites"].items()
            }
        sim.net.restored()
    cluster = getattr(sim.cluster, "inner", sim.cluster)
    cluster.restored()
    # v2 ledger: a pure derived cache — rebuild from the running set
    sim._ledger = None
    sim._lv = None
    if sim._lazy:
        from gpuschedule_tpu.sim.ledger import JobLedger

        sim._ledger = JobLedger(
            attribution=sim.attribution,
            vector=bool(getattr(sim.policy, "reads_progress", True)),
        )
        if sim._ledger.vector:
            sim._lv = sim._ledger
            for job in sim.running:
                sim._lv.bind(job)
    sim._snap_restores += 1
    return sim
