"""Discrete-event simulation engine.

The reference drives its policies from per-policy time-stepped while-loops
(SURVEY.md §3.1: advance clock, charge progress, invoke policy, apply
preemptions).  This engine keeps that contract — progress charging, policy
invocation after every state change, gang-aware start/preempt — but is
event-driven rather than fixed-delta: the clock jumps between arrivals,
(predicted) completions, and policy-requested wakeups ("ticks", used for
Tiresias quanta / Gandiva rounds / Optimus rounds).  Completion events are
predicted from each job's current speed and invalidated by a per-job epoch
counter whenever a preemption/resize changes the prediction, so replay is
exact rather than quantized to a time step.

Single-process, pure Python, no accelerator in the loop (SURVEY.md §3.1:
"pure single-process CPU sim").
"""

from __future__ import annotations

import heapq
import itertools
import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from gpuschedule_tpu.obs.perfetto import track_label
from gpuschedule_tpu.obs.tracer import get_tracer
from gpuschedule_tpu.sim.job import END_STATES, Job, JobState
from gpuschedule_tpu.sim.jobset import JobSet
from gpuschedule_tpu.sim.metrics import MetricsLog, SimResult

# Event kinds, in processing-priority order at equal timestamps: completions
# free resources before arrivals are considered, faults land after both (a
# job finishing exactly when its chips fail completed first — nothing to
# revoke), repairs land after the fault that scheduled them (a zero-length
# outage still revokes, then heals, within one batch), spot pre-revoke
# warnings (ISSUE 6) land after repairs (they are pushed strictly before
# their fault's timestamp, so they can never share a batch with it), and
# the policy runs once after the whole batch.  Cluster samples (ISSUE 5)
# sort last so a sample coinciding with real events snapshots the
# post-fault/repair state of that instant (though still before the policy
# pass reacts to it) — and so the run loops' "sample on top means the
# whole batch is samples" fast path stays sound.
_COMPLETION, _ARRIVAL, _TICK, _FAULT, _REPAIR, _WARN, _SAMPLE = (
    0, 1, 2, 3, 4, 5, 6
)
# Injected what-if mutations (ISSUE 12, sim/whatif.py): sorts after
# everything at an equal timestamp — INCLUDING _SAMPLE, so the run
# loops' samples-only fast path is gated on `_whatif_pending == 0` (a
# sample on top no longer proves the batch is all samples while a
# mutation is in flight).  Critically EVEN: the lazy spec feed treats
# odd kinds as cursor-fed specs (popping one admits the next), so an
# injected event must never wear an odd kind or it would double-feed
# the cursor.  Only present in speculative forks / direct API use;
# ordinary replays never push it.
_WHATIF = 8


def _prog(job: Job) -> dict:
    """Exact cumulative progress snapshot carried under ``"prog"`` on every
    per-job lifecycle event (schema 1, docs/events.md).  Full-precision
    floats — json round-trips Python floats bit-exactly — so the analyzer
    (obs/analyze.py) reconstructs the goodput decomposition to the last
    float without replaying the engine's internal advance chunking."""
    return {
        "work": job.executed_work,
        "service": job.attained_service,
        "lost_service": job.lost_service,
        "overhead_service": job.overhead_service,
        "lost_work": job.lost_work,
        "overhead_left": job.overhead_remaining,
    }


class Simulator:
    """Replay a trace against a cluster under a policy.

    The policy object receives this simulator as its scheduling context and
    mutates job state only through the engine API (:meth:`try_start`,
    :meth:`preempt`, :meth:`set_speed`, :meth:`migrate`), which keeps
    progress accounting and completion prediction consistent.
    """

    # count of injected-but-unapplied what-if events in the heap (class
    # default so restored pre-ISSUE-12 snapshots read 0).  _WHATIF sorts
    # after _SAMPLE, so the run loops' "sample on top means the whole
    # batch is samples" fast path is only sound while this is zero —
    # with a mutation pending, sample-topped batches take the full path
    # (pre-advance, fault dispatch, policy pass, net update).  Ordinary
    # replays never inject, so the fast path — and its byte-identity
    # contract — is untouched outside speculative forks.
    _whatif_pending = 0

    def __init__(
        self,
        cluster,
        policy,
        jobs: Sequence[Job],
        *,
        metrics: Optional[MetricsLog] = None,
        max_time: float = float("inf"),
        eps: float = 1e-6,
        faults=None,
        net=None,
        sample_interval: Optional[float] = None,
        sample_on_change: bool = False,
        profiler=None,
        accounting: str = "v1",
        snapshot_every: Optional[float] = None,
        snapshot_path=None,
    ):
        self.cluster = cluster
        self.policy = policy
        # Accounting version (ISSUE 11 tentpole).  "v1" (the default) is
        # the historical chunk-per-batch integration — every running job
        # advances at every event batch, and the resulting float sums are
        # part of the byte-identity contract.  "v2" replaces byte-identity
        # with exact-sum closure (docs/performance.md): jobs integrate
        # lazily at mutation/read points (Job.advance is segment-exact for
        # any dt), the per-batch sweep disappears for policies that never
        # read running-job progress (Policy.reads_progress False — FIFO),
        # and becomes the JobLedger's vectorized sync_all for those that
        # do.  The knob rides the CLI config hash when set to v2.
        if accounting not in ("v1", "v2"):
            raise ValueError(
                f"accounting must be 'v1' or 'v2', got {accounting!r}"
            )
        self.accounting = accounting
        self._lazy = accounting == "v2"
        self._ledger = None
        self._lv = None  # the ledger iff it maintains columns (vector mode)
        if self._lazy:
            from gpuschedule_tpu.sim.ledger import JobLedger

            self._ledger = JobLedger(
                attribution=bool(getattr(metrics, "attribution", False)),
                vector=bool(getattr(policy, "reads_progress", True)),
            )
            if self._ledger.vector:
                self._lv = self._ledger
        # Periodic engine snapshots (ISSUE 11): every ``snapshot_every``
        # sim seconds the full engine state is serialized to
        # ``snapshot_path`` (sim/snapshot.py), making long replays
        # crash-resumable.  Purely observational: the snapshot lands
        # between batches, so the replay's own bytes never move.
        if (snapshot_every is None) != (snapshot_path is None):
            raise ValueError(
                "snapshot_every and snapshot_path arm together"
            )
        if snapshot_every is not None and snapshot_every <= 0.0:
            raise ValueError(
                f"snapshot_every must be > 0, got {snapshot_every}"
            )
        self._snap_every = snapshot_every
        self._snap_path = snapshot_path
        self._snap_next = snapshot_every if snapshot_every is not None else math.inf
        self._snap_writes = 0
        self._snap_restores = 0
        # Shared-fabric contention (net/): a NetModel that re-prices every
        # running multislice job's locality_factor by max-min fair
        # bandwidth sharing whenever the running set or link health
        # changes.  None (the default) is the static-factor path,
        # bit-identical to the pre-net engine.
        self.net = net
        self._net_links: Dict[str, tuple] = {}  # last emitted link sample
        self._net_priced: Dict[str, float] = {}  # job_id -> last emitted bw
        # adaptive routing (ISSUE 8): job_id -> last priced route (the
        # flow's weighted uplink set); maintained only when the fabric
        # has redundant uplinks, so single-uplink runs never touch it
        self._net_routes: Dict[str, tuple] = {}
        if net is not None:
            net.attach(cluster)
        # Fault injection (faults/): a FaultPlan whose records become
        # _FAULT events and whose RecoveryModel prices each revocation.
        # None (the default) is the fault-free path, bit-identical to the
        # pre-faults engine; an empty-record plan (mtbf=inf) arms the path
        # without firing it.
        self.faults = faults
        # Failure hazard (faults/hazard.py, ISSUE 8): when the fault plan
        # arms any hazard knob, build the runtime model, bind it to the
        # cluster (placement schemes read cluster.hazard_score) and arm
        # the proactive checkpoint-and-migrate trigger.  The default
        # (plan.hazard None) leaves self.hazard None: no wear tracking,
        # no per-batch observe call, no behavior change.
        self.hazard = None
        self._migrate_threshold = math.inf
        if faults is not None and getattr(faults, "hazard", None) is not None:
            from gpuschedule_tpu.faults.hazard import HazardModel

            self.hazard = HazardModel(faults.hazard, cluster)
            cluster.bind_hazard(self.hazard)
            self._migrate_threshold = faults.hazard.migrate_threshold
        # Stable sort: ties on submit_time keep trace order, and each job gets
        # a numeric arrival sequence so policies can tie-break without relying
        # on string job_id ordering (which misorders 'j2' vs 'j10').
        self.jobs: List[Job] = sorted(jobs, key=lambda j: j.submit_time)
        for seq, job in enumerate(self.jobs):
            job.arrival_seq = seq
        self.metrics = metrics or MetricsLog()
        self.metrics.attach_jobs(self.jobs)
        self.max_time = max_time
        self.eps = eps
        # Causal attribution (ISSUE 5 tentpole): armed by the metrics log
        # (MetricsLog(attribution=True) / CLI --attrib).  Arms each job's
        # ``attrib`` leg dict; everything else is gated on this flag so
        # the off path stays byte-identical to the pre-attribution engine.
        self.attribution = bool(getattr(self.metrics, "attribution", False))
        if self.attribution:
            for job in self.jobs:
                job.attrib = {}
        # Periodic cluster-side samples (ISSUE 5): every ``sample_interval``
        # sim seconds a ``sample`` event snapshots physical occupancy,
        # health-masked chips, fragmentation and queue depth straight from
        # the cluster flavor.  Samples never mark the batch dirty (no
        # policy invocation, no replay perturbation) and stop re-arming
        # once only ticks/samples remain in the heap.
        if sample_interval is not None and sample_interval <= 0.0:
            raise ValueError(
                f"sample_interval must be > 0, got {sample_interval}"
            )
        self.sample_interval = sample_interval
        # On-change sampling (ISSUE 10 satellite, retiring the PR-5
        # "sampling is time-driven only" omission): emit a cluster
        # ``sample`` event whenever a batch changed the health/degrade
        # masks (fault, repair, straggler onset/recovery, domain outage)
        # — in addition to (and independent of) the periodic timer.  The
        # sample lands after the batch's fault/repair records and before
        # the policy pass's reactions, the same instant the timer-driven
        # sampler would snapshot; like it, it observes without dirtying,
        # so the lifecycle stream stays byte-identical modulo the sample
        # records themselves.
        self.sample_on_change = bool(sample_on_change)
        # bumped by every health/degrade-mask transition (chip/domain
        # fault, straggler onset/recovery, mask repair) — NOT by link
        # faults (net-model state, no cluster mask moves) or warnings
        self._mask_mut = 0
        # Wall-clock phase profiler (ISSUE 10 tentpole): when attached,
        # run() selects the _run_profiled loop body — the plain loop with
        # two perf_counter reads per segment; detached (the default) no
        # code path ever reads a clock (the check_overhead.py contract).
        self._profiler = profiler
        # Cache telemetry (ISSUE 10 tentpole): when the metrics log arms
        # it, the end of the run harvests every PR-7/9 cache's hit/miss
        # counters (cluster allocate caches, net pricing/flow/group
        # caches, engine memos) into labeled engine_cache_events metrics,
        # summary counters, and one trailing "cache" stream record.  Off
        # (the default) nothing is harvested and the summary/stream stay
        # byte-identical.
        self._cache_telemetry = bool(
            getattr(self.metrics, "cache_telemetry", False)
        )
        # Observability (obs/): the span tracer is a process singleton whose
        # ``enabled`` flag picks the run loop — the disabled path is the
        # uninstrumented loop verbatim (tools/check_overhead.py guards that
        # it stays overhead-free).
        self._tracer = get_tracer()

        self.now: float = 0.0
        # Insertion-ordered, O(1)-mutation sets (see jobset.py): pending keeps
        # arrival order for non-preemptive policies; both make start/preempt/
        # finish constant-time at Philly scale.
        self.pending: JobSet = JobSet()   # submitted, not running, not finished
        self.running: JobSet = JobSet()   # holding allocations
        self.finished: List[Job] = []
        self._heap: list = []
        self._seq = itertools.count()
        self._nonticks = 0  # heap entries that are not policy ticks
        # Indexed hot paths (ISSUE 9): alloc_id -> Job for every bound
        # allocation, so fault/warning victim resolution is O(victims)
        # instead of a running-set sweep; running-set insertion tickets
        # (Job.run_seq) let any indexed subset reproduce the sweep's exact
        # iteration order by sorting.
        self._alloc_jobs: Dict[int, Job] = {}
        self._run_tickets = itertools.count()
        # running multislice members (net/): the only jobs _net_update can
        # emit for, so the per-pass scan is O(flows), not O(running).
        # Keyed by object identity; values iterated in run_seq order.
        self._net_members: Dict[int, Job] = {}
        # engine-mutation counter + memo for the _quiesced endgame scan
        # (every job.epoch bump increments it; see _quiesced); hit/miss
        # counts feed the ISSUE 10 cache telemetry
        self._mut = 0
        self._stall_memo: tuple = ()
        self._stall_hits = 0
        self._stall_misses = 0
        if self.sample_interval is not None:
            # first sample one interval in (a t=0 sample of an empty
            # cluster carries no information)
            self._push(self.sample_interval, _SAMPLE)
        # _drain_faults: records remain in the heap after every job has
        # reached an end state (the schedule is generated to a conservative
        # horizon); the run loops discard them by stopping early.  False
        # for an empty plan so mtbf=inf replays stay event-for-event
        # identical to faults=None.
        self._drain_faults = False
        # Event-stream header (obs/analyze.py): when the caller armed a
        # header (run_meta), fill in the facts the engine knows and the
        # caller might not have set — the policy name and cluster capacity
        # (the analyzer's utilization denominator).  setdefault: explicit
        # caller values win.
        if self.metrics.run_meta is not None:
            self.metrics.run_meta.setdefault("policy", policy.name)
            self.metrics.run_meta.setdefault("total_chips", cluster.total_chips)
        # record identity -> stable index: fault and repair events carry it
        # as "fid" so the Perfetto exporter pairs each repair with ITS
        # outage even when outages of different durations overlap on one
        # scope (FIFO pairing would mis-attribute the intervals)
        self._fault_ids: Dict[int, int] = {}
        # spot record identity -> job_ids that took an emergency
        # checkpoint on ITS warning: the revoke event's "warned" flag
        # marks only revocations whose own notice protected the victim
        # (the persistent ckpt_protected watermark still shrinks losses
        # of later unrelated revocations, but those are not "warned")
        self._warned_jobs: Dict[int, set] = {}
        # Lazy event feed (ISSUE 9): trace arrivals and fault/warning
        # records used to be pushed into the heap up front, so the heap
        # held O(jobs + faults) entries for the whole replay and every
        # push/pop paid a log of the TRACE length — the first of the
        # per-event costs that grew with fleet scale.  Instead, the
        # pre-known events become a time-sorted spec list fed through a
        # cursor: exactly one spec sits in the heap at a time, and popping
        # it pushes the next, so the heap stays at O(running + residue)
        # whatever the trace length.  Byte-identity: the heap breaks ties
        # by (time, kind, push seq) and spec kinds (_ARRIVAL/_FAULT/_WARN,
        # the odd numbers) never collide with dynamic kinds at equal
        # (time, kind) — sorting specs by (time, kind) with a stable sort
        # (construction order breaks remaining ties, exactly as the old
        # ascending push-seq did) reproduces the old pop order event for
        # event.
        specs: list = [(job.submit_time, _ARRIVAL, job) for job in self.jobs]
        if faults is not None and faults.records:
            self._drain_faults = True
            for i, rec in enumerate(faults.records):
                self._fault_ids[id(rec)] = i
                specs.append((rec.time, _FAULT, rec))
                # spot pre-revoke notice (ISSUE 6 priced recovery): the
                # warning lands strictly before its revocation, giving
                # running gangs on the spot unit a window to take an
                # emergency checkpoint (faults/recovery.py)
                if rec.kind == "spot" and rec.warning > 0.0:
                    t_warn = rec.time - rec.warning
                    if 0.0 < t_warn < rec.time:
                        specs.append((t_warn, _WARN, rec))
        specs.sort(key=lambda s: (s[0], s[1]))
        self._specs = specs
        self._spec_i = 0
        self._push_next_spec()
        # Priced checkpoint writes (ISSUE 6): when the recovery model
        # charges for writes, size each job's per-write cost from its
        # model state and gang once, up front — Job.advance folds it into
        # the overhead leg as the write-time fraction of every productive
        # interval.  The default (ckpt_write=0) leaves every job's fields
        # at their dataclass defaults, keeping the advance hot path (and
        # every replayed float) bit-identical to the unpriced engine.
        if faults is not None and faults.recovery is not None:
            recovery = faults.recovery
            if getattr(recovery, "writes_cost", lambda: False)():
                for job in self.jobs:
                    interval = recovery.checkpoint_interval(job)
                    if 0.0 < interval < math.inf:
                        job.ckpt_write_s = recovery.ckpt_write_seconds(
                            job, cluster
                        )
                        job.ckpt_every = interval
        policy.attach(self)

    # ------------------------------------------------------------------ #
    # event plumbing

    def _push(self, time: float, kind: int, payload=None, epoch: int = 0) -> None:
        # ticks and samples are excluded from _nonticks: neither can change
        # scheduler-visible state, so _quiesced()'s "only residue remains"
        # test (and the sample re-arm cutoff) ignores them
        if kind != _TICK and kind != _SAMPLE:
            self._nonticks += 1
        heapq.heappush(self._heap, (time, kind, next(self._seq), payload, epoch))

    def _push_next_spec(self) -> None:
        """Feed the next pre-known event (arrival / fault / warning) from
        the time-sorted spec list into the heap.  Exactly one spec lives
        in the heap at a time — the cursor invariant that keeps the heap
        scale-free (ISSUE 9) and, while any spec remains, keeps
        ``_nonticks`` >= 1 so the quiescence test and the sample re-arm
        cutoff see pending real work exactly as they used to."""
        i = self._spec_i
        specs = self._specs
        if i < len(specs):
            self._spec_i = i + 1
            t, kind, payload = specs[i]
            self._push(t, kind, payload)

    def request_wakeup(self, time: float) -> None:
        """Policy-facing: ask to be re-invoked at absolute sim time ``time``."""
        if time > self.now + self.eps:
            self._push(time, _TICK)

    def _schedule_completion(self, job: Job) -> None:
        rt = job.remaining_runtime()
        if rt != float("inf"):
            self._push(self.now + rt, _COMPLETION, job, job.epoch)

    def _advance_running(self, t: float) -> None:
        for job in self.running:
            job.advance(t)

    def _bind_allocation(self, job: Job, alloc) -> None:
        """Attach a granted allocation to a job, deriving every allocation-
        dependent field (single site: placement quality feeds progress).
        ``slow_factor`` is the straggler multiplier (faults/): the min
        residual rate over the granted chips — 1.0 (and free to compute)
        whenever no chip is degraded."""
        job.allocation = alloc
        job.locality_factor = getattr(alloc.detail, "speed_factor", 1.0)
        job.slow_factor = self.cluster.alloc_slow_factor(alloc)
        self._alloc_jobs[alloc.alloc_id] = job
        if self.net is not None:
            # the flow set / pod occupancy changed: invalidate the cached
            # fabric pricing (ISSUE 7 incremental re-pricing)
            self.net.mark_dirty(job)
            if getattr(alloc.detail, "slices", None):
                # a DCN-spanning gang: it is (or is about to become) a
                # flow, so _net_update must visit it (ISSUE 9 member set)
                self._net_members[id(job)] = job

    def _unbind_allocation(self, job: Job) -> None:
        """Drop a job's allocation from the engine indices — called at
        every ``cluster.free`` site, before the free, so the index never
        holds a dead alloc_id."""
        alloc = job.allocation
        if alloc is not None:
            self._alloc_jobs.pop(alloc.alloc_id, None)

    def _net_release(self, job: Job) -> None:
        """Invalidate the cached fabric pricing for a job about to lose
        its allocation — called while the allocation is still attached so
        the dirty test can see which pods it loaded.  Only leaving-the-
        running-set sites call this (preempt / finish / revoke), so it
        also retires the job's net-member entry; the resize/migrate paths
        keep membership until the next recompute closes the job's share."""
        if self.net is not None:
            self.net.mark_dirty(job)
            self._net_members.pop(id(job), None)

    # ------------------------------------------------------------------ #
    # causal attribution (ISSUE 5): blame tagging + cluster sampling

    def _queue_cause(self, job: Job) -> str:
        """Blame for a queued-at-arrival interval, decided from cluster
        state at event time: ``capacity`` when not even unhealthy chips
        would cover the gang, ``fault-outage`` when health-masked chips
        are what's missing, ``net-outage`` when the missing chips are
        held by gangs stalled at rate 0 by hard DCN-uplink outages (the
        capacity would exist if those gangs could progress and finish —
        the PR-5 omission that misfiled this under ``capacity``),
        ``admission`` when enough nominally-free healthy chips exist —
        the delay is slice geometry or scheduler ordering, not a
        resource shortage."""
        free = self.cluster.free_chips
        if free >= job.num_chips:
            return "admission"
        unhealthy = self.cluster.unhealthy_chips
        if free + unhealthy >= job.num_chips:
            return "fault-outage"
        if self.net is not None:
            # a locality factor of exactly 0.0 only arises from a fully
            # degraded uplink (net/model.py): the gang holds its chips
            # but can never finish until the link heals
            stalled = sum(
                j.allocated_chips
                for j in self.running
                if j.locality_factor == 0.0
            )
            if stalled and free + unhealthy + stalled >= job.num_chips:
                return "net-outage"
        return "capacity"

    def _open_blame(self, job: Job, cause: str) -> None:
        job.blame_cause = cause
        job.blame_since = self.now

    def _close_blame(self, job: Job) -> None:
        """Charge the open queued/suspended interval to its cause (exact
        cumulative floats; the analyzer adopts them from event snapshots
        and SimResult sums them with the same arithmetic)."""
        cause = job.blame_cause
        if cause is None:
            return
        dt = self.now - job.blame_since
        if dt > 0.0:
            job.attrib[cause] = job.attrib.get(cause, 0.0) + dt
        job.blame_cause = None

    def _close_attribution(self) -> None:
        """End of run: close the open wait interval of every job still in
        the pending set (queued or suspended), so SimResult's per-cause
        aggregate covers the full simulated span.

        Each closed job also gets a terminal ``cutoff`` record carrying
        the final legs: the run can end *later* than the last lifecycle
        event (a max_time horizon with nothing running, a stale-
        completion drain), and without a record at ``self.now`` the
        analyzer's stream would end early and its end-of-stream close
        would stop short — silently losing the wait tail (review-
        confirmed regression, pinned by
        tests/test_attrib.py::test_closure_holds_at_horizon_with_nothing_running)."""
        if not self.attribution:
            return
        record = self.metrics.record_events
        for job in self.pending:
            if job.blame_cause is None:
                continue
            self._close_blame(job)
            if record:
                self.metrics.event(
                    "cutoff", self.now, job, chips=0, blame=dict(job.attrib)
                )

    def _emit_sample(self, t: float) -> None:
        """One periodic cluster-side ``sample`` event: *physical*
        occupancy (overlay-packed guests consume no extra chips, unlike
        the demand series the analyzer derives from start events),
        health-masked chips, fragmentation, and queue depth — straight
        from the cluster flavor's :meth:`sample_state`.  A no-op without
        the event stream, so the sampling-on/events-off path costs only
        the heap traffic (tools/check_overhead.py gates it)."""
        if not self.metrics.record_events:
            return
        self.metrics.event(
            "sample", t, None,
            running=len(self.running), pending=len(self.pending),
            **self.cluster.sample_state(),
        )

    # ------------------------------------------------------------------ #
    # policy-facing mutation API

    def try_start(
        self,
        job: Job,
        *,
        chips: Optional[int] = None,
        speed: float = 1.0,
        overhead: float = 0.0,
        placement_hint: Optional[dict] = None,
        why: Optional[dict] = None,
    ) -> bool:
        """Gang-start (or resume) ``job`` on ``chips`` chips; False if the
        cluster cannot grant a valid allocation (all-or-nothing, SURVEY.md §3.1
        placement step).

        ``why`` is the policy's scheduling rationale for this decision (the
        ``Policy.explain`` channel): a small dict naming the rule that fired,
        persisted into the event stream so a trace answers *why* a job
        started, not just *that* it did.  Policies pass None when the event
        stream is off, keeping the hot path allocation-free."""
        if job.state not in (JobState.PENDING, JobState.SUSPENDED):
            raise RuntimeError(f"try_start on non-schedulable job {job!r}")
        if speed <= 0.0:
            # A RUNNING job at speed<=0 never completes and holds chips forever;
            # pausing-in-place is expressed via preempt(suspend=True) instead.
            raise ValueError(f"try_start requires speed > 0, got {speed}")
        chips = chips if chips is not None else job.num_chips
        if job.pin_hint is not None:
            # what-if placement pin (ISSUE 12): the injected candidate's
            # hint wins over the policy's on key conflicts
            placement_hint = (
                {**placement_hint, **job.pin_hint} if placement_hint
                else job.pin_hint
            )
        alloc = self.cluster.allocate(chips, job=job, hint=placement_hint)
        if alloc is None:
            return False
        job.advance(self.now)
        if self.attribution:
            self._close_blame(job)
        self._bind_allocation(job, alloc)
        job.allocated_chips = chips
        job.state = JobState.RUNNING
        job.speed = speed
        job.overhead_remaining += overhead
        job.epoch += 1
        self._mut += 1
        if job.first_start_time is None:
            job.first_start_time = self.now
        self.pending.discard(job)
        self.running.append(job)
        # running-set insertion ticket: ascending run_seq IS the running
        # set's iteration order, so indexed subsets (victims, net members)
        # can reproduce a full sweep's order by sorting on it (ISSUE 9)
        job.run_seq = next(self._run_tickets)
        self._schedule_completion(job)
        if self._lv is not None:
            self._lv.bind(job)
        if self.metrics.record_events:
            extra = {"chips": chips, "speed": speed, "overhead": overhead,
                     "locality": job.locality_factor,
                     "track": track_label(alloc.detail), "prog": _prog(job)}
            if job.slow_factor != 1.0:
                extra["slow_factor"] = job.slow_factor
            if why is not None:
                extra["why"] = why
            if self.attribution:
                extra["blame"] = dict(job.attrib)
            self.metrics.event("start", self.now, job, **extra)
        return True

    def preempt(
        self, job: Job, *, suspend: bool = True, why: Optional[dict] = None
    ) -> None:
        """Take ``job`` off the cluster.  ``suspend=True`` marks it as a
        time-sliced victim with resume intent (Gandiva); ``suspend=False``
        returns it to the pending queue (Tiresias/SRTF demotion).  ``why``
        is the rationale channel (see :meth:`try_start`)."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"preempt on non-running job {job!r}")
        record = self.metrics.record_events
        track = track_label(job.allocation.detail) if record else None
        job.advance(self.now)
        self._net_release(job)
        self._unbind_allocation(job)
        self.cluster.free(job.allocation)
        job.allocation = None
        job.allocated_chips = 0
        job.speed = 0.0
        job.locality_factor = 1.0
        job.slow_factor = 1.0
        job.epoch += 1
        self._mut += 1
        job.preempt_count += 1
        job.state = JobState.SUSPENDED if suspend else JobState.PENDING
        self.running.remove(job)
        if self._lv is not None:
            self._lv.release(job)
        self.pending.append(job)
        self.metrics.count("preemptions")
        if self.attribution:
            # the whole wait that follows is blamed on this preemption,
            # however long capacity later takes to reappear (cause decided
            # at interval start — docs/events.md)
            self._open_blame(job, "policy-preempt")
        if record:
            extra = {"suspend": suspend, "track": track, "prog": _prog(job)}
            if why is not None:
                extra["why"] = why
            if self.attribution:
                extra["cause"] = "policy-preempt"
                if why is not None and "code" in why:
                    extra["cause_code"] = why["code"]
                extra["blame"] = dict(job.attrib)
            self.metrics.event("preempt", self.now, job, **extra)

    def set_speed(self, job: Job, speed: float, *, why: Optional[dict] = None) -> None:
        """Change a running job's progress rate (elastic resize effect)."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"set_speed on non-running job {job!r}")
        if speed <= 0.0:
            raise ValueError(f"set_speed requires speed > 0, got {speed}")
        job.advance(self.now)
        job.speed = speed
        job.epoch += 1
        self._mut += 1
        self._schedule_completion(job)
        if self._lv is not None:
            self._lv.refresh(job)
        if self.metrics.record_events:
            extra = {"speed": speed, "prog": _prog(job)}
            if why is not None:
                extra["why"] = why
            self.metrics.event("speed", self.now, job, **extra)

    def migrate(
        self,
        job: Job,
        *,
        overhead: float,
        placement_hint: Optional[dict] = None,
        why: Optional[dict] = None,
        event_extra: Optional[dict] = None,
    ) -> bool:
        """Move a running job to a fresh allocation, paying ``overhead``
        seconds of modeled checkpoint/restore cost (SURVEY.md §3.3 migration).

        Returns False — with NO cost charged — when the move didn't happen:
        the hint was unsatisfiable, or first-fit handed back the very slice
        the job already held (a job already at its packed position must not
        be taxed for a no-op "migration")."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"migrate on non-running job {job!r}")
        chips, speed = job.allocated_chips, job.speed
        old_detail = job.allocation.detail if job.allocation is not None else None
        job.advance(self.now)
        self._unbind_allocation(job)
        self.cluster.free(job.allocation)
        alloc = self.cluster.allocate(chips, job=job, hint=placement_hint)
        if alloc is None:  # hint unsatisfiable; restore in place (no cost charged)
            alloc = self.cluster.allocate(chips, job=job)
            if alloc is None:
                raise RuntimeError(f"allocation vanished during migration of {job!r}")
            # "in place" may still land differently (e.g. a better GPU
            # locality tier): re-derive the factor and re-predict completion,
            # or the stale event computed at the old rate stands
            self._bind_allocation(job, alloc)
            job.epoch += 1
            self._mut += 1
            self._schedule_completion(job)
            if self._lv is not None:
                self._lv.refresh(job)
            self._emit_rebind(job, old_detail, alloc)
            return False
        self._bind_allocation(job, alloc)
        if old_detail is not None and alloc.detail == old_detail:
            if self._lv is not None:
                self._lv.refresh(job)
            return False  # same slice re-granted: no movement, no cost
        job.overhead_remaining += overhead
        job.migration_count += 1
        job.epoch += 1
        self._mut += 1
        self._schedule_completion(job)
        if self._lv is not None:
            self._lv.refresh(job)
        self.metrics.count("migrations")
        if self.metrics.record_events:
            extra = {"overhead": overhead, "locality": job.locality_factor,
                     "track": track_label(alloc.detail), "prog": _prog(job)}
            if job.slow_factor != 1.0:
                extra["slow_factor"] = job.slow_factor
            if why is not None:
                extra["why"] = why
            if event_extra:
                extra.update(event_extra)
            self.metrics.event("migrate", self.now, job, **extra)
        return True

    def resize(
        self,
        job: Job,
        *,
        chips: int,
        speed: float,
        overhead: float = 0.0,
        why: Optional[dict] = None,
    ) -> bool:
        """Elastic grow/shrink (Optimus, SURVEY.md §3.2): re-allocate ``job``
        at ``chips`` with new progress rate ``speed``."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"resize on non-running job {job!r}")
        if speed <= 0.0:
            raise ValueError(f"resize requires speed > 0, got {speed}")
        if chips == job.allocated_chips and speed == job.speed:
            return True
        job.advance(self.now)
        old_detail = job.allocation.detail if job.allocation is not None else None
        self._unbind_allocation(job)
        self.cluster.free(job.allocation)
        alloc = self.cluster.allocate(chips, job=job)
        if alloc is None:
            alloc = self.cluster.allocate(job.allocated_chips, job=job)
            if alloc is None:
                raise RuntimeError(f"allocation vanished during resize of {job!r}")
            self._bind_allocation(job, alloc)
            job.epoch += 1
            self._mut += 1
            self._schedule_completion(job)
            if self._lv is not None:
                self._lv.refresh(job)
            self._emit_rebind(job, old_detail, alloc)
            return False
        self._bind_allocation(job, alloc)
        job.allocated_chips = chips
        job.speed = speed
        job.overhead_remaining += overhead
        job.epoch += 1
        self._mut += 1
        self._schedule_completion(job)
        if self._lv is not None:
            self._lv.refresh(job)
        if self.metrics.record_events:
            extra = {"chips": chips, "speed": speed,
                     "locality": job.locality_factor,
                     "track": track_label(alloc.detail), "prog": _prog(job)}
            if job.slow_factor != 1.0:
                extra["slow_factor"] = job.slow_factor
            if why is not None:
                extra["why"] = why
            self.metrics.event("resize", self.now, job, **extra)
        return True

    def _emit_rebind(self, job: Job, old_detail, alloc) -> None:
        """Event for the migrate/resize fallback that re-granted an
        allocation in place: the move the policy asked for didn't happen,
        but the job may now sit on a *different* slice (a better locality
        tier), which changes its progress rate — a silent transition the
        analyzer could not reconstruct without this record.  Skipped when
        the re-grant is literally the same placement (nothing observable
        changed)."""
        if not self.metrics.record_events:
            return
        if old_detail is not None and alloc.detail == old_detail:
            return
        extra = {}
        if job.slow_factor != 1.0:
            extra["slow_factor"] = job.slow_factor
        self.metrics.event(
            "rebind", self.now, job,
            chips=job.allocated_chips, speed=job.speed,
            locality=job.locality_factor,
            track=track_label(alloc.detail), prog=_prog(job), **extra,
        )

    def proactive_migrate(
        self, job: Job, *, exposure: float = 0.0, why: Optional[dict] = None
    ) -> bool:
        """Priced checkpoint-then-migrate (ISSUE 8): the action the
        engine offers ``Policy.on_hazard`` when a running gang's failure
        exposure crosses the fault plan's ``migrate_threshold``.

        Takes a checkpoint *now* (the write cost plus the restore on the
        new slice ride the move as overhead — the PR-6 priced-recovery
        machinery), migrates the gang to a strictly clean allocation
        (``avoid_degraded="strict"``: no clean box anywhere → no move,
        NO cost — the gang keeps limping where it is), and raises the
        rollback floor to the checkpointed watermark so a later fault on
        the new hardware loses nothing already protected.

        Accounting: ``avoided_s`` is the work a revocation at this
        instant would have rolled back (the loss this move insures
        against), ``write_s + restore_s`` the overhead actually paid —
        both ride the migrate event (``proactive`` payload) and the
        ``proactive_avoided_work_s`` / ``proactive_overhead_s``
        counters, so the fault panel can weigh avoided-loss against
        paid-overhead."""
        if job.state is not JobState.RUNNING:
            return False
        if self.faults is None or self.faults.recovery is None:
            return False
        recovery = self.faults.recovery
        job.advance(self.now)
        write = recovery.ckpt_write_seconds(job, self.cluster)
        restore = recovery.restore_overhead(job, self.cluster)
        avoided = recovery.lost_progress(job)
        event_extra = None
        if self.metrics.record_events:
            event_extra = {"proactive": {
                "exposure": exposure, "avoided_s": avoided,
                "write_s": write, "restore_s": restore,
            }}
        moved = self.migrate(
            job, overhead=write + restore,
            placement_hint={"avoid_degraded": "strict"},
            why=why, event_extra=event_extra,
        )
        if not moved:
            self.metrics.count("proactive_migrates_blocked")
            return False
        # the checkpoint this move just paid for protects everything
        # executed so far: a fault right after it loses nothing
        job.ckpt_protected = max(job.ckpt_protected or 0.0, job.executed_work)
        self.metrics.count("proactive_migrations")
        self.metrics.count("proactive_avoided_work_s", avoided)
        self.metrics.count("proactive_overhead_s", write + restore)
        return True

    def _offer_hazard_migrations(self) -> None:
        """Offer ``Policy.on_hazard`` every running gang whose exposure
        crosses the armed ``migrate_threshold``.  Evaluated after each
        degrade-mask change (straggler onset/recovery) — the events that
        move exposure; a gang stuck on degraded chips with no clean box
        is re-offered at the next change and stays put at zero cost."""
        hazard = self.hazard
        threshold = self._migrate_threshold
        for job in list(self.running):
            exposure = 1.0 - job.slow_factor
            if hazard is not None and job.allocation is not None:
                exposure += hazard.gang_exposure(job.allocation)
            if exposure >= threshold:
                self.policy.on_hazard(self, job, exposure)

    # ------------------------------------------------------------------ #

    def _finish(self, job: Job) -> None:
        record = self.metrics.record_events
        track = track_label(job.allocation.detail) if record else None
        job.advance(self.now)
        job.executed_work = job.duration  # absorb float residue
        self._net_release(job)
        self._unbind_allocation(job)
        self.cluster.free(job.allocation)
        job.allocation = None
        job.allocated_chips = 0
        job.speed = 0.0
        job.epoch += 1
        self._mut += 1
        job.state = job.end_state
        job.end_time = self.now
        self.running.remove(job)
        if self._lv is not None:
            self._lv.release(job)
        self.finished.append(job)
        self.metrics.record_job(job)
        if record:
            extra = {}
            if self.attribution:
                extra = {"blame": dict(job.attrib)}
            self.metrics.event(
                "finish", self.now, job, end_state=job.state.value, track=track,
                prog=_prog(job), **extra,
            )

    # ------------------------------------------------------------------ #
    # shared-fabric contention (net/)

    def _net_update(self) -> None:
        """Re-price every running multislice job's dynamic locality factor
        from its max-min fair bandwidth share (net/), after any event
        batch that may have changed the running set or link health.

        Factor changes ride the same re-predict machinery as the migrate/
        resize in-place fallback: advance progress at the old rate, bind
        the new factor, bump the epoch, reschedule the completion.  Each
        change is emitted as a ``net`` event (with the exact progress
        snapshot) and changed link loads as ``netlink`` events, so the
        analyzer reconstructs bandwidth shares and link utilization from
        the stream alone.

        Incremental fast path (ISSUE 7): when no allocation mutation or
        link-health change marked the model dirty since the last pass,
        ``poll`` hands back the cached state and the whole running-set
        scan is skipped — nothing could have changed, so no event would
        have been emitted anyway (the pre-incremental engine would have
        re-derived identical shares and fallen through every emit
        branch).

        Member-set scan (ISSUE 9): only running multislice gangs (plus
        gangs whose stale bandwidth share still needs closing) can make
        this loop emit or mutate anything — the engine maintains exactly
        that set at bind/release time (``_net_members``), so a dirty pass
        costs O(flows), not O(running).  Iterating members in ascending
        ``run_seq`` reproduces the running-set sweep's order exactly, so
        every emitted event lands in the same stream position."""
        if self.net.poll(self.now) is not None:
            return
        state = self.net.recompute(self.now, self.running, reuse_flows=True)
        record = self.metrics.record_events
        # adaptive routing (ISSUE 8): with redundant uplinks, a flow's
        # weighted uplink set is a route choice that shifts when link
        # health does — emit the change as a ``reroute`` event.  Gated on
        # the fabric actually having siblings, so single-uplink replays
        # never touch the dict (byte-identity with PR 7).
        routing = getattr(self.net, "routing_enabled", False)
        if routing:
            routed, self._net_routes = self._net_routes, {}
        priced, self._net_priced = self._net_priced, {}
        # _net_members holds running multislice gangs only (registered
        # at bind, retired at release):
        # lint: job-states[running] membership provenance for GS7xx
        members = sorted(
            self._net_members.values(), key=lambda j: j.run_seq
        )
        for job in members:
            share = state.shares.get(job.job_id)
            if share is None:
                # still running but no longer a flow (an elastic shrink/
                # migration back inside one pod): close its bandwidth in
                # the stream if it was priced, then retire the membership
                # — a later multislice re-grow re-registers it at bind
                del self._net_members[id(job)]
                if priced.get(job.job_id):
                    self.metrics.count("net_reprices")
                    if record:
                        self.metrics.event(
                            "net", self.now, job,
                            locality=job.locality_factor, bw_gbps=0.0,
                            prog=_prog(job),
                        )
                continue
            self._net_priced[job.job_id] = share.gbps
            if routing:
                route = share.route
                self._net_routes[job.job_id] = route
                old = routed.get(job.job_id)
                if old is not None and old != route:
                    # the flow moved onto different uplinks (or different
                    # weights) — a route change, not just a speed change
                    self.metrics.count("reroutes")
                    if record:
                        self.metrics.event(
                            "reroute", self.now, job,
                            links=[[name, w] for name, w in route],
                        )
            if (share.factor == job.locality_factor
                    and priced.get(job.job_id) == share.gbps):
                continue
            if share.factor != job.locality_factor:
                job.advance(self.now)
                job.locality_factor = share.factor
                job.epoch += 1
                self._mut += 1
                self._schedule_completion(job)
                if self._lv is not None:
                    self._lv.refresh(job)
            self.metrics.count("net_reprices")
            if record:
                self.metrics.event(
                    "net", self.now, job, locality=share.factor,
                    bw_gbps=share.gbps, demand_gbps=share.demand_gbps,
                    prog=_prog(job),
                )
        if record:
            for name, sample in state.links.items():
                cur = (sample.used_gbps, sample.capacity_gbps)
                if self._net_links.get(name) == cur:
                    continue
                self._net_links[name] = cur
                self.metrics.event(
                    "netlink", self.now, None, link=name,
                    used_gbps=sample.used_gbps,
                    capacity_gbps=sample.capacity_gbps, util=sample.util,
                )
        self.metrics.net_link_samples(state.links)

    # ------------------------------------------------------------------ #
    # fault injection (faults/)

    def _apply_fault(self, rec) -> None:
        """One hardware outage: mark the scope unhealthy, revoke every
        running gang on it, schedule the repair, and let the policy react.

        A correlated domain outage (``kind="domain"``) rides this same
        path: its scope covers every chip under the host/rack/pod at
        once, so the single ``mark_unhealthy`` returns every overlapping
        gang and the whole blast radius is one fault event, one
        revocation batch, one repair — the single-event accounting the
        per-chip model could not express."""
        if rec.scope and rec.scope[0] == "link":
            self._apply_link_fault(rec)
            return
        if rec.kind == "straggler":
            self._apply_straggler(rec)
            return
        if self._lazy and self._lv is None:
            # v2 lazy accounting: fault dispatch (and the policy hooks it
            # invokes) may read any running job's progress — bring the
            # whole set to now first (cold path; v1 already swept it at
            # the top of the batch, and vector mode's sync_all did too)
            self._advance_running(self.now)
        victim_ids = self.cluster.mark_unhealthy(rec.scope)
        self._mask_mut += 1  # health mask moved (on-change sampling)
        self.metrics.count("faults")
        self.metrics.count(f"faults_{rec.kind}")
        if self.metrics.record_events:
            extra = {"level": rec.level} if rec.level else {}
            self.metrics.event(
                "fault", self.now, None,
                scope=rec.label, fault=rec.kind, fid=self._fault_ids[id(rec)],
                # "inf" (string) keeps events.jsonl strict JSON for
                # never-repaired outages
                duration=rec.duration if math.isfinite(rec.duration) else "inf",
                **extra,
            )
        if math.isfinite(rec.duration):
            # duration <= 0 lands in this same batch (kind order puts the
            # repair after the fault), modeling a blip that still revokes
            self._push(self.now + max(0.0, rec.duration), _REPAIR, rec)
        # alloc-index victim resolution (ISSUE 9): O(victims) instead of a
        # running-set sweep; run_seq order IS the sweep's iteration order
        victims = self._victim_jobs(victim_ids)
        for job in victims:
            self._revoke(job, rec)
        self.policy.on_fault(self, rec, victims)
        if math.isfinite(self._migrate_threshold):
            # hazard-heat exposure (wear-aged pods) moves with time, not
            # only with the degrade mask: fault events are the periodic
            # evaluation points for configs whose stragglers are off
            self._offer_hazard_migrations()

    def _apply_link_fault(self, rec) -> None:
        """A ``("link", pod)`` DCN-uplink outage — the first *partial
        degradation* fault (ROADMAP PR-2 open item): nothing is revoked
        and no chip goes unhealthy; the degraded uplink slows multislice
        jobs through the contention model (the post-batch ``_net_update``
        re-prices them).  Without a net model the outage is recorded but
        cannot change any speed — counted as ``link_faults_inert`` so an
        operator sees the fault spec asked for something the run cannot
        express (run with ``--net``)."""
        if self._lazy and self._lv is None:
            self._advance_running(self.now)
        self.metrics.count("faults")
        self.metrics.count(f"faults_{rec.kind}")
        if self.metrics.record_events:
            self.metrics.event(
                "fault", self.now, None,
                scope=rec.label, fault=rec.kind, fid=self._fault_ids[id(rec)],
                degrade=rec.degrade,
                duration=rec.duration if math.isfinite(rec.duration) else "inf",
            )
        if self.net is not None:
            # keyed by record identity so the repair heals exactly the
            # sibling this outage degraded (redundant-uplink fabrics)
            self.net.degrade_link(int(rec.scope[1]), rec.degrade, key=id(rec))
        else:
            self.metrics.count("link_faults_inert")
        if math.isfinite(rec.duration):
            self._push(self.now + max(0.0, rec.duration), _REPAIR, rec)
        self.policy.on_fault(self, rec, [])

    def _apply_straggler(self, rec) -> None:
        """A straggler onset (``kind="straggler"``): one chip/node drops
        to ``rec.degrade`` of its rate.  Nothing is revoked and no chip
        leaves the health mask — the unit stays allocatable, just slow —
        but every synchronous gang holding it slows to the straggler's
        rate (``Job.slow_factor``, the compute-side analogue of PR 4's
        link degradation).  Clusters without a degrade mask record the
        fault but cannot slow anyone (``straggler_faults_inert``, the
        link_faults_inert pattern)."""
        if self._lazy and self._lv is None:
            self._advance_running(self.now)
        self.metrics.count("faults")
        self.metrics.count(f"faults_{rec.kind}")
        if self.metrics.record_events:
            self.metrics.event(
                "fault", self.now, None,
                scope=rec.label, fault=rec.kind, fid=self._fault_ids[id(rec)],
                degrade=rec.degrade,
                duration=rec.duration if math.isfinite(rec.duration) else "inf",
            )
        mark = getattr(self.cluster, "mark_degraded", None)
        if mark is None:
            self.metrics.count("straggler_faults_inert")
        else:
            touched = mark(rec.scope, rec.degrade)
            self._mask_mut += 1  # degrade mask moved (on-change sampling)
            self._apply_slow_factors(touched)
        if math.isfinite(rec.duration):
            self._push(self.now + max(0.0, rec.duration), _REPAIR, rec)
        self.policy.on_fault(self, rec, [])

    def _apply_slow_factors(self, alloc_ids=None) -> None:
        """Re-derive running gangs' straggler multipliers from the
        cluster's degrade mask after a straggler onset or recovery.
        Factor changes ride the usual re-predict machinery (advance at
        the old rate, bind, epoch bump, reschedule) and are emitted as
        ``slow`` events with the exact progress snapshot, so the
        analyzer tracks the rate change without replaying the mask.

        ``alloc_ids`` (ISSUE 9) scopes the re-derivation to the gangs the
        cluster reported overlapping the changed scope — a gang's min-
        over-chips factor can only move when one of ITS chips did, so
        visiting only those gangs (in run_seq = sweep order) emits the
        identical events.  ``None`` keeps the full running-set sweep for
        clusters whose mask cannot report overlap."""
        record = self.metrics.record_events
        jobs = (
            self.running if alloc_ids is None
            else self._victim_jobs(alloc_ids)
        )
        for job in jobs:
            factor = self.cluster.alloc_slow_factor(job.allocation)
            if factor == job.slow_factor:
                continue
            job.advance(self.now)
            job.slow_factor = factor
            job.epoch += 1
            self._mut += 1
            self._schedule_completion(job)
            if self._lv is not None:
                self._lv.refresh(job)
            self.metrics.count("straggler_reprices")
            if record:
                self.metrics.event(
                    "slow", self.now, job, slow_factor=factor,
                    prog=_prog(job),
                )
        if math.isfinite(self._migrate_threshold):
            # proactive checkpoint-and-migrate (ISSUE 8): straggler
            # exposure moves exactly when the degrade mask does; the
            # hazard-heat term is additionally re-evaluated at fault
            # events (_apply_fault) — between those, exposure changes
            # are not observed (docs/faults.md omissions)
            self._offer_hazard_migrations()

    def _apply_warning(self, rec) -> None:
        """A spot pre-revoke notice, ``rec.warning`` seconds ahead of its
        revocation: every gang that would be revoked right now gets the
        chance to take an *emergency checkpoint* (faults/recovery.py) —
        when the window covers the job's checkpoint-write cost, the
        write is charged as overhead inside the window and the rollback
        floor rises to the warned watermark, so the later revocation
        loses only the window's tail instead of a full checkpoint
        interval.  Gangs whose write cannot finish in time are notified
        but unprotected (``spot_warnings_missed``)."""
        if self._lazy and self._lv is None:
            self._advance_running(self.now)
        self.metrics.count("spot_warnings")
        peek = getattr(self.cluster, "peek_victims", None)
        victims = self._victim_jobs(peek(rec.scope) if peek is not None else ())
        record = self.metrics.record_events
        recovery = self.faults.recovery
        window = rec.time - self.now
        for job in victims:
            write = recovery.ckpt_write_seconds(job, self.cluster)
            if write > window + self.eps:
                self.metrics.count("spot_warnings_missed")
                if record:
                    self.metrics.event(
                        "warn", self.now, job, scope=rec.label,
                        fault=rec.kind, window=window, write=write,
                        saved=False,
                    )
                continue
            job.advance(self.now)
            job.ckpt_protected = max(
                job.ckpt_protected or 0.0, job.executed_work
            )
            job.overhead_remaining += write
            job.epoch += 1
            self._mut += 1
            self._schedule_completion(job)
            if self._lv is not None:
                self._lv.refresh(job)
            self._warned_jobs.setdefault(id(rec), set()).add(job.job_id)
            self.metrics.count("emergency_ckpts")
            if record:
                self.metrics.event(
                    "warn", self.now, job, scope=rec.label, fault=rec.kind,
                    window=window, write=write, saved=True, prog=_prog(job),
                )
        self.policy.on_warning(self, rec, victims)

    def _revoke(self, job: Job, rec) -> None:
        """Fault-revoke one running job: progress rolls back to its last
        checkpoint, a restore cost is charged for the next run, and the job
        requeues (the recovery model in faults/recovery.py decides both
        amounts; this method only applies them)."""
        record = self.metrics.record_events
        track = track_label(job.allocation.detail) if record else None
        job.advance(self.now)
        recovery = self.faults.recovery
        # priced while the gang still holds its chips (restore cost scales
        # with the slice's host count in "auto" mode)
        restore = recovery.restore_overhead(job, self.cluster)
        lost = recovery.lost_progress(job)
        # a warned revocation is one whose OWN pre-revoke notice took the
        # emergency checkpoint that then shrank the rollback; the
        # persistent watermark shrinking a later unrelated revocation's
        # loss does not count (that record gave no warning)
        warned = (
            job.job_id in self._warned_jobs.get(id(rec), ())
            and lost < recovery.lost_progress(job, use_emergency=False)
        )
        if lost > 0.0 and job.executed_work > 0.0:
            # prorate the rolled-back share of this job's useful chip-time
            # into the lost leg of the goodput decomposition: surviving
            # work keeps (1 - frac) of the previously-useful service
            frac = min(1.0, lost / job.executed_work)
            job.lost_service += frac * max(
                0.0, job.attained_service - job.lost_service
            )
            job.executed_work -= lost
            job.lost_work += lost
        self._net_release(job)
        self._unbind_allocation(job)
        self.cluster.free(job.allocation)
        job.allocation = None
        job.allocated_chips = 0
        job.speed = 0.0
        job.locality_factor = 1.0
        job.slow_factor = 1.0
        job.epoch += 1
        self._mut += 1
        job.fault_count += 1
        # the checkpoint restore supersedes any partially burned setup cost
        # (a job faulted mid-resume starts its recovery over)
        job.overhead_remaining = restore
        job.state = JobState.PENDING
        self.running.remove(job)
        if self._lv is not None:
            self._lv.release(job)
        self.pending.append(job)
        self.metrics.count("fault_revocations")
        if warned:
            self.metrics.count("warned_revocations")
        if self.attribution:
            self._open_blame(job, "fault-outage")
        if record:
            # exact floats (schema 1): the analyzer attributes this event's
            # lost work to its fault kind and closes the decomposition
            # against SimResult.goodput bit-for-bit — rounding here would
            # break the closure (docs/events.md)
            extra = {}
            if self.attribution:
                extra = {"cause": "fault-outage", "blame": dict(job.attrib)}
            if warned:
                extra["warned"] = True
            self.metrics.event(
                "revoke", self.now, job,
                scope=rec.label, fault=rec.kind,
                lost_work=lost, restore=restore,
                track=track, prog=_prog(job), **extra,
            )

    def _apply_repair(self, payload, t: float) -> None:
        """One repair record: heal the link / degrade mask / health mask
        (hoisted verbatim out of ``_drain_batch`` so the profiled loop
        can time it as fault dispatch with one wrapper)."""
        if payload.scope and payload.scope[0] == "link":
            # uplink outages live in the net model, not the chip
            # health mask (nothing was marked unhealthy)
            if self.net is not None:
                self.net.repair_link(int(payload.scope[1]),
                                     payload.degrade,
                                     key=id(payload))
        elif payload.kind == "straggler":
            # straggler recovery lives in the degrade mask, not
            # the health mask; gangs on the healed unit speed
            # back up through the same slow-factor re-derivation
            if hasattr(self.cluster, "clear_degraded"):
                touched = self.cluster.clear_degraded(
                    payload.scope, payload.degrade
                )
                self._mask_mut += 1
                self._apply_slow_factors(touched)
        else:
            self.cluster.repair(payload.scope)
            self._mask_mut += 1
        self.metrics.count("repairs")
        if self.metrics.record_events:
            self.metrics.event(
                "repair", t, None, scope=payload.label,
                fault=payload.kind, fid=self._fault_ids[id(payload)],
            )

    # resolves alloc ids through the live allocation index, so every
    # returned job holds an allocation:
    # lint: job-states[running] return provenance for GS7xx
    def _victim_jobs(self, alloc_ids) -> List[Job]:
        """Resolve a cluster-reported alloc_id list to the running jobs
        holding them, in running-set iteration order (ascending run_seq) —
        the indexed replacement for ``[j for j in self.running if
        j.allocation.alloc_id in ids]`` (ISSUE 9)."""
        if not alloc_ids:
            return []
        index = self._alloc_jobs
        victims = [index[a] for a in alloc_ids if a in index]
        victims.sort(key=lambda j: j.run_seq)
        return victims

    def _drain_batch(self, t: float, prof=None) -> bool:
        """Pop and apply every event at or before ``t``; True if any event
        changed scheduler-visible state (the policy must then run).

        ``prof`` (the profiled loop only) times fault/warning/repair
        dispatch as its own phase — the cold branches check it once per
        fault event; the plain/traced loops never pass it, so the hot
        arrival/completion branches are untouched."""
        dirty = False
        heap = self._heap
        heappop = heapq.heappop
        metrics = self.metrics
        lazy = self._lazy
        while heap and heap[0][0] <= t:
            _, kind, _, payload, epoch = heappop(heap)
            if kind != _TICK and kind != _SAMPLE:
                self._nonticks -= 1
            if kind & 1:
                # spec kinds are exactly the odd ones (_ARRIVAL/_FAULT/
                # _WARN): popping the cursor's in-heap spec admits the
                # next one — at an equal timestamp it joins this same
                # batch, in the old pop order (see _push_next_spec)
                self._push_next_spec()
            if kind == _SAMPLE:
                # cluster-side snapshot: emit (when the event stream is on)
                # and re-arm while real events remain — sampling past the
                # last arrival/completion/fault would only pad the stream.
                # Never marks the batch dirty: the sampler observes, the
                # replay must not feel it.
                self._emit_sample(t)
                if self._nonticks:
                    self._push(t + self.sample_interval, _SAMPLE)
                continue
            if kind == _ARRIVAL:
                job: Job = payload
                job.last_update_time = t
                metrics.count("arrivals")
                if not self.cluster.is_satisfiable(job.num_chips):
                    # Admission control: this gang size can never be
                    # granted here (non-slice size, bigger than a pod).
                    # Reject now instead of letting it wedge priority
                    # schedulers that would reserve budget for it forever.
                    # REJECTED is excluded from JCT/makespan aggregates
                    # (metrics.result), so rejecting clusters don't score
                    # artificially good headline numbers.
                    job.state = JobState.REJECTED
                    job.end_time = t
                    self.finished.append(job)
                    metrics.record_job(job)
                    metrics.count("rejected_unsatisfiable")
                    if metrics.record_events:
                        metrics.event("reject", t, job, chips=job.num_chips)
                else:
                    self.pending.append(job)
                    cause = None
                    if self.attribution:
                        cause = self._queue_cause(job)
                        self._open_blame(job, cause)
                    if metrics.record_events:
                        # duration/status ride along so the analyzer can
                        # derive slowdown and expected end states without
                        # re-reading the trace
                        extra = {"chips": job.num_chips,
                                 "duration": job.duration,
                                 "status": job.status}
                        if job.ckpt_write_s > 0.0:
                            # priced checkpoint writes: the analyzer needs
                            # the per-job write cost and period to mirror
                            # the engine's work/overhead split in its
                            # drift guard
                            extra["ckpt_write_s"] = job.ckpt_write_s
                            extra["ckpt_every"] = job.ckpt_every
                        if cause is not None:
                            extra["cause"] = cause
                        metrics.event("arrival", t, job, **extra)
                dirty = True
            elif kind == _COMPLETION:
                job = payload
                if job.epoch != epoch or job.state is not JobState.RUNNING:
                    continue  # stale prediction from before a preempt/resize
                if lazy:
                    # v2: integrate to the completion instant (v1 swept
                    # the whole running set at the top of the batch; the
                    # vector sync_all leaves this a dt == 0 no-op)
                    job.advance(t)
                if job.remaining_runtime() > self.eps:
                    # speed changed without epoch bump — repredict
                    self._schedule_completion(job)
                    continue
                self._finish(job)
                dirty = True
            elif kind == _FAULT:
                if prof is not None:
                    with prof.phase("fault_dispatch"):
                        self._apply_fault(payload)
                else:
                    self._apply_fault(payload)
                dirty = True
            elif kind == _WARN:
                # spot pre-revoke notice (ISSUE 6): may charge emergency
                # checkpoint overhead, so the policy gets a pass after it
                if prof is not None:
                    with prof.phase("fault_dispatch"):
                        self._apply_warning(payload)
                else:
                    self._apply_warning(payload)
                dirty = True
            elif kind == _REPAIR:
                if prof is not None:
                    with prof.phase("fault_dispatch"):
                        self._apply_repair(payload, t)
                else:
                    self._apply_repair(payload, t)
                dirty = True  # restored capacity: waiters may now place
            elif kind == _WHATIF:
                # injected what-if mutation (cold path: only speculative
                # forks ever push these)
                self._apply_whatif(payload, t)
                dirty = True
            else:  # _TICK
                dirty = True
        return dirty

    def run(self) -> SimResult:
        """Drive the event loop to completion and return summary metrics.

        Three bodies, one behavior: the profiled loop (ISSUE 10) buckets
        each batch's wall time into replay phases, the traced loop wraps
        each event batch and policy invocation in tracer spans (dual
        wall/sim clocks), and the plain loop is the uninstrumented hot
        path, selected when both are off so replay pays nothing for the
        telemetry layer's existence (the tools/check_overhead.py
        contract).  A profiler takes precedence over the tracer — the
        phase buckets ARE the wall-clock story; per-batch spans on top
        would double the clock reads they measure."""
        if self._profiler is not None:
            return self._run_profiled()
        if self._tracer.enabled:
            return self._run_traced()
        return self._run_plain()

    def _cutoff_at_horizon(self) -> None:
        """Horizon cutoff: charge running jobs up to max_time so executed
        work and utilization cover the full simulated span.  Shared by both
        run-loop bodies — cold code, one owner.

        Each still-running job gets a terminal ``cutoff`` event carrying its
        final progress snapshot: the cutoff advance happens *after* the
        job's last lifecycle event, so without this record the analyzer's
        per-job legs would stop short of what SimResult.goodput integrates
        (suspended/pending jobs don't advance here and need none)."""
        self.now = self.max_time
        self._advance_running(self.max_time)
        if self.metrics.record_events:
            for job in self.running:
                extra = {}
                if self.attribution:
                    extra = {"blame": dict(job.attrib)}
                self.metrics.event(
                    "cutoff", self.now, job,
                    chips=job.allocated_chips,
                    track=track_label(job.allocation.detail),
                    prog=_prog(job), **extra,
                )
            # waiting jobs get their horizon record from the end-of-run
            # _close_attribution (which runs at this same self.now)
        self.metrics.sample(
            self.now, self.cluster, len(self.running), len(self.pending)
        )

    def _quiesced(self) -> bool:
        """Fault runs can strand jobs: a permanent outage (repair=inf) may
        leave a once-satisfiable gang unplaceable forever.  Once nothing is
        running and no arrival/completion/fault/repair remains — only
        policy-requested ticks — no tick can change anything (every policy
        already ran after the last real event and placed what fits; time
        alone cannot un-strand a gang), so spinning through the tick chain
        would loop forever for policies that always re-request a wakeup
        while jobs wait (Gandiva rounds).  Gated on _drain_faults: the
        fault-free path cannot strand jobs (unsatisfiable gangs are
        rejected at admission) and keeps its exact pre-faults behavior.

        The net/ analogue of a stranded gang: a permanent hard link
        outage (link_repair=inf, degrade=0) pins a multislice job's
        dynamic locality factor at 0.0 — it runs forever at zero rate
        and never schedules a completion.  With nothing pending and only
        ticks left, no tick can revive it (the policy already ran after
        the outage and every tick since; the dead uplink stays dead), so
        the run quiesces instead of spinning through the tick chain."""
        if not self._drain_faults:
            return False
        if len(self.finished) == len(self.jobs):
            return True
        if self._nonticks:
            return False
        if not self.running:
            return True
        if self.pending:
            return False
        # Memoized endgame scan (ISSUE 9): between heap events nothing can
        # change a running job's remaining_runtime without bumping _mut (a
        # job already stalled at rate 0 burns neither work nor its stall:
        # advance() is a no-op on the answer), so a long tick chain asks
        # the O(running) question once per mutation instead of per tick.
        key = (len(self.finished), len(self.running), self._mut)
        memo = self._stall_memo
        if memo and memo[0] == key:
            self._stall_hits += 1
            return memo[1]
        stalled = all(
            j.remaining_runtime() == math.inf for j in self.running
        )
        self._stall_memo = (key, stalled)
        self._stall_misses += 1
        return stalled

    def _run_plain(self) -> SimResult:
        # Hot loop (ISSUE 7): every attribute below is fixed for the whole
        # run, so bind once — at Philly scale this loop turns over millions
        # of times and the repeated self.* lookups are measurable.
        heap = self._heap
        max_time = self.max_time
        net = self.net
        hazard = self.hazard
        cluster = self.cluster
        running, pending = self.running, self.pending
        policy_schedule = self.policy.schedule
        metrics_sample = self.metrics.sample
        soc = self.sample_on_change
        # Per-batch progress sweep (ISSUE 11): v1 = the chunked advance
        # of every running job (byte-identity contract); v2 vector = the
        # ledger's masked-array sync_all (policy reads progress every
        # pass); v2 lazy = nothing at all (jobs integrate at mutations).
        advance = self._advance_running
        if self._ledger is not None:
            advance = self._lv.sync_all if self._lv is not None else None
        snapping = self._snap_every is not None
        while heap:
            if self._quiesced():
                break  # only fault/repair/tick residue past the last job
            head = heap[0]
            t = head[0]
            if t > max_time:
                self._cutoff_at_horizon()
                break
            if snapping and t >= self._snap_next:
                # between-batch instant: self.now is still the previous
                # batch time and every index/heap invariant holds — the
                # exact state a restore re-enters (sim/snapshot.py)
                self._snapshot_tick(t)
            self.now = t
            if head[1] == _SAMPLE and not self._whatif_pending:
                # _SAMPLE sorts last at equal timestamps, so a sample on
                # top means the whole batch is samples: nothing scheduler-
                # visible changes and no progress needs integrating.
                # Skipping the advance keeps every progress float chunked
                # — and therefore the event stream byte-for-byte — exactly
                # as in the sampling-free replay (the ISSUE 5 regression
                # contract extends to sampling-on runs modulo the sample
                # records themselves).
                # deliberately no metrics.sample() either: an extra
                # integration point would re-chunk the utilization
                # integral and dust its low-order bits
                self._drain_batch(t)
                continue
            if hazard is not None:
                # integrate wear before the batch mutates occupancy:
                # between batches occupancy is constant, so the busy
                # chip-second integral is exact piecewise
                hazard.observe(t, cluster)
            if advance is not None:
                advance(t)
            mm = self._mask_mut
            if self._drain_batch(t):
                if soc and self._mask_mut != mm:
                    # on-change sample (ISSUE 10 satellite): the batch
                    # touched a health/degrade mask — snapshot the
                    # post-fault, pre-policy cluster state, exactly where
                    # a coinciding timer sample would land
                    self._emit_sample(t)
                wakeup = policy_schedule(self)
                if wakeup is not None:
                    self.request_wakeup(wakeup)
                if net is not None:
                    self._net_update()
            metrics_sample(self.now, cluster, len(running), len(pending))
        if self._lazy:
            # v2: the loop never swept progress — bring every still-
            # running job to the final clock before the summary sums
            self._advance_running(self.now)
        if self.net is not None:
            self.net.close(self.now)
        self._close_attribution()
        if self._cache_telemetry:
            self._harvest_cache_stats()
        return self.metrics.result(self.jobs, self.now)

    def _run_traced(self) -> SimResult:
        tracer = self._tracer
        with tracer.span(
            "sim.run", cat="sim", sim_now=0.0,
            policy=self.policy.name, jobs=len(self.jobs),
        ) as run_sp:
            n_batches = 0
            advance = self._advance_running
            if self._ledger is not None:
                advance = self._lv.sync_all if self._lv is not None else None
            snapping = self._snap_every is not None
            while self._heap:
                if self._quiesced():
                    break  # only fault/repair/tick residue past the last job
                t = self._heap[0][0]
                if t > self.max_time:
                    self._cutoff_at_horizon()
                    break
                if snapping and t >= self._snap_next:
                    self._snapshot_tick(t)
                self.now = t
                if self._heap[0][1] == _SAMPLE and not self._whatif_pending:
                    # pure-sample batch: same skip as the plain loop (no
                    # advance, no metrics.sample, no policy, no span —
                    # the sampler observes, the replay must not feel it)
                    self._drain_batch(t)
                    continue
                if self.hazard is not None:
                    self.hazard.observe(t, self.cluster)
                with tracer.span("sim.batch", cat="sim", sim_now=t) as sp:
                    if advance is not None:
                        advance(t)
                    mm = self._mask_mut
                    dirty = self._drain_batch(t)
                    if dirty:
                        if self.sample_on_change and self._mask_mut != mm:
                            self._emit_sample(t)
                        with tracer.span(
                            "policy.schedule", cat="policy", sim_now=t,
                            policy=self.policy.name,
                        ) as psp:
                            wakeup = self.policy.schedule(self)
                            psp.set(
                                running=len(self.running),
                                pending=len(self.pending),
                                wakeup=wakeup,
                            )
                        if wakeup is not None:
                            self.request_wakeup(wakeup)
                        if self.net is not None:
                            self._net_update()
                    sp.set(dirty=dirty).end_sim(self.now)
                n_batches += 1
                self.metrics.sample(
                    self.now, self.cluster, len(self.running), len(self.pending)
                )
            run_sp.set(batches=n_batches).end_sim(self.now)
        if self._lazy:
            self._advance_running(self.now)
        if self.net is not None:
            self.net.close(self.now)
        self._close_attribution()
        if self._cache_telemetry:
            self._harvest_cache_stats()
        return self.metrics.result(self.jobs, self.now)

    def _run_profiled(self) -> SimResult:
        """The ISSUE 10 self-profiling loop body: the plain loop's exact
        call sequence with each segment's wall time charged to a replay
        phase (obs/selfprof.py PHASES).  Replay behavior is byte-
        identical to the plain loop — the clock reads observe, they never
        steer — pinned by tests/test_selfprof.py.

        Phase accounting: fault/warning/repair dispatch is timed inside
        ``_drain_batch`` (the ``prof`` parameter) and subtracted from the
        surrounding event-apply segment, so phases are disjoint; the
        un-segmented residue (heap peeks, the quiescence test, loop
        dispatch) lands in ``other`` at :meth:`PhaseProfiler.finish`, so
        the phase totals sum to the measured total exactly."""
        prof = self._profiler
        # lint: allow[GS101] the self-profiler measures wall time by design (ISSUE 10); replay output stays byte-identical
        perf = time.perf_counter
        prof.start(policy=self.policy.name, jobs=len(self.jobs))
        heap = self._heap
        max_time = self.max_time
        net = self.net
        hazard = self.hazard
        cluster = self.cluster
        running, pending = self.running, self.pending
        policy_schedule = self.policy.schedule
        metrics_sample = self.metrics.sample
        soc = self.sample_on_change
        p_advance = prof.phase("advance")
        p_policy = prof.phase("policy_schedule")
        p_net = prof.phase("net_resolve")
        p_metrics = prof.phase("metrics_emit")
        fault_totals = prof.totals  # read fault_dispatch between clock reads
        # v2 accounting (ISSUE 11): the vector ledger sync is its own
        # phase (``ledger_sync``) so a v2 profile names the new per-batch
        # cost; the lazy path has no per-batch sweep at all and charges
        # nothing here.  Hazard wear integration stays under ``advance``.
        advance = self._advance_running
        adv_phase = p_advance
        if self._ledger is not None:
            if self._lv is not None:
                advance = self._lv.sync_all
                adv_phase = prof.phase("ledger_sync")
            else:
                advance = None
        snapping = self._snap_every is not None
        while heap:
            if self._quiesced():
                break  # only fault/repair/tick residue past the last job
            head = heap[0]
            t = head[0]
            if t > max_time:
                with p_metrics:
                    self._cutoff_at_horizon()
                break
            if snapping and t >= self._snap_next:
                self._snapshot_tick(t)
            self.now = t
            if head[1] == _SAMPLE and not self._whatif_pending:
                # pure-sample batch: same skip as the plain loop (no
                # advance, no metrics.sample, no policy); sample batches
                # can never contain faults (_SAMPLE sorts last), so the
                # whole drain is event application
                t0 = perf()
                self._drain_batch(t)
                prof.add("event_apply", perf() - t0)
                prof.batch_done()
                continue
            if hazard is not None:
                with p_advance:
                    hazard.observe(t, cluster)
            if advance is not None:
                with adv_phase:
                    advance(t)
            mm = self._mask_mut
            f0 = fault_totals["fault_dispatch"]
            t0 = perf()
            dirty = self._drain_batch(t, prof=prof)
            prof.add(
                "event_apply",
                (perf() - t0) - (fault_totals["fault_dispatch"] - f0),
            )
            if dirty:
                if soc and self._mask_mut != mm:
                    with p_metrics:
                        self._emit_sample(t)
                with p_policy:
                    wakeup = policy_schedule(self)
                if wakeup is not None:
                    self.request_wakeup(wakeup)
                if net is not None:
                    with p_net:
                        self._net_update()
            with p_metrics:
                metrics_sample(self.now, cluster, len(running), len(pending))
            prof.batch_done()
        if self._lazy:
            with p_advance:
                self._advance_running(self.now)
        if self.net is not None:
            self.net.close(self.now)
        with p_metrics:
            self._close_attribution()
        if self._cache_telemetry:
            self._harvest_cache_stats()
        with prof.phase("analytics"):
            res = self.metrics.result(self.jobs, self.now)
        prof.finish()
        return res

    # ------------------------------------------------------------------ #
    # what-if speculation (ISSUE 12, sim/whatif.py)

    def run_until(self, t: float) -> None:
        """Advance the replay through every batch at time <= ``t``, then
        pause *between batches* — exactly the instant :meth:`snapshot` /
        :meth:`fork` capture, so a paused engine is a live mirror to
        speculate from.  The loop body is the plain loop's exact call
        sequence; pausing never finalizes (no horizon cutoff, no
        attribution close, no summary — those belong to :meth:`run`,
        which picks up seamlessly), so ``run_until(t)`` followed by
        ``run()`` replays byte-identically to an uninterrupted ``run()``
        (pinned by tests/test_whatif.py)."""
        heap = self._heap
        max_time = self.max_time
        net = self.net
        hazard = self.hazard
        cluster = self.cluster
        running, pending = self.running, self.pending
        policy_schedule = self.policy.schedule
        metrics_sample = self.metrics.sample
        soc = self.sample_on_change
        advance = self._advance_running
        if self._ledger is not None:
            advance = self._lv.sync_all if self._lv is not None else None
        snapping = self._snap_every is not None
        while heap:
            if self._quiesced():
                break
            head = heap[0]
            bt = head[0]
            if bt > t or bt > max_time:
                break
            if snapping and bt >= self._snap_next:
                self._snapshot_tick(bt)
            self.now = bt
            if head[1] == _SAMPLE and not self._whatif_pending:
                self._drain_batch(bt)
                continue
            if hazard is not None:
                hazard.observe(bt, cluster)
            if advance is not None:
                advance(bt)
            mm = self._mask_mut
            if self._drain_batch(bt):
                if soc and self._mask_mut != mm:
                    self._emit_sample(bt)
                wakeup = policy_schedule(self)
                if wakeup is not None:
                    self.request_wakeup(wakeup)
                if net is not None:
                    self._net_update()
            metrics_sample(self.now, cluster, len(running), len(pending))

    def inject_admit(self, job: Job, *, t: Optional[float] = None,
                     pin: Optional[dict] = None) -> Job:
        """Queue a synthetic arrival — the "admit this job (where)?"
        what-if mutation.  ``job`` joins the trace at ``t`` (default:
        now) through the ordinary arrival path (admission control, blame
        tagging, policy pass); ``pin`` (an allocation hint, e.g.
        ``{"pod": 3}``) rides the job as :attr:`Job.pin_hint` and wins
        over the policy's placement hints, so candidate placements are
        comparable across forks.  Meant for speculative forks; calling
        it on a live run legitimately extends that run's trace."""
        at = self.now if t is None else float(t)
        if at < self.now:
            raise ValueError(
                f"inject_admit at {at} is in the past (now={self.now})"
            )
        job.submit_time = at
        job.arrival_seq = len(self.jobs)
        if pin:
            job.pin_hint = dict(pin)
        if self.attribution:
            job.attrib = {}
        if self.faults is not None and self.faults.recovery is not None:
            recovery = self.faults.recovery
            if getattr(recovery, "writes_cost", lambda: False)():
                interval = recovery.checkpoint_interval(job)
                if 0.0 < interval < math.inf:
                    job.ckpt_write_s = recovery.ckpt_write_seconds(
                        job, self.cluster
                    )
                    job.ckpt_every = interval
        self.jobs.append(job)
        self._whatif_pending += 1
        self._push(at, _WHATIF, ("admit", job))
        return job

    def inject_drain(self, scope, *, t: Optional[float] = None,
                     duration: float = math.inf):
        """Schedule a what-if drain: every chip under ``scope`` (e.g.
        ``("pod", 7)``) leaves service at ``t`` (default: now) for
        ``duration`` seconds, as a synthetic ``maintenance`` outage
        riding the ordinary fault path — running gangs revoke with
        checkpoint recovery priced by the armed RecoveryModel (a default
        one is armed when the run had no fault plan), capacity returns
        at the repair.  Answers "drain pod 7 now or at the maintenance
        window?" by forked replay of both variants."""
        at = self.now if t is None else float(t)
        if at < self.now:
            raise ValueError(
                f"inject_drain at {at} is in the past (now={self.now})"
            )
        from gpuschedule_tpu.faults.recovery import FaultPlan
        from gpuschedule_tpu.faults.schedule import FaultRecord

        rec = FaultRecord(at, tuple(scope), float(duration), "maintenance")
        if self.faults is None:
            self.faults = FaultPlan(records=[rec])
        else:
            self.faults.records.append(rec)
        # registered like a scheduled record: snapshot/fork remap the
        # id()-keyed index through the records list, injected or not
        self._fault_ids[id(rec)] = len(self.faults.records) - 1
        self._drain_faults = True
        self._whatif_pending += 1
        self._push(at, _WHATIF, ("fault", rec))
        return rec

    def swap_policy(self, policy) -> None:
        """Replace the scheduling policy mid-replay — the "what if we
        ran SRTF instead?" mutation.  Per-job policy scratch
        (``Job.sched``) is cleared for live jobs so the incoming policy
        derives its own state lazily; engine-owned accounting (progress,
        attained service, attribution legs) carries over untouched.
        Under v2 accounting the ledger rebuilds for the new policy's
        ``reads_progress`` declaration.  A tick is pushed at the swap
        instant so the incoming policy gets an immediate scheduling pass
        — without it the swap would lie dormant until the next dirty
        batch (hours of sim time away on a quiet heap), and a
        policy-swap what-if would under-measure its own delta."""
        for job in self.pending:
            job.sched.clear()
        for job in self.running:
            job.sched.clear()
        self.policy = policy
        if self._lazy:
            from gpuschedule_tpu.sim.ledger import JobLedger

            self._ledger = JobLedger(
                attribution=self.attribution,
                vector=bool(getattr(policy, "reads_progress", True)),
            )
            self._lv = self._ledger if self._ledger.vector else None
            if self._lv is not None:
                for job in self.running:
                    self._lv.bind(job)
        policy.attach(self)
        # request_wakeup drops same-instant ticks; the swap wants one NOW
        self._push(self.now, _TICK)

    def _apply_whatif(self, payload, t: float) -> None:
        """Apply one injected what-if mutation: a synthetic arrival
        (mirroring the _ARRIVAL branch — kept inline there for the hot
        path) or a drain record dispatched down the ordinary fault
        path."""
        self._whatif_pending -= 1
        kind = payload[0]
        if kind == "admit":
            job: Job = payload[1]
            job.last_update_time = t
            self.metrics.count("arrivals")
            self.metrics.count("whatif_admits")
            if not self.cluster.is_satisfiable(job.num_chips):
                job.state = JobState.REJECTED
                job.end_time = t
                self.finished.append(job)
                self.metrics.record_job(job)
                self.metrics.count("rejected_unsatisfiable")
                if self.metrics.record_events:
                    self.metrics.event("reject", t, job, chips=job.num_chips)
                return
            self.pending.append(job)
            cause = None
            if self.attribution:
                cause = self._queue_cause(job)
                self._open_blame(job, cause)
            if self.metrics.record_events:
                extra = {"chips": job.num_chips, "duration": job.duration,
                         "status": job.status}
                if job.ckpt_write_s > 0.0:
                    extra["ckpt_write_s"] = job.ckpt_write_s
                    extra["ckpt_every"] = job.ckpt_every
                if cause is not None:
                    extra["cause"] = cause
                self.metrics.event("arrival", t, job, **extra)
        elif kind == "fault":
            self.metrics.count("whatif_drains")
            self._apply_fault(payload[1])
        else:
            raise ValueError(f"unknown what-if mutation {kind!r}")

    # ------------------------------------------------------------------ #
    # engine snapshot / restore / fork (ISSUE 11 tentpole)

    def _snapshot_tick(self, t: float) -> None:
        """Periodic snapshot trigger (``--snapshot-every``): serialize the
        engine between batches — ``self.now`` is still the previous batch
        time, the heap head at ``t`` is untouched — then re-arm at the
        next multiple past ``t``.  Cold path; the run loops pay one bool
        test per batch when disarmed.

        Tailable-sink contract (ISSUE 15): the snapshot itself flushes
        the event sink (sim/snapshot.py ``snapshot_state``), so the
        on-disk stream is always consistent AT the snapshot instant —
        and a tiny ``<snapshot>.meta.json`` sidecar names that instant,
        so a tailing watchtower (obs/watch.py) can pin "the nearest
        snapshot before the incident" for ``whatif`` replay without
        unpickling the full engine state.  The sidecar is replaced
        BEFORE the snapshot: at every instant the on-disk meta's ``t``
        is >= the on-disk snapshot's, so a concurrent watcher copying
        snap-then-meta can never pair a snapshot with an OLDER sidecar
        (its ``snapshot_t`` may overstate — harmless, ``whatif --at``
        lands at-or-after the restored clock — but never understate)."""
        import json as _json
        import os as _os

        meta = str(self._snap_path) + ".meta.json"
        tmp = meta + ".tmp"
        with open(tmp, "w") as f:
            f.write(_json.dumps(
                {"t": self.now, "snapshot_writes": self._snap_writes + 1},
                sort_keys=True,
            ))
        _os.replace(tmp, meta)
        self.snapshot(self._snap_path)
        every = self._snap_every
        nxt = self._snap_next
        while nxt <= t:
            nxt += every
        self._snap_next = nxt

    def snapshot(self, path) -> None:
        """Serialize the full engine state (jobset, cluster masks and
        counters, heap + lazy-feed cursor, net dirty sets, metrics
        accumulators, event-sink position) to a versioned snapshot file
        (sim/snapshot.py).  Purely observational — the replay's own
        bytes never move; RNG-free by construction since every stochastic
        stream (trace, faults) is pregenerated into specs."""
        from gpuschedule_tpu.sim.snapshot import save_snapshot

        save_snapshot(self, path)
        self._snap_writes += 1

    @classmethod
    def restore(cls, path, *, metrics=None, events_sink=None, profiler=None):
        """Reconstruct a mid-replay simulator from :meth:`snapshot` in a
        fresh process; ``run()`` then finishes the replay.  Under v1
        accounting the resumed tail is byte-identical to the
        uninterrupted run (events.jsonl / jobs.csv / utilization.csv);
        under v2 it is closure-exact (docs/performance.md)."""
        from gpuschedule_tpu.sim.snapshot import load_snapshot

        return load_snapshot(
            path, metrics=metrics, events_sink=events_sink,
            profiler=profiler,
        )

    def fork(self):
        """In-memory speculative copy (the digital-twin primitive): a
        fully independent simulator continuing from this engine's exact
        current state, with the event stream detached so what-if replays
        never write into the parent's outputs.  Counters and utilization
        integrals carry over, so the fork's ``result()`` covers the whole
        history."""
        from gpuschedule_tpu.sim.snapshot import fork_simulator

        return fork_simulator(self)

    # ------------------------------------------------------------------ #
    # cache telemetry (ISSUE 10 tentpole)

    def cache_stats(self) -> Dict[str, Dict[str, int]]:
        """Every engine-visible cache's event counts, unified as
        ``{cache: {outcome: count}}`` — the PR-7/9 lattice made
        observable: the fabric pricing / flow-list / bottleneck-group
        caches (net/), the TPU allocate-failure cache, can_allocate memo
        and bitmask row cache (cluster/tpu.py), and the engine's own
        quiescence memo.  Sources that were never armed (no net model, a
        non-TPU cluster) simply contribute nothing."""
        stats: Dict[str, Dict[str, int]] = {}
        cluster = getattr(self.cluster, "inner", self.cluster)
        for source in (cluster, self.net):
            get = getattr(source, "cache_stats", None)
            if get is not None:
                for name, outcomes in get().items():
                    stats[name] = dict(outcomes)
        stats["quiesce_memo"] = {
            "hit": self._stall_hits, "miss": self._stall_misses,
        }
        if self._ledger is not None:
            # v2 accounting (ISSUE 11): slot churn served in place vs
            # capacity growth
            for name, outcomes in self._ledger.cache_stats().items():
                stats[name] = dict(outcomes)
        if self._snap_writes or self._snap_restores:
            stats["snapshot"] = {
                "write": self._snap_writes, "restore": self._snap_restores,
            }
        return stats

    def _harvest_cache_stats(self) -> None:
        """End-of-run: fold :meth:`cache_stats` into summary counters
        (``cache_<name>_<outcome>``), the labeled registry metric
        (``engine_cache_events{cache,outcome}``), and — when the event
        stream is on — one trailing ``cache`` record the analyzer turns
        into the report's Engine-health table."""
        stats = self.cache_stats()
        emit = {}
        for name in sorted(stats):
            outcomes = stats[name]
            kept = {k: int(v) for k, v in sorted(outcomes.items()) if v}
            if not kept:
                continue
            emit[name] = kept
            for outcome, n in kept.items():
                self.metrics.cache_event(name, outcome, n)
        if self.metrics.record_events:
            self.metrics.event("cache", self.now, None, caches=emit)
