"""Discrete-event simulation engine.

The reference drives its policies from per-policy time-stepped while-loops
(SURVEY.md §3.1: advance clock, charge progress, invoke policy, apply
preemptions).  This engine keeps that contract — progress charging, policy
invocation after every state change, gang-aware start/preempt — but is
event-driven rather than fixed-delta: the clock jumps between arrivals,
(predicted) completions, and policy-requested wakeups ("ticks", used for
Tiresias quanta / Gandiva rounds / Optimus rounds).  Completion events are
predicted from each job's current speed and invalidated by a per-job epoch
counter whenever a preemption/resize changes the prediction, so replay is
exact rather than quantized to a time step.

Single-process, pure Python, no accelerator in the loop (SURVEY.md §3.1:
"pure single-process CPU sim").
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from gpuschedule_tpu.sim.job import END_STATES, Job, JobState
from gpuschedule_tpu.sim.jobset import JobSet
from gpuschedule_tpu.sim.metrics import MetricsLog, SimResult

# Event kinds, in processing-priority order at equal timestamps: completions
# free resources before arrivals are considered, and the policy runs once
# after the whole batch.
_COMPLETION, _ARRIVAL, _TICK = 0, 1, 2


class Simulator:
    """Replay a trace against a cluster under a policy.

    The policy object receives this simulator as its scheduling context and
    mutates job state only through the engine API (:meth:`try_start`,
    :meth:`preempt`, :meth:`set_speed`, :meth:`migrate`), which keeps
    progress accounting and completion prediction consistent.
    """

    def __init__(
        self,
        cluster,
        policy,
        jobs: Sequence[Job],
        *,
        metrics: Optional[MetricsLog] = None,
        max_time: float = float("inf"),
        eps: float = 1e-6,
    ):
        self.cluster = cluster
        self.policy = policy
        # Stable sort: ties on submit_time keep trace order, and each job gets
        # a numeric arrival sequence so policies can tie-break without relying
        # on string job_id ordering (which misorders 'j2' vs 'j10').
        self.jobs: List[Job] = sorted(jobs, key=lambda j: j.submit_time)
        for seq, job in enumerate(self.jobs):
            job.arrival_seq = seq
        self.metrics = metrics or MetricsLog()
        self.metrics.attach_jobs(self.jobs)
        self.max_time = max_time
        self.eps = eps

        self.now: float = 0.0
        # Insertion-ordered, O(1)-mutation sets (see jobset.py): pending keeps
        # arrival order for non-preemptive policies; both make start/preempt/
        # finish constant-time at Philly scale.
        self.pending: JobSet = JobSet()   # submitted, not running, not finished
        self.running: JobSet = JobSet()   # holding allocations
        self.finished: List[Job] = []
        self._heap: list = []
        self._seq = itertools.count()

        for job in self.jobs:
            self._push(job.submit_time, _ARRIVAL, job)
        policy.attach(self)

    # ------------------------------------------------------------------ #
    # event plumbing

    def _push(self, time: float, kind: int, payload=None, epoch: int = 0) -> None:
        heapq.heappush(self._heap, (time, kind, next(self._seq), payload, epoch))

    def request_wakeup(self, time: float) -> None:
        """Policy-facing: ask to be re-invoked at absolute sim time ``time``."""
        if time > self.now + self.eps:
            self._push(time, _TICK)

    def _schedule_completion(self, job: Job) -> None:
        rt = job.remaining_runtime()
        if rt != float("inf"):
            self._push(self.now + rt, _COMPLETION, job, job.epoch)

    def _advance_running(self, t: float) -> None:
        for job in self.running:
            job.advance(t)

    @staticmethod
    def _bind_allocation(job: Job, alloc) -> None:
        """Attach a granted allocation to a job, deriving every allocation-
        dependent field (single site: placement quality feeds progress)."""
        job.allocation = alloc
        job.locality_factor = getattr(alloc.detail, "speed_factor", 1.0)

    # ------------------------------------------------------------------ #
    # policy-facing mutation API

    def try_start(
        self,
        job: Job,
        *,
        chips: Optional[int] = None,
        speed: float = 1.0,
        overhead: float = 0.0,
        placement_hint: Optional[dict] = None,
    ) -> bool:
        """Gang-start (or resume) ``job`` on ``chips`` chips; False if the
        cluster cannot grant a valid allocation (all-or-nothing, SURVEY.md §3.1
        placement step)."""
        if job.state not in (JobState.PENDING, JobState.SUSPENDED):
            raise RuntimeError(f"try_start on non-schedulable job {job!r}")
        if speed <= 0.0:
            # A RUNNING job at speed<=0 never completes and holds chips forever;
            # pausing-in-place is expressed via preempt(suspend=True) instead.
            raise ValueError(f"try_start requires speed > 0, got {speed}")
        chips = chips if chips is not None else job.num_chips
        alloc = self.cluster.allocate(chips, job=job, hint=placement_hint)
        if alloc is None:
            return False
        job.advance(self.now)
        self._bind_allocation(job, alloc)
        job.allocated_chips = chips
        job.state = JobState.RUNNING
        job.speed = speed
        job.overhead_remaining += overhead
        job.epoch += 1
        if job.first_start_time is None:
            job.first_start_time = self.now
        if job in self.pending:
            self.pending.remove(job)
        self.running.append(job)
        self._schedule_completion(job)
        self.metrics.event(
            "start", self.now, job, chips=chips, speed=speed, overhead=overhead
        )
        return True

    def preempt(self, job: Job, *, suspend: bool = True) -> None:
        """Take ``job`` off the cluster.  ``suspend=True`` marks it as a
        time-sliced victim with resume intent (Gandiva); ``suspend=False``
        returns it to the pending queue (Tiresias/SRTF demotion)."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"preempt on non-running job {job!r}")
        job.advance(self.now)
        self.cluster.free(job.allocation)
        job.allocation = None
        job.allocated_chips = 0
        job.speed = 0.0
        job.locality_factor = 1.0
        job.epoch += 1
        job.preempt_count += 1
        job.state = JobState.SUSPENDED if suspend else JobState.PENDING
        self.running.remove(job)
        self.pending.append(job)
        self.metrics.count("preemptions")
        self.metrics.event("preempt", self.now, job, suspend=suspend)

    def set_speed(self, job: Job, speed: float) -> None:
        """Change a running job's progress rate (elastic resize effect)."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"set_speed on non-running job {job!r}")
        if speed <= 0.0:
            raise ValueError(f"set_speed requires speed > 0, got {speed}")
        job.advance(self.now)
        job.speed = speed
        job.epoch += 1
        self._schedule_completion(job)
        self.metrics.event("speed", self.now, job, speed=speed)

    def migrate(self, job: Job, *, overhead: float, placement_hint: Optional[dict] = None) -> bool:
        """Move a running job to a fresh allocation, paying ``overhead``
        seconds of modeled checkpoint/restore cost (SURVEY.md §3.3 migration).

        Returns False — with NO cost charged — when the move didn't happen:
        the hint was unsatisfiable, or first-fit handed back the very slice
        the job already held (a job already at its packed position must not
        be taxed for a no-op "migration")."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"migrate on non-running job {job!r}")
        chips, speed = job.allocated_chips, job.speed
        old_detail = job.allocation.detail if job.allocation is not None else None
        job.advance(self.now)
        self.cluster.free(job.allocation)
        alloc = self.cluster.allocate(chips, job=job, hint=placement_hint)
        if alloc is None:  # hint unsatisfiable; restore in place (no cost charged)
            alloc = self.cluster.allocate(chips, job=job)
            if alloc is None:
                raise RuntimeError(f"allocation vanished during migration of {job!r}")
            # "in place" may still land differently (e.g. a better GPU
            # locality tier): re-derive the factor and re-predict completion,
            # or the stale event computed at the old rate stands
            self._bind_allocation(job, alloc)
            job.epoch += 1
            self._schedule_completion(job)
            return False
        self._bind_allocation(job, alloc)
        if old_detail is not None and alloc.detail == old_detail:
            return False  # same slice re-granted: no movement, no cost
        job.overhead_remaining += overhead
        job.migration_count += 1
        job.epoch += 1
        self._schedule_completion(job)
        self.metrics.count("migrations")
        self.metrics.event("migrate", self.now, job, overhead=overhead)
        return True

    def resize(self, job: Job, *, chips: int, speed: float, overhead: float = 0.0) -> bool:
        """Elastic grow/shrink (Optimus, SURVEY.md §3.2): re-allocate ``job``
        at ``chips`` with new progress rate ``speed``."""
        if job.state is not JobState.RUNNING:
            raise RuntimeError(f"resize on non-running job {job!r}")
        if speed <= 0.0:
            raise ValueError(f"resize requires speed > 0, got {speed}")
        if chips == job.allocated_chips and speed == job.speed:
            return True
        job.advance(self.now)
        self.cluster.free(job.allocation)
        alloc = self.cluster.allocate(chips, job=job)
        if alloc is None:
            alloc = self.cluster.allocate(job.allocated_chips, job=job)
            if alloc is None:
                raise RuntimeError(f"allocation vanished during resize of {job!r}")
            self._bind_allocation(job, alloc)
            job.epoch += 1
            self._schedule_completion(job)
            return False
        self._bind_allocation(job, alloc)
        job.allocated_chips = chips
        job.speed = speed
        job.overhead_remaining += overhead
        job.epoch += 1
        self._schedule_completion(job)
        self.metrics.event("resize", self.now, job, chips=chips, speed=speed)
        return True

    # ------------------------------------------------------------------ #

    def _finish(self, job: Job) -> None:
        job.advance(self.now)
        job.executed_work = job.duration  # absorb float residue
        self.cluster.free(job.allocation)
        job.allocation = None
        job.allocated_chips = 0
        job.speed = 0.0
        job.epoch += 1
        job.state = job.end_state
        job.end_time = self.now
        self.running.remove(job)
        self.finished.append(job)
        self.metrics.record_job(job)
        self.metrics.event("finish", self.now, job, end_state=job.state.value)

    def run(self) -> SimResult:
        """Drive the event loop to completion and return summary metrics."""
        while self._heap:
            t = self._heap[0][0]
            if t > self.max_time:
                # Horizon cutoff: charge running jobs up to max_time so
                # executed work and utilization cover the full simulated span.
                self.now = self.max_time
                self._advance_running(self.max_time)
                self.metrics.sample(
                    self.now, self.cluster, len(self.running), len(self.pending)
                )
                break
            self.now = t
            self._advance_running(t)
            dirty = False
            while self._heap and self._heap[0][0] <= t:
                _, kind, _, payload, epoch = heapq.heappop(self._heap)
                if kind == _ARRIVAL:
                    job: Job = payload
                    job.last_update_time = t
                    self.metrics.count("arrivals")
                    if not self.cluster.is_satisfiable(job.num_chips):
                        # Admission control: this gang size can never be
                        # granted here (non-slice size, bigger than a pod).
                        # Reject now instead of letting it wedge priority
                        # schedulers that would reserve budget for it forever.
                        # REJECTED is excluded from JCT/makespan aggregates
                        # (metrics.result), so rejecting clusters don't score
                        # artificially good headline numbers.
                        job.state = JobState.REJECTED
                        job.end_time = t
                        self.finished.append(job)
                        self.metrics.record_job(job)
                        self.metrics.count("rejected_unsatisfiable")
                        self.metrics.event("reject", t, job, chips=job.num_chips)
                    else:
                        self.pending.append(job)
                        self.metrics.event("arrival", t, job, chips=job.num_chips)
                    dirty = True
                elif kind == _COMPLETION:
                    job = payload
                    if job.epoch != epoch or job.state is not JobState.RUNNING:
                        continue  # stale prediction from before a preempt/resize
                    if job.remaining_runtime() > self.eps:
                        # speed changed without epoch bump — repredict
                        self._schedule_completion(job)
                        continue
                    self._finish(job)
                    dirty = True
                else:  # _TICK
                    dirty = True
            if dirty:
                wakeup = self.policy.schedule(self)
                if wakeup is not None:
                    self.request_wakeup(wakeup)
            self.metrics.sample(self.now, self.cluster, len(self.running), len(self.pending))
        return self.metrics.result(self.jobs, self.now)
