"""Metrics and logging: per-job CSV, cluster-utilization samples, summaries.

Matches the reference's logging surface (SURVEY.md §2 "Metrics/log", §8 in the
layer map): per-job rows (submit/start/end → JCT, queueing delay), per-event
cluster utilization samples, and an end-of-run summary whose headline numbers
are **average JCT** and **makespan** (the BASELINE.json contract metrics),
plus 95th-percentile queueing delay (SURVEY.md §3.1 summary line).
"""

from __future__ import annotations

import csv
import json
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import IO, Dict, List, Optional, Sequence, Union

from gpuschedule_tpu.sim.job import Job, JobState

# JCT/queueing-delay histogram buckets for the obs registry: seconds to a
# week, the span Philly-scale replays actually cover.
_DELAY_BUCKETS = (
    60.0, 300.0, 900.0, 3600.0, 4 * 3600.0, 24 * 3600.0, 7 * 24 * 3600.0,
    float("inf"),
)

# Streamed-sink write batching (ISSUE 7 satellite): events accumulate in
# an in-process buffer and hit the sink in one write() per this many
# records, instead of one write() per event.  The flush contract is
# explicit: flush_events() / close_events() / write() force the buffer
# down (the MetricsLog context manager guarantees it on engine crashes —
# the pinned crash-flush regression); until then the tail of the stream
# may sit in the buffer.
_SINK_BUFFER_RECORDS = 512

# Version of the JSONL event-stream schema.  The stream's first record is a
# header ``{"schema": EVENT_SCHEMA, "run_id", "seed", "policy",
# "config_hash", ...}`` when the run supplies ``run_meta``; readers
# (obs/analyze.py) refuse streams whose header is missing or from a
# different schema version instead of silently mis-reconstructing.  Bump
# this when event payloads change incompatibly (docs/events.md records the
# policy).
EVENT_SCHEMA = 1

JOB_CSV_FIELDS = [
    "job_id",
    "num_chips",
    "submit_time",
    "first_start_time",
    "end_time",
    "jct",
    "queueing_delay",
    "slowdown",
    "executed_work",
    "attained_service",
    "preempt_count",
    "migration_count",
    "fault_count",
    "lost_work",
    "status",
    "end_state",
    "model_name",
]


def _percentile(values: Sequence[float], p: float) -> float:
    """Nearest-rank percentile on a copy-sorted list (no numpy dependency in
    the sim core)."""
    if not values:
        return 0.0
    s = sorted(values)
    k = max(0, min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1)))))
    return s[k]


@dataclass
class SimResult:
    """End-of-run summary. ``jobs`` holds the full per-job records."""

    avg_jct: float
    makespan: float
    p95_queueing_delay: float
    mean_utilization: float
    num_finished: int
    num_unfinished: int
    counters: Dict[str, int]
    end_time: float
    num_rejected: int = 0
    # Fairness tail: slowdown = JCT / dedicated-run duration per job
    # (sim/job.py).  avg JCT rewards policies that favor short jobs;
    # these expose what that costs the worst-treated job (Themis's
    # objective is minimizing exactly this tail).
    p95_slowdown: float = 0.0
    max_slowdown: float = 0.0
    # Trace-declared end states among the finished jobs (a faithful Philly
    # replay surfaces Failed/Killed terminals, not just a finished count).
    num_done: int = 0
    num_failed: int = 0
    num_killed: int = 0
    # Goodput decomposition in chip-seconds (faults/): every chip-second of
    # service went to exactly one leg — work that survived to the end
    # ("useful"), work a later fault rolled back ("lost"), or modeled
    # restart/migration/restore overhead.  useful + lost + overhead ==
    # total by construction.  "total" is per-job service time (each job's
    # allocated_chips x held seconds); under Gandiva overlay packing two
    # jobs sharing one slice each accrue their own service, so the total
    # can exceed physical occupancy — it equals it exactly when nothing
    # is packed.
    goodput: Dict[str, float] = field(default_factory=dict)
    # Causal attribution (ISSUE 5): per-cause delay/run legs in seconds,
    # summed over per-job ``Job.attrib`` dicts in arrival order — the same
    # order and arithmetic obs/analyze.py uses, so the analyzer's
    # ``delay_by_cause()`` equals this to the last float (the wait-
    # decomposition closure, like the goodput one).  Empty unless the run
    # was captured with ``MetricsLog(attribution=True)``.
    delay_by_cause: Dict[str, float] = field(default_factory=dict)
    jobs: List[Job] = field(repr=False, default_factory=list)

    def summary(self) -> Dict[str, float]:
        return {
            "avg_jct": self.avg_jct,
            "makespan": self.makespan,
            "p95_queueing_delay": self.p95_queueing_delay,
            "p95_slowdown": self.p95_slowdown,
            "max_slowdown": self.max_slowdown,
            "mean_utilization": self.mean_utilization,
            "num_finished": self.num_finished,
            "num_unfinished": self.num_unfinished,
            "num_rejected": self.num_rejected,
            "num_done": self.num_done,
            "num_failed": self.num_failed,
            "num_killed": self.num_killed,
            **{f"goodput_{k}": v for k, v in self.goodput.items()},
            # only attribution-armed runs carry these keys, so the
            # attribution-off stdout contract stays byte-identical
            **{
                f"delay_{k.replace('-', '_')}_s": v
                for k, v in sorted(self.delay_by_cause.items())
            },
            **{k: float(v) for k, v in self.counters.items()},
        }


class MetricsLog:
    """Accumulates job records and utilization samples during a run.

    The time-weighted utilization summary is integrated incrementally at
    every :meth:`sample` call, so it stays exact regardless of how many
    samples are *stored*: storage is capped at ``max_util_samples`` by
    stride-doubling decimation (keep every 2nd, then every 4th, ...), which
    bounds memory on Philly-scale traces (10^5 jobs -> ~10^6 event samples)
    while the persisted utilization.csv remains a uniform subsample.
    """

    def __init__(
        self,
        *,
        max_util_samples: int = 200_000,
        record_events: bool = False,
        events_sink: Optional[Union[str, Path, IO]] = None,
        registry=None,
        run_meta: Optional[dict] = None,
        attribution: bool = False,
        cache_telemetry: bool = False,
        flush_interval_s: Optional[float] = None,
    ) -> None:
        self.job_rows: List[dict] = []
        # Cache telemetry (ISSUE 10): when armed, the engine harvests
        # every PR-7/9 cache's hit/miss/invalidate counts at the end of
        # the run through :meth:`cache_event` — summary counters gain
        # ``cache_<name>_<outcome>`` keys, the registry (when attached)
        # gains the labeled ``engine_cache_events`` family, and the event
        # stream a trailing ``cache`` record.  Off (the default) the
        # summary/stream/registry stay byte-identical to pre-telemetry.
        self.cache_telemetry = bool(cache_telemetry)
        self._reg_cache_events = None
        # Causal attribution (ISSUE 5): when True the engine blames every
        # queued interval with its cause, splits running time into
        # slowdown legs (sim/job.py WAIT_CAUSES / RUN_LEGS), and stamps
        # the cumulative legs onto lifecycle events.  Off by default —
        # the off path emits byte-identical streams, jobs.csv, and
        # summaries (the ISSUE 5 regression contract).
        self.attribution = bool(attribution)
        # Structured event stream (SURVEY.md §5 "Metrics/logging": CSVs plus
        # a structured JSONL event log).  Off by default: at Philly scale the
        # stream is ~10^6 dicts, so it is opt-in (CLI --events).
        #
        # ``events_sink`` (a path or an open text file) streams each event to
        # JSONL as it happens instead of buffering: the in-memory list stays
        # empty, so Philly-scale runs no longer hold ~10^6 dicts in RAM just
        # to persist them at write() time (ISSUE 1 satellite).  Passing a
        # sink implies ``record_events``.
        self.record_events = record_events or events_sink is not None
        self.events: List[dict] = []
        # Event-stream header (ISSUE 3 satellite): when the caller identifies
        # the run (run_id/seed/policy/config_hash, CLI does), the first
        # record emitted is a schema-versioned header so readers can refuse
        # mismatched or concatenated streams.  None (the default, every
        # pre-existing caller) emits no header and the stream is exactly the
        # bare transition log it always was.
        self.run_meta = dict(run_meta) if run_meta is not None else None
        self._header_emitted = False
        self._sink_path: Optional[Path] = None
        self._sink_fh: Optional[IO] = None
        self._owns_sink = False
        self._sink_opened = False
        self._sink_buf: List[str] = []  # pending JSONL lines (flush contract)
        if events_sink is not None:
            if hasattr(events_sink, "write"):
                self._sink_fh = events_sink
            else:
                self._sink_path = Path(events_sink)
        # Tailable-sink flush cadence (ISSUE 15): batching alone lets the
        # tail of a live stream sit in the buffer for an unbounded sim
        # span (512 records can be hours of a quiet replay), which a
        # tailing watcher (obs/watch.py) would read as a stalled cluster.
        # ``flush_interval_s`` arms a SIM-TIME cadence: whenever an event
        # lands at or past the next multiple, the buffer AND the file
        # handle flush, so the on-disk stream is never more than one
        # interval of sim time behind the replay.  None (the default)
        # keeps the pure 512-record batching — byte-for-byte the
        # historical write pattern.  The engine's periodic snapshots
        # flush independently (sim/snapshot.py snapshot_state), so a
        # snapshot always lands on a stream consistent AT its instant —
        # the watchtower's flight-recorder handshake.
        if flush_interval_s is not None and flush_interval_s <= 0.0:
            raise ValueError(
                f"flush_interval_s must be > 0 seconds, got {flush_interval_s}"
            )
        self._flush_every = flush_interval_s
        self._flush_next = flush_interval_s if flush_interval_s else None
        # Optional obs-layer registry (obs/metrics.py): counters mirror into
        # Prometheus counter families, per-job records feed JCT/queueing
        # histograms, and every utilization sample updates the occupancy
        # gauges.  None (the default) costs one attribute check per call.
        self._registry = registry
        self._reg_counters: Dict[str, object] = {}  # count() key -> Counter
        if registry is not None:
            self._reg_running = registry.gauge(
                "sim_jobs_running", "jobs holding allocations")
            self._reg_pending = registry.gauge(
                "sim_jobs_pending", "jobs queued for allocations")
            self._reg_used = registry.gauge(
                "sim_chips_used", "chips currently allocated")
            self._reg_total = registry.gauge(
                "sim_chips_total", "cluster capacity in chips")
            self._reg_jct = registry.histogram(
                "sim_jct_seconds", "job completion time", buckets=_DELAY_BUCKETS)
            self._reg_queue = registry.histogram(
                "sim_queueing_delay_seconds", "submit-to-first-start delay",
                buckets=_DELAY_BUCKETS)
            self._reg_end_state = registry.counter(
                "sim_jobs_end_state_total",
                "terminal job states (trace-declared Pass/Failed/Killed "
                "plus admission rejections)",
                labelnames=("state",))
        # net/ link gauges are created lazily on the first sample: a run
        # without the contention model must leave the registry (and its
        # metrics.prom bytes) exactly as before the net layer existed
        self._reg_net_util = None
        self._reg_net_gbps = None
        self.util_samples: List[tuple] = []  # (t, used, total, running, pending)
        self.counters: Counter = Counter()
        self._all_jobs: Sequence[Job] = ()   # set by attach_jobs(); lets write()
                                             # emit rows for unfinished jobs too
        self.max_util_samples = max(2, max_util_samples)
        self._stride = 1                     # store every _stride-th sample
        self._sample_calls = 0
        self._last_t: Optional[float] = None
        self._last_frac = 0.0                # used/total at the previous sample
        self._util_area = 0.0                # integral of (used/total) dt
        self._util_horizon = 0.0             # total dt with total > 0
        self._tail: Optional[tuple] = None   # most recent sample, always kept

    def attach_jobs(self, jobs: Sequence[Job]) -> None:
        """Register the full job list (engine does this at construction) so
        :meth:`write` can emit rows for unfinished jobs even if the run aborts
        before :meth:`result` is reached."""
        self._all_jobs = jobs

    # ------------------------------------------------------------------ #
    def count(self, key: str, n: int = 1) -> None:
        self.counters[key] += n
        if self._registry is not None:
            c = self._reg_counters.get(key)
            if c is None:
                # resolve the family once per key: sanitize + registry lock
                # stay off the per-event hot path
                c = self._registry.counter(
                    f"sim_{key}_total", "engine counter (MetricsLog)")
                self._reg_counters[key] = c
            c.inc(n)

    def cache_event(self, cache: str, outcome: str, n: int = 1) -> None:
        """One unified cache-telemetry event (ISSUE 10): mirrors into the
        plain summary counter ``cache_<cache>_<outcome>`` and, with a
        registry attached, the labeled counter family
        ``engine_cache_events{cache=...,outcome=...}`` — one surface for
        what used to be ad-hoc per-subsystem counters."""
        self.counters[f"cache_{cache}_{outcome}"] += n
        if self._registry is not None:
            if self._reg_cache_events is None:
                self._reg_cache_events = self._registry.counter(
                    "engine_cache_events",
                    "engine cache events by cache and outcome "
                    "(hit / miss / invalidate / fallback)",
                    labelnames=("cache", "outcome"),
                )
            self._reg_cache_events.labels(cache, outcome).inc(n)

    def _sink(self) -> Optional[IO]:
        if self._sink_fh is not None:
            return self._sink_fh
        if self._sink_path is not None:
            self._sink_path.parent.mkdir(parents=True, exist_ok=True)
            # "a" on reopen: a close_events()/write() mid-run must not let a
            # later event truncate everything streamed before it
            self._sink_fh = open(self._sink_path, "w" if not self._sink_opened else "a")
            self._owns_sink = self._sink_opened = True
            return self._sink_fh
        return None

    def set_run_meta(self, **fields) -> None:
        """Merge identifying fields into the pending event-stream header
        (no-op once the header has been written — identity is immutable
        after the first event)."""
        if self._header_emitted:
            return
        if self.run_meta is None:
            self.run_meta = {}
        self.run_meta.update(fields)

    def _emit_record(self, rec: dict) -> None:
        if self._sink_fh is not None or self._sink_path is not None:
            # buffered streaming (ISSUE 7 satellite): one write() per
            # _SINK_BUFFER_RECORDS events instead of one per event; the
            # explicit flush contract (flush_events/close_events/write)
            # forces the tail down
            buf = self._sink_buf
            buf.append(json.dumps(rec) + "\n")
            if len(buf) >= _SINK_BUFFER_RECORDS:
                self.flush_events()
        else:
            self.events.append(rec)

    def flush_events(self) -> None:
        """Push buffered event lines to the sink in a single write().
        Part of the explicit flush contract: callers that need the stream
        durable mid-run (tailing a live replay, handing the file to a
        reader) call this; :meth:`close_events` and :meth:`write` call it
        for you."""
        if self._sink_buf:
            sink = self._sink()
            if sink is not None:
                sink.write("".join(self._sink_buf))
            self._sink_buf.clear()

    def _emit_header(self) -> None:
        """Write the schema-versioned header record ahead of the first
        event (lazy so ``set_run_meta`` calls between construction and the
        first transition — the Simulator fills in policy/cluster facts —
        all land in it)."""
        if self._header_emitted or self.run_meta is None:
            return
        self._header_emitted = True
        self._emit_record({"schema": EVENT_SCHEMA, **self.run_meta})

    def event(self, kind: str, t: float, job: Optional[Job] = None, **extra) -> None:
        """Record one structured event (no-op unless ``record_events``):
        streamed straight to the JSONL sink when one is configured, buffered
        in :attr:`events` otherwise."""
        if not self.record_events:
            return
        if not self._header_emitted:
            self._emit_header()
        rec: dict = {"t": t, "event": kind}
        if job is not None:
            rec["job"] = job.job_id
        rec.update(extra)
        self._emit_record(rec)
        if self._flush_every is not None and t >= self._flush_next:
            # tailable-sink cadence (ISSUE 15): make everything up to and
            # including this event durable, down to the OS
            self.flush_events()
            if self._sink_fh is not None:
                self._sink_fh.flush()
            nxt = self._flush_next
            while nxt <= t:
                nxt += self._flush_every
            self._flush_next = nxt

    def close_events(self) -> None:
        """Flush (buffer included) and — when this log opened it — close
        the JSONL sink.  Safe to call repeatedly; :meth:`write` calls it
        for you."""
        self.flush_events()
        if self._sink_fh is not None:
            self._sink_fh.flush()
            if self._owns_sink:
                self._sink_fh.close()
                self._sink_fh = None
                self._owns_sink = False

    def __enter__(self) -> "MetricsLog":
        """Context-manager path (ISSUE 3 satellite): guarantees the JSONL
        sink is flushed/closed even when the engine raises mid-run, so a
        crashed replay still leaves an analyzable stream behind."""
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close_events()

    @staticmethod
    def _job_row(job: Job) -> dict:
        """One jobs.csv row; end_time/jct are None while a job is unfinished."""
        return {
            "job_id": job.job_id,
            "num_chips": job.num_chips,
            "submit_time": job.submit_time,
            "first_start_time": job.first_start_time,
            "end_time": job.end_time,
            "jct": job.jct(),
            "queueing_delay": job.queueing_delay(),
            "slowdown": job.slowdown(),
            "executed_work": round(job.executed_work, 6),
            "attained_service": round(job.attained_service, 6),
            "preempt_count": job.preempt_count,
            "migration_count": job.migration_count,
            "fault_count": job.fault_count,
            "lost_work": round(job.lost_work, 6),
            "status": job.status,
            "end_state": job.state.value,
            "model_name": job.model_name,
        }

    def record_job(self, job: Job) -> None:
        self.job_rows.append(self._job_row(job))
        if self._registry is not None:
            self._reg_end_state.labels(job.state.value).inc()
            if job.state is not JobState.REJECTED:
                j = job.jct()
                if j is not None:
                    self._reg_jct.observe(j)
                q = job.queueing_delay()
                if q is not None:
                    self._reg_queue.observe(q)

    def sample(self, t: float, cluster, num_running: int, num_pending: int) -> None:
        used, total = cluster.used_chips, cluster.total_chips
        # Exact piecewise-constant integral: occupancy over [last_t, t) is
        # whatever the previous sample observed.
        if self._last_t is not None and total > 0 and t > self._last_t:
            dt = t - self._last_t
            self._util_area += self._last_frac * dt
            self._util_horizon += dt
        self._last_t = t
        self._last_frac = used / total if total > 0 else 0.0

        if self._registry is not None:
            self._reg_running.set(num_running)
            self._reg_pending.set(num_pending)
            self._reg_used.set(used)
            self._reg_total.set(total)

        self._tail = (t, used, total, num_running, num_pending)
        if self._sample_calls % self._stride == 0:
            self.util_samples.append(self._tail)
            if len(self.util_samples) > self.max_util_samples:
                self.util_samples = self.util_samples[::2]
                self._stride *= 2
        self._sample_calls += 1

    def net_link_samples(self, links) -> None:
        """Mirror the contention model's per-link load into labeled
        registry gauges (net/ tentpole observability).  No-op without a
        registry; gauges materialize on the first call so net-free runs
        keep a byte-identical Prometheus exposition."""
        if self._registry is None or not links:
            return
        if self._reg_net_util is None:
            self._reg_net_util = self._registry.gauge(
                "net_link_utilization",
                "fraction of DCN link capacity in use (ingest + allreduce)",
                labelnames=("link",))
            self._reg_net_gbps = self._registry.gauge(
                "net_link_used_gbps",
                "DCN link load in Gbps (ingest + allreduce)",
                labelnames=("link",))
        for name, sample in links.items():
            self._reg_net_util.labels(name).set(sample.util)
            self._reg_net_gbps.labels(name).set(sample.used_gbps)

    def _flush_tail(self) -> None:
        """Ensure the final observed sample is stored: once decimation raises
        the stride, the last call is usually not a stride multiple, and the
        persisted log would end before the simulation does.

        Idempotent by construction — the tail is only appended when it is not
        already the stored last sample — so ``write()`` twice, or ``write()``
        followed by ``result()``, never duplicates it even right after a
        stride-doubling decimation dropped it (the regression pinned by
        tests/test_events.py::test_write_idempotent_after_flush_tail)."""
        if self._tail is not None and (
            not self.util_samples or self.util_samples[-1] != self._tail
        ):
            self.util_samples.append(self._tail)

    # ------------------------------------------------------------------ #
    def result(self, jobs: Sequence[Job], end_time: float) -> SimResult:
        self._flush_tail()
        # Admission-rejected jobs never ran: counting their 0-second "JCT"
        # would flatter clusters that reject more, so they are excluded from
        # every aggregate and surfaced via the num_rejected field /
        # rejected_unsatisfiable counter instead.
        finished = [
            j for j in jobs if j.end_time is not None and j.state is not JobState.REJECTED
        ]
        jcts = [j.jct() for j in finished]
        qdelays = [j.queueing_delay() for j in finished if j.queueing_delay() is not None]
        slowdowns = [j.slowdown() for j in finished if j.slowdown() is not None]
        if finished:
            start = min(j.submit_time for j in finished)
            makespan = max(j.end_time for j in finished) - start
        else:
            makespan = 0.0
        # Time-weighted mean utilization, integrated incrementally in sample()
        # (exact even when the stored sample list has been decimated).
        util = self._util_area / self._util_horizon if self._util_horizon > 0 else 0.0
        rejected = sum(1 for j in jobs if j.state is JobState.REJECTED)
        states = Counter(j.state for j in finished)
        # Goodput decomposition over ALL jobs (unfinished ones occupied
        # chips too): attained_service splits into the surviving and the
        # fault-rolled-back share, overhead_service is the third leg.
        attained = sum(j.attained_service for j in jobs)
        lost = sum(j.lost_service for j in jobs)
        overhead = sum(j.overhead_service for j in jobs)
        goodput = {
            "useful_chip_s": attained - lost,
            "lost_chip_s": lost,
            "restart_overhead_chip_s": overhead,
            "total_chip_s": attained + overhead,
        }
        # Attribution legs summed per cause, jobs in arrival order with
        # sorted keys per job — obs/analyze.py mirrors this arithmetic
        # exactly, which is what makes the wait-decomposition closure
        # bit-exact (same floats, same additions, same order).
        delay_by_cause: Dict[str, float] = {}
        for j in jobs:
            if j.attrib:
                for k in sorted(j.attrib):
                    delay_by_cause[k] = delay_by_cause.get(k, 0.0) + j.attrib[k]
        return SimResult(
            avg_jct=sum(jcts) / len(jcts) if jcts else 0.0,
            makespan=makespan,
            p95_queueing_delay=_percentile(qdelays, 95.0),
            p95_slowdown=_percentile(slowdowns, 95.0),
            max_slowdown=max(slowdowns) if slowdowns else 0.0,
            mean_utilization=util,
            num_finished=len(finished),
            num_unfinished=len(jobs) - len(finished) - rejected,
            counters=dict(self.counters),
            end_time=end_time,
            num_rejected=rejected,
            num_done=states[JobState.DONE],
            num_failed=states[JobState.FAILED],
            num_killed=states[JobState.KILLED],
            goodput=goodput,
            delay_by_cause=delay_by_cause,
            jobs=list(jobs),
        )

    # ------------------------------------------------------------------ #
    def write(self, out_dir: str | Path, *, prefix: str = "") -> None:
        """Write job-level and utilization CSVs plus a counters JSON."""
        self._flush_tail()
        out = Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        # Finished jobs were recorded incrementally; unfinished jobs (horizon
        # cutoff) get a row with empty end_time/jct so the persisted log covers
        # the whole trace.
        extra_rows = [
            self._job_row(j) for j in self._all_jobs if j.end_time is None
        ]
        with open(out / f"{prefix}jobs.csv", "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=JOB_CSV_FIELDS)
            w.writeheader()
            w.writerows(self.job_rows)
            w.writerows(extra_rows)
        with open(out / f"{prefix}utilization.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["time", "used_chips", "total_chips", "running", "pending"])
            w.writerows(self.util_samples)
        with open(out / f"{prefix}counters.json", "w") as f:
            json.dump(dict(self.counters), f, indent=2, sort_keys=True)
        if self.record_events:
            if self._sink_path is not None or self._sink_fh is not None:
                # streamed as they happened; just make them durable.  A
                # zero-event run never opened its lazy path sink — force the
                # file into existence (header included, when armed) so the
                # JSONL is always there, exactly as the buffered branch
                # below guarantees.
                if self._sink_path is not None and not self._sink_opened:
                    self._sink()
                self._emit_header()
                self.close_events()
            else:
                self._emit_header()  # zero-event buffered run, header armed
                with open(out / f"{prefix}events.jsonl", "w") as f:
                    for rec in self.events:
                        f.write(json.dumps(rec) + "\n")
