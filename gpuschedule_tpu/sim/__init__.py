"""Simulation core: job model, traces, discrete-event engine, metrics.

This layer is deliberately JAX-free: trace replay must run end-to-end with no
accelerator in the loop (BASELINE.json north_star; SURVEY.md §4).
"""

from gpuschedule_tpu.sim.job import Job, JobState
from gpuschedule_tpu.sim.jobset import JobSet
from gpuschedule_tpu.sim.engine import Simulator, SimResult

__all__ = ["Job", "JobState", "JobSet", "Simulator", "SimResult"]
