"""Modeled checkpoint/restore costs, parameterized per model and slice size.

The reference charges fixed suspend/resume and migration constants
(SURVEY.md §5 "Checkpoint / resume": costs are modeled, not real).  This
module is the TPU-native refinement the round-1 verdict asked for
("parameterized per slice size"): the cost of bringing a preempted job back
is a fixed framework setup term plus the time to stream its training state
back through the hosts that feed the slice —

    restore_s(model, chips, gen) = base_s
        + ckpt_bytes(model) / (hosts(chips, gen) * host_gbps / 8 * 1e9)

- ``ckpt_bytes`` = 12 bytes/param (f32 master weights + two Adam moments),
  i.e. what orbax would actually persist for the train states built in
  :mod:`gpuschedule_tpu.parallel.train`;
- ``hosts`` = chips / chips_per_host for the generation: a bigger slice has
  more hosts pulling shards in parallel, so restore *speeds up* with slice
  size while growing with model size — the shape the fixed constants miss;
- migration pays save + restore (2x the transfer) on top of the base term.

Pure Python over :data:`~gpuschedule_tpu.models.config.MODEL_CONFIGS` and
the generation table — no jax import (sim-core rule).

**Measured vs modeled** (round-5; tests/test_elastic_loop.py): an
engine-driven Optimus shrink executing the REAL orbax save+restore of a
transformer-tiny ShardedTrainer (8 -> 4 devices, ~17 MB of train state,
CPU mesh + local disk) measures ~0.3-3 s of mechanism time against
``migrate_seconds('transformer-tiny', 4)`` ~= 5.0 s — the same order of
magnitude, with the modeled figure dominated by the ``base_s`` floor
standing in for process-restart/compile costs the in-process measurement
does not pay.  The test pins the agreement to within two orders.
"""

from __future__ import annotations

import math

from gpuschedule_tpu.cluster.tpu import DCN_GBPS, GENERATIONS
from gpuschedule_tpu.models.config import resolve_model_config

# Framework teardown/setup floor (process restart, compile-cache hit, data
# pipeline rewind) — the part of Gandiva's observed suspend/resume cost that
# does not scale with state size.
DEFAULT_BASE_S = 5.0

BYTES_PER_PARAM = 12  # f32 params + 2 Adam moments


def ckpt_bytes(model_name: str) -> int:
    """Persisted training-state size for a model (params + opt state).

    Unknown model names (e.g. straight from a Philly trace) resolve through
    the shared zoo-median fallback (models/config.py), the same phantom
    model that prices their DCN toll — one job, one consistent size."""
    return BYTES_PER_PARAM * resolve_model_config(model_name).param_count


def restore_seconds(
    model_name: str,
    chips: int,
    *,
    generation: str = "v5e",
    base_s: float = DEFAULT_BASE_S,
    host_gbps: float = DCN_GBPS,
    round_trips: int = 1,
) -> float:
    """Seconds to resume a suspended job on a ``chips``-chip slice.

    ``round_trips=2`` models migration (save on the old slice + load on the
    new one); 1 models resume-from-checkpoint where the save already
    happened at suspend time, off the critical path.
    """
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    spec = GENERATIONS[generation]
    hosts = max(1, math.ceil(chips / spec["chips_per_host"]))
    bytes_per_s = hosts * host_gbps * 1e9 / 8.0
    return base_s + round_trips * ckpt_bytes(model_name) / bytes_per_s


def migrate_seconds(model_name: str, chips: int, *, generation: str = "v5e") -> float:
    """Modeled migration cost: save + restore across congruent slices."""
    return restore_seconds(model_name, chips, generation=generation, round_trips=2)


# Fixed floor of one checkpoint *write*: flushing device buffers and
# committing the manifest — much smaller than the restore floor because no
# process restart or compile is on this path.
CKPT_WRITE_BASE_S = 1.0


def ckpt_write_seconds(
    model_name: str,
    chips: int,
    *,
    generation: str = "v5e",
    base_s: float = CKPT_WRITE_BASE_S,
    host_gbps: float = DCN_GBPS,
) -> float:
    """Seconds one periodic checkpoint WRITE takes on a ``chips``-chip
    slice: the same state-streaming transfer as :func:`restore_seconds`
    (every host pushes its shard in parallel, so bigger slices write
    faster while bigger models write slower) over a much smaller fixed
    floor.  This is what ``RecoveryModel.ckpt_write="auto"`` charges the
    ``overhead`` leg every ``ckpt_interval`` work-seconds — the priced-
    recovery half of the checkpoint trade (the other half is the lost
    work a revocation rolls back)."""
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    spec = GENERATIONS[generation]
    hosts = max(1, math.ceil(chips / spec["chips_per_host"]))
    bytes_per_s = hosts * host_gbps * 1e9 / 8.0
    return base_s + ckpt_bytes(model_name) / bytes_per_s


def cluster_generation(cluster) -> str:
    """Best-effort generation lookup for overhead modeling (v5e default)."""
    gen = getattr(cluster, "generation", None)
    return gen if gen in GENERATIONS else "v5e"


def resolve_overhead(spec, job, cluster, *, migration: bool = False) -> float:
    """Interpret a policy's overhead knob: a number is used as-is; the
    string ``"auto"`` derives the cost from the job's model and gang size."""
    if spec == "auto":
        fn = migrate_seconds if migration else restore_seconds
        return fn(
            job.model_name,
            max(1, job.allocated_chips or job.num_chips),
            generation=cluster_generation(cluster),
        )
    return float(spec)
