"""Insertion-ordered job set with O(1) membership, append, and remove.

The engine's ``pending``/``running`` collections were plain lists in the
first cut; ``list.remove`` made every start/preempt/finish O(n), which turns
Philly-scale replays (10^5 jobs) into O(n^2) hot loops (SURVEY.md §3.1 "hot
spot": placement search + queue re-sort per step).  This dict-backed set
keeps the list API the policies already use (iteration in insertion order,
``len``, truthiness, ``in``, indexing, ``+``) while making the engine's
mutations constant-time.

Insertion order is a real invariant, not an accident: arrivals enter
``pending`` in (submit_time, arrival_seq) order because the event heap pops
them that way, so a non-preemptive policy (FIFO) can consume ``pending`` in
arrival order with no per-event sort.  Preemptive policies re-append
preempted jobs at the tail and impose their own priority order anyway.

The backing store is an ``OrderedDict`` — a real doubly-linked list —
not a plain dict (ISSUE 9).  A plain dict keeps deleted entries as
tombstones until an insert-triggered resize compacts them, so the
front-heavy churn these sets live under (FIFO consumes the head, the
engine removes finished jobs constantly) makes "first element" and
iteration scan an ever-growing tombstone run: at million-job scale the
end-of-trace drain — all deletions, no inserts, so no compaction ever —
went quadratic in the backlog and dominated the whole replay.  The
linked list makes head access and iteration O(live entries), always.
"""

from __future__ import annotations

from collections import OrderedDict
from itertools import islice
from typing import Iterable, Iterator, List

from gpuschedule_tpu.sim.job import Job


class JobSet:
    """Ordered set of jobs keyed by identity."""

    __slots__ = ("_jobs",)

    def __init__(self, jobs: Iterable[Job] = ()):
        self._jobs: "OrderedDict[int, Job]" = OrderedDict(
            (id(j), j) for j in jobs
        )

    def append(self, job: Job) -> None:
        # re-append moves nothing: OrderedDict keeps the first position
        # for an existing key (same contract the plain dict had)
        self._jobs[id(job)] = job

    def remove(self, job: Job) -> None:
        try:
            del self._jobs[id(job)]
        except KeyError:
            raise ValueError(f"{job!r} not in JobSet") from None

    def discard(self, job: Job) -> None:
        """Remove ``job`` if present (one dict op — the engine's start
        path replaces its contains-then-remove pair with this)."""
        self._jobs.pop(id(job), None)

    def __contains__(self, job: Job) -> bool:
        return id(job) in self._jobs

    def __iter__(self) -> Iterator[Job]:
        return iter(self._jobs.values())

    def __len__(self) -> int:
        return len(self._jobs)

    def __bool__(self) -> bool:
        return bool(self._jobs)

    def __getitem__(self, index: int) -> Job:
        """Positional access in insertion order.  Index 0 is O(1) — FIFO's
        head-of-line peek reads it once per start attempt; other indices
        are O(index) (tests and debugging only)."""
        if index == 0 and self._jobs:
            return next(iter(self._jobs.values()))
        n = len(self._jobs)
        if index < 0:
            index += n
        if not 0 <= index < n:
            raise IndexError(index)
        return next(islice(iter(self._jobs.values()), index, None))

    def __add__(self, other: Iterable[Job]) -> List[Job]:
        """``pending + running`` — the policies' idiom for the active set."""
        return [*self, *other]

    def __radd__(self, other: Iterable[Job]) -> List[Job]:
        return [*other, *self]

    def __reduce__(self):
        """Pickle as the ordered job list (engine snapshots, ISSUE 11):
        the backing store is keyed by ``id(job)``, which is meaningless in
        another process — reconstruction re-keys the same jobs (identity
        preserved by the enclosing pickle graph) in the same order."""
        return (JobSet, (list(self),))

    def __repr__(self) -> str:
        return f"JobSet({[j.job_id for j in self]})"
